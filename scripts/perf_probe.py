"""Depth-32 serving-tail probe: splits the client recv phase into
server-wait (submit -> stream response) and region readback (d2h), and
reports p50/p90/p99 per phase alongside throughput, so ratio misses are
attributable (VERDICT r3 weak #1/#6).

Run alone on the chip (memory: axon-tunnel-measurement-pitfalls).

Env: PROBE_DEPTH (default 32), PROBE_SECONDS per window (default 6),
PROBE_WINDOWS (default 3), BENCH_MODEL / BENCH_BATCH / BENCH_SEQ as bench.py.
"""

import json
import os
import sys
import time

import numpy as np

os.environ.setdefault("TPU_SERVER_DYNAMIC_BATCH", "0")
sys.setswitchinterval(0.0002)


def pct(sorted_vals, p):
    if not sorted_vals:
        return 0.0
    import math

    idx = min(len(sorted_vals) - 1, math.ceil(p / 100.0 * len(sorted_vals)) - 1)
    return sorted_vals[max(idx, 0)]


def main():
    depth = int(os.environ.get("PROBE_DEPTH", "32"))
    seconds = float(os.environ.get("PROBE_SECONDS", "6"))
    n_windows = int(os.environ.get("PROBE_WINDOWS", "3"))
    batch = int(os.environ.get("BENCH_BATCH", "8"))
    seq = int(os.environ.get("BENCH_SEQ", "128"))

    import jax

    from tritonclient_tpu.models.bert import BertBaseModel
    from tritonclient_tpu.perf_analyzer import PerfAnalyzer
    from tritonclient_tpu.perf_analyzer._analyzer import (
        MeasurementSession,
        _Worker,
    )
    from tritonclient_tpu.perf_analyzer._stats import RequestTimers
    from tritonclient_tpu.server import InferenceServer

    model = BertBaseModel()
    payloads = [
        np.random.randint(0, 30000, (batch, seq)).astype(np.int32)
        for _ in range(16)
    ]
    dispatch = lambda p: model._fwd(model._params, p)  # noqa: E731
    model.warmup()

    # Cross-boundary timing: client and server share this process, so one
    # monotonic clock covers submit -> server-entry -> server-exit -> resp.
    submit_ts = {}     # rid -> perf_counter at stream write
    leg = {"req": [], "srv": [], "resp": []}
    from tritonclient_tpu.server import _grpc as _sgrpc

    # The two-phase stream path splits parse (feeder) from response
    # finalization (yielder): req leg stamps at parse entry, srv leg
    # spans parse entry -> response built, which covers batcher queue +
    # dispatch + finalize for deferred requests and the whole handler
    # for pool/inline ones.
    _orig_parse = _sgrpc._Servicer._parse_cached
    _orig_respond = _sgrpc._Servicer._respond_stream
    entry_ts = {}
    exit_ts = {}

    def _timed_parse(self, request, cached_reqs):
        t_in = time.perf_counter()
        t_sub = submit_ts.get(request.id)
        if t_sub is not None:
            leg["req"].append(t_in - t_sub)
        entry_ts[request.id] = t_in
        return _orig_parse(self, request, cached_reqs)

    def _timed_respond(self, request, cresp, cached_resps):
        out = _orig_respond(self, request, cresp, cached_resps)
        t_out = time.perf_counter()
        t_in = entry_ts.get(request.id)
        if t_in is not None:
            leg["srv"].append(t_out - t_in)
        # Response leg measured client-side: mux reader stamps arrival.
        exit_ts[request.id] = t_out
        return out

    _sgrpc._Servicer._parse_cached = _timed_parse
    _sgrpc._Servicer._respond_stream = _timed_respond

    class ProbeWorker(_Worker):
        """_run_streaming with the recv phase split into wait vs readback."""

        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self.phase = {"send": [], "wait": [], "read": [], "gap": []}

        def _run_streaming(self, end_time):
            a = self.analyzer
            self._ensure_stream()
            done = self._done
            outputs = self._build_outputs()
            rid = f"w{self.wid}"
            prepared = self._client.prepare_request(
                a.model_name, self._static_inputs, outputs=outputs,
                request_id=rid,
            )
            i = 0
            t_prev_end = None
            while time.perf_counter() < end_time and not self._stop.is_set():
                payloads_ = self.payload_sets[i % len(self.payload_sets)]
                i += 1
                timers = RequestTimers()
                timers.capture("request_start")
                t0 = time.perf_counter()
                if t_prev_end is not None:
                    self.phase["gap"].append(t0 - t_prev_end)
                try:
                    timers.capture("send_start")
                    self._write_region(payloads_)
                    timers.capture("send_end")
                    t1 = time.perf_counter()

                    def _send():
                        submit_ts[rid] = time.perf_counter()
                        self._client.async_stream_infer(prepared_request=prepared)

                    if self.mux is not None:
                        self.mux.submit(rid, _send)
                    else:
                        _send()
                    timers.capture("recv_start")
                    result, error = done.get(timeout=120)
                    t2 = time.perf_counter()
                    t_exit = exit_ts.get(rid)
                    if t_exit is not None:
                        leg["resp"].append(t2 - t_exit)
                    if error is not None:
                        self.errors += 1
                        continue
                    if a.read_outputs:
                        self._consume_outputs(result)
                    timers.capture("recv_end")
                    t3 = time.perf_counter()
                except Exception:
                    self.errors += 1
                    continue
                timers.capture("request_end")
                t_prev_end = t3
                self.stat.update(timers)
                self.latencies.append(timers.total_ns)
                self.phase["send"].append(t1 - t0)
                self.phase["wait"].append(t2 - t1)
                self.phase["read"].append(t3 - t2)

    with InferenceServer(models=[model], http=False) as server:
        analyzer = PerfAnalyzer(
            server.grpc_address,
            model.name,
            protocol="grpc",
            batch_size=batch,
            shared_memory="tpu",
            streaming=True,
            read_outputs=True,
            measurement_interval_s=seconds,
            warmup_s=1.0,
            shape_overrides={"INPUT_IDS": seq},
        )
        session = MeasurementSession(analyzer, depth)
        session.workers = [
            ProbeWorker(
                analyzer, w,
                mux=session.muxes[w // analyzer.mux_shard] if session.muxes else None,
            )
            for w in range(depth)
        ]
        from statistics import median

        serve_ips, inproc_ips = [], []
        with session:
            session.measure(interval_s=2.0)  # discard
            for w in session.workers:
                w.phase = {"send": [], "wait": [], "read": [], "gap": []}
            from bench import _pipelined_inprocess  # reuse comparator

            for _ in range(n_windows):
                ips, _lat = _pipelined_inprocess(
                    dispatch, jax.device_get, payloads, seconds, depth
                )
                inproc_ips.append(ips)
                window = session.measure(interval_s=seconds)
                serve_ips.append(window.summary()["throughput_infer_per_sec"])

            phases = {}
            for key in ("send", "wait", "read", "gap"):
                vals = sorted(
                    v * 1000
                    for w in session.workers
                    for v in w.phase[key]
                )
                phases[key] = {
                    "p50": round(pct(vals, 50), 2),
                    "p90": round(pct(vals, 90), 2),
                    "p99": round(pct(vals, 99), 2),
                    "mean": round(sum(vals) / max(len(vals), 1), 2),
                    "n": len(vals),
                }
        stats = server.core.model_statistics(model.name)[0]["inference_stats"]
        n = max(stats["success"]["count"], 1)
        server_us = {
            k: int(stats[k]["ns"] / n / 1000)
            for k in ("queue", "compute_input", "compute_infer", "compute_output")
        }
        print(json.dumps({
            "depth": depth,
            "serving_ips": [round(x, 1) for x in serve_ips],
            "inprocess_ips": [round(x, 1) for x in inproc_ips],
            "ratio_median": round(
                median(s / i for s, i in zip(serve_ips, inproc_ips)), 4
            ),
            "client_phases_ms": phases,
            "legs_ms": {
                k: {
                    "p50": round(pct(sorted(v), 50) * 1000, 2),
                    "p90": round(pct(sorted(v), 90) * 1000, 2),
                    "p99": round(pct(sorted(v), 99) * 1000, 2),
                    "n": len(v),
                }
                for k, v in leg.items()
            },
            "server_mean_us": server_us,
        }, indent=1))


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    main()

#!/usr/bin/env bash
# Single static-analysis entry point shared by CI and tier-1.
#
#   scripts/run_static_checks.sh [--write-baseline] [--sanitize] [--modelcheck] [--fuzz] [--changed] [paths...]
#
# --changed is the pre-commit fast path: tpulint lints only git-touched
# files against the cached whole-program call graph (<2 s warm), and the
# other checks are skipped.
#
# --sanitize closes the static/dynamic loop: after the static checks it
# runs the tpusan-instrumented tier-1 subset (TPUSAN=1, the runtime
# sanitizer witnessing TPU001/TPU006/TPU007/TPU009 plus the JAX
# compute-plane witnesses for TPU015/TPU016/TPU017 — donation poisoner,
# transfer guard, compile-cache watcher; see the README "Runtime
# sanitizers" subsection), writes the runtime report, and diffs it
# against the static picture with scripts/tpusan_report.py.
#
# --modelcheck runs tpumc (scripts/tpumc.py): the four scheduling-core
# harness models explored under the bounded-preemption schedule
# enumerator, each capped at 60 s wall clock. Deterministic (seeded DFS)
# — any finding prints a replay trace and fails the check.
#
# --fuzz runs tpufuzz (scripts/tpufuzz.py): the seeded protocol fuzzer
# drives 500 mutated KServe v2 requests per plane (committed corpus,
# fixed seed) at a live in-process server under TPUSAN=1, asserting
# no-500/no-hang/no-leak, then re-runs and byte-compares the two
# reports — any nondeterminism or contract violation fails the check.
#
# Chains, in order:
#   1. tpulint        — project-specific checks (TPU001..TPU017, incl. the
#                       interprocedural TPU009 guarded-by race detection,
#                       TPU010 JAX hot-path hazards, TPU013 untrusted-sink
#                       taint, and the tpushape compute-plane rules
#                       TPU015 donation / TPU016 sharding-drift /
#                       TPU017 bucket discipline); see
#                       `python scripts/tpulint.py --list-rules`. Runs over
#                       tritonclient_tpu/ + scripts/ + tests/ against the
#                       committed baseline (scripts/tpulint_baseline.json):
#                       pre-existing findings there stay recorded, only NEW
#                       findings fail. `--write-baseline` regenerates it
#                       after deliberate changes.
#   2. ruff           — generic Python lint, config in pyproject.toml
#                       (skipped with a notice when ruff is not installed)
#   3. mypy           — type check, config in pyproject.toml
#                       (skipped with a notice when mypy is not installed)
#   4. metrics check  — boots an in-process InferenceCore, renders
#                       /metrics exposition text, and validates it with
#                       scripts/check_metrics_exposition.py
#
# Exits non-zero if any check that actually ran reported findings.
# Optional tools being absent is NOT a failure: the container this repo
# targets bakes in a fixed toolchain, so the script degrades instead of
# demanding installs.

set -u -o pipefail

REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "${REPO_ROOT}"

PYTHON="${PYTHON:-python}"
BASELINE_FILE="scripts/tpulint_baseline.json"

WRITE_BASELINE=0
SANITIZE=0
MODELCHECK=0
FUZZ=0
CHANGED=0
while :; do
    case "${1:-}" in
        --write-baseline) WRITE_BASELINE=1; shift ;;
        --sanitize) SANITIZE=1; shift ;;
        --modelcheck) MODELCHECK=1; shift ;;
        --fuzz) FUZZ=1; shift ;;
        --changed) CHANGED=1; shift ;;
        *) break ;;
    esac
done

PATHS=("$@")
if [ "${#PATHS[@]}" -eq 0 ]; then
    # tpulint covers the support code too; ruff/mypy stay scoped to the
    # package (their pyproject configs are tuned for it).
    TPULINT_PATHS=(tritonclient_tpu scripts tests)
    TOOL_PATHS=(tritonclient_tpu)
else
    TPULINT_PATHS=("${PATHS[@]}")
    TOOL_PATHS=("${PATHS[@]}")
fi

if [ "${WRITE_BASELINE}" -eq 1 ]; then
    exec "${PYTHON}" scripts/tpulint.py --write-baseline "${BASELINE_FILE}" \
        "${TPULINT_PATHS[@]}"
fi

failures=0

run_check() {
    local name="$1"
    shift
    echo "==> ${name}"
    if "$@"; then
        echo "    ${name}: OK"
    else
        echo "    ${name}: FAILED (exit $?)"
        failures=$((failures + 1))
    fi
}

# 1. tpulint — always available (lives in this repo, stdlib-only).
TPULINT_ARGS=()
if [ -f "${BASELINE_FILE}" ]; then
    TPULINT_ARGS+=(--baseline "${BASELINE_FILE}")
fi
if [ "${CHANGED}" -eq 1 ]; then
    # Pre-commit fast path: changed files only, cached call graph, and
    # nothing else — the full chain runs in CI.
    exec "${PYTHON}" scripts/tpulint.py --changed \
        "${TPULINT_ARGS[@]+"${TPULINT_ARGS[@]}"}" "${TPULINT_PATHS[@]}"
fi
run_check "tpulint" "${PYTHON}" scripts/tpulint.py \
    "${TPULINT_ARGS[@]+"${TPULINT_ARGS[@]}"}" "${TPULINT_PATHS[@]}"

# 1b. Baseline may only shrink: new findings must be fixed, not recorded.
run_check "tpulint-baseline-shrink" "${PYTHON}" \
    scripts/check_baseline_shrink.py

# 2. ruff — optional.
if "${PYTHON}" -m ruff --version >/dev/null 2>&1; then
    run_check "ruff" "${PYTHON}" -m ruff check "${TOOL_PATHS[@]}"
elif command -v ruff >/dev/null 2>&1; then
    run_check "ruff" ruff check "${TOOL_PATHS[@]}"
else
    echo "==> ruff: not installed, skipping"
fi

# 3. mypy — optional.
if "${PYTHON}" -m mypy --version >/dev/null 2>&1; then
    run_check "mypy" "${PYTHON}" -m mypy "${TOOL_PATHS[@]}"
else
    echo "==> mypy: not installed, skipping"
fi

# 4. Metrics exposition conformance, offline: render the Prometheus text
#    from a fresh in-process core (no sockets) and validate its grammar.
run_check "metrics-exposition" bash -c "
    '${PYTHON}' -c '
from tritonclient_tpu.server import default_models
from tritonclient_tpu.server._core import InferenceCore

print(InferenceCore(default_models()).prometheus_metrics())
' | '${PYTHON}' scripts/check_metrics_exposition.py
"

# 5. tpusan (opt-in): tier-1 subset under the runtime sanitizer, then the
#    static-vs-dynamic diff. Zero findings is the gate — the conftest
#    plugin fails the pytest session itself on any surviving finding.
if [ "${SANITIZE}" -eq 1 ]; then
    TPUSAN_OUT="${TPUSAN_REPORT:-/tmp/tpusan_report.json}"
    run_check "tpusan-tier1" env JAX_PLATFORMS=cpu TPUSAN=1 \
        TPUSAN_REPORT="${TPUSAN_OUT}" \
        "${PYTHON}" -m pytest -q -m 'not slow' -p no:cacheprovider \
        tests/test_tpusan.py tests/test_fleet.py tests/test_chaos.py tests/test_deadlines.py tests/test_shared_memory.py \
        tests/test_server.py tests/test_grpc_client.py \
        tests/test_http_client.py tests/test_aio_clients.py \
        tests/test_aio_stress.py tests/test_batcher_stress.py \
        tests/test_gpt_engine.py
    run_check "tpusan-report" "${PYTHON}" scripts/tpusan_report.py \
        --dynamic "${TPUSAN_OUT}" --fail-on-witnessed
fi

# 6. tpumc (opt-in): schedule-space model checking of the four
#    scheduling cores. Seeded + bounded, so the run is deterministic;
#    each harness gets at most 60 s of wall clock. Findings embed replay
#    traces (re-run with `scripts/tpumc.py --replay <trace.json>`).
if [ "${MODELCHECK}" -eq 1 ]; then
    TPUMC_OUT="${TPUMC_REPORT:-/tmp/tpumc_report.json}"
    run_check "tpumc" env JAX_PLATFORMS=cpu "${PYTHON}" scripts/tpumc.py \
        --seed 0 --deadline-s 60 --json "${TPUMC_OUT}"
fi

# 7. tpufuzz (opt-in): seeded deterministic protocol fuzzing of both
#    planes under the runtime sanitizer, twice, with a byte-diff of the
#    two reports. The fixed seed + committed corpus make the stream
#    reproducible: any failure prints the case id, which replays with
#    the same scripts/tpufuzz.py invocation.
if [ "${FUZZ}" -eq 1 ]; then
    FUZZ_SEED="${TPUFUZZ_SEED:-20260807}"
    FUZZ_N="${TPUFUZZ_REQUESTS:-500}"
    FUZZ_OUT="${TPUFUZZ_REPORT:-/tmp/tpufuzz_report.json}"
    run_check "tpufuzz-self-check" env JAX_PLATFORMS=cpu \
        "${PYTHON}" scripts/tpufuzz.py --self-check
    run_check "tpufuzz" env JAX_PLATFORMS=cpu TPUSAN=1 \
        "${PYTHON}" scripts/tpufuzz.py --seed "${FUZZ_SEED}" \
        --requests "${FUZZ_N}" --json "${FUZZ_OUT}" \
        --sarif "${FUZZ_OUT%.json}.sarif"
    run_check "tpufuzz-determinism" bash -c "
        env JAX_PLATFORMS=cpu TPUSAN=1 '${PYTHON}' scripts/tpufuzz.py \
            --seed '${FUZZ_SEED}' --requests '${FUZZ_N}' \
            --json '${FUZZ_OUT}.second' >/dev/null \
        && cmp '${FUZZ_OUT}' '${FUZZ_OUT}.second'
    "
fi

if [ "${failures}" -ne 0 ]; then
    echo "static checks: ${failures} check(s) failed"
    exit 1
fi
echo "static checks: all passed"

#!/usr/bin/env python
"""tpumc launcher: explore, or byte-identically replay, harness models.

Exploration mode runs the named harnesses (default: the four scheduling
cores — ``batcher``, ``gpt_engine``, ``kvcache``, ``fleet_admission``)
under the bounded-preemption explorer and prints one summary line per
harness; any finding prints with its replay trace and fails the run.
Demo harnesses (``demo_lost_wakeup``, ``demo_deadlock``) carry seeded
bugs and are excluded from the default set — run them by name to watch
the checker work.

Replay mode (``--replay trace.json``) re-executes one recorded schedule
— the ``trace`` object embedded in every finding — and prints the
findings it reproduces. Replaying a finding's trace reproduces that
finding's record byte-for-byte; that is the debugging contract.

Usage:
    python scripts/tpumc.py                       # the four cores
    python scripts/tpumc.py demo_lost_wakeup      # watch a seeded bug
    python scripts/tpumc.py --list
    python scripts/tpumc.py --sarif tpumc.sarif --json tpumc.json
    python scripts/tpumc.py --replay trace.json

Exit status: 1 if any explored harness produced findings (or a replay
reproduced none), else 0. A harness whose subsystem is unavailable in
this interpreter (e.g. ``gpt_engine`` without jax) is skipped with a
notice, not failed — the container CI targets has the full toolchain.
"""

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from tritonclient_tpu import mc  # noqa: E402


def _print_findings(findings):
    for rec in findings:
        print(f"  {rec['path']}:{rec['line']}: {rec['rule']} "
              f"{rec['message']}")
        print(f"    replay: {json.dumps(rec['trace'], sort_keys=True)}")


def _explore(args) -> int:
    names = args.harness or list(mc.DEFAULT_HARNESSES)
    unknown = [n for n in names if n not in mc.HARNESSES]
    if unknown:
        print(f"tpumc: unknown harness(es): {', '.join(unknown)} "
              f"(--list shows all)", file=sys.stderr)
        return 2
    results = []
    failed = 0
    for name in names:
        budget = args.max_schedules or mc.SCHEDULE_BUDGETS.get(name, 1000)
        try:
            result = mc.run_harness(
                name,
                preemption_budget=args.preemption_budget,
                max_schedules=budget,
                deadline_s=args.deadline_s,
                seed=args.seed,
                prune=args.prune,
            )
        except mc.HarnessUnavailable as e:
            print(f"tpumc: {name}: SKIPPED ({e})")
            continue
        results.append(result)
        status = "complete" if result.complete else "capped"
        print(f"tpumc: {name}: {result.schedules} schedules ({status}), "
              f"{len(result.findings)} finding(s), "
              f"{result.elapsed_s:.1f}s, "
              f"pruned {result.pruned_independent} independent / "
              f"{result.pruned_budget} over-budget branches")
        if result.findings:
            failed += 1
            _print_findings(result.findings)
    if args.json_out:
        with open(args.json_out, "w", encoding="utf-8") as f:
            json.dump([r.as_dict() for r in results], f, indent=2,
                      sort_keys=True)
            f.write("\n")
    if args.sarif_out:
        merged = mc.ExploreResult("all", args.seed, args.preemption_budget)
        for r in results:
            for rec in r.findings:
                merged.add_finding(rec)
        with open(args.sarif_out, "w", encoding="utf-8") as f:
            f.write(merged.sarif())
    if failed:
        print(f"tpumc: {failed} harness(es) with findings")
        return 1
    return 0


def _replay(args) -> int:
    with open(args.replay, encoding="utf-8") as f:
        doc = json.load(f)
    # Accept a bare trace, a finding record, or a findings list.
    if isinstance(doc, list):
        doc = doc[0]
    trace = doc.get("trace", doc)
    name = trace["harness"]
    if name not in mc.HARNESSES:
        print(f"tpumc: trace names unknown harness {name!r}",
              file=sys.stderr)
        return 2
    explorer = mc.Explorer(
        mc.HARNESSES[name], name=name,
        preemption_budget=trace.get("preemption_budget", 2),
        seed=trace.get("seed", 0),
    )
    result = explorer.replay(trace)
    print(f"tpumc: replayed {name} schedule "
          f"({len(trace['decisions'])} decisions): "
          f"{len(result.findings)} finding(s)")
    _print_findings(result.findings)
    return 0 if result.findings else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("harness", nargs="*",
                        help="harness names (default: the four cores)")
    parser.add_argument("--list", action="store_true",
                        help="list available harnesses and exit")
    parser.add_argument("--replay", metavar="TRACE",
                        help="replay a recorded trace (JSON file: a "
                        "trace object or a finding embedding one)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--preemption-budget", type=int, default=2)
    parser.add_argument("--max-schedules", type=int, default=0,
                        help="override the per-harness schedule budget")
    parser.add_argument("--deadline-s", type=float, default=60.0,
                        help="wall-clock cap per harness (default 60)")
    parser.add_argument("--prune", choices=("dpor", "naive"),
                        default="dpor",
                        help="'naive' disables DPOR pruning (PERF A/B)")
    parser.add_argument("--json", dest="json_out", metavar="FILE",
                        help="write per-harness results as JSON")
    parser.add_argument("--sarif", dest="sarif_out", metavar="FILE",
                        help="write merged findings as SARIF 2.1.0")
    args = parser.parse_args(argv)
    if args.list:
        for name in sorted(mc.HARNESSES):
            tag = "" if name in mc.DEFAULT_HARNESSES else "  (demo)"
            print(f"{name}{tag}")
        return 0
    if args.replay:
        return _replay(args)
    return _explore(args)


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Fleet SLO report: burn rates, per-replica divergence, and cohort
verdicts from a fleetscope dump.

The router retains per-replica time series, exact merged DDSketches,
SLO burn windows, and cohort comparisons (``GET
v2/fleet/debug/fleetscope``). This report renders that document into
the operator's questions:

* **per-replica divergence** — which replica's counter rates strayed
  furthest from the fleet mean (plus scrape health: age, failures,
  counter resets);
* **fleet quantiles** — per-model/per-stage p50/p99/p999 from the
  exact bucket-wise sketch merges;
* **SLO burn** — per-objective fast/slow burn rates and remaining
  error budget;
* **cohort verdicts** — baseline-vs-cohort comparison outcomes
  (``regressed`` / ``clean`` / ``insufficient-data``) with the p99 and
  error-rate evidence;
* optionally, a merged fleet flight dump (``GET
  v2/fleet/debug/flight_recorder``) for per-replica record
  attribution (deeper stage analysis belongs to ``tail_report.py``).

Usage::

    python scripts/fleet_report.py FLEETSCOPE_DUMP [--flight DUMP]
        [--json]
    python scripts/fleet_report.py --self-check

``--self-check`` drives a real :class:`FleetScope` on a fake clock
through a scripted scenario (one divergent replica, one regressed
canary cohort, one burning objective), dumps it, and exits non-zero
unless the report recovers every seeded answer — deterministic, no
sockets, no RNG.
"""

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


# --------------------------------------------------------------------------- #
# loading                                                                     #
# --------------------------------------------------------------------------- #


def load_dump(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("kind") != "fleetscope":
        raise ValueError(
            f"{path}: not a fleetscope dump "
            f"(kind={doc.get('kind') if isinstance(doc, dict) else '?'})"
        )
    return doc


def load_flight(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("kind") != (
        "fleet_flight_recorder"
    ):
        raise ValueError(f"{path}: not a merged fleet flight dump")
    return doc


# --------------------------------------------------------------------------- #
# analysis                                                                    #
# --------------------------------------------------------------------------- #


def _mean_rates(samples: List[dict]) -> Dict[str, float]:
    """Mean per-second rate per counter series over one replica's ring."""
    sums: Dict[str, float] = {}
    counts: Dict[str, int] = {}
    for sample in samples:
        for series, rate in (sample.get("rates") or {}).items():
            sums[series] = sums.get(series, 0.0) + float(rate)
            counts[series] = counts.get(series, 0) + 1
    return {s: sums[s] / counts[s] for s in sums}


def _divergence(per_replica: Dict[str, Dict[str, float]]) -> Dict[str, dict]:
    """Max relative divergence of each replica's mean rates from the
    fleet mean, over series observed on at least two replicas (a series
    only one replica exports is a difference in workload, not a
    divergence within it)."""
    series_values: Dict[str, List[float]] = {}
    for rates in per_replica.values():
        for series, value in rates.items():
            series_values.setdefault(series, []).append(value)
    fleet_mean = {
        s: sum(vs) / len(vs)
        for s, vs in series_values.items()
        if len(vs) >= 2 and sum(vs) > 0
    }
    out: Dict[str, dict] = {}
    for replica, rates in per_replica.items():
        worst, worst_series = 0.0, None
        for series, mean in fleet_mean.items():
            if series not in rates:
                continue
            rel = abs(rates[series] - mean) / mean
            if rel > worst:
                worst, worst_series = rel, series
        out[replica] = {
            "divergence": round(worst, 4),
            "series": worst_series,
        }
    return out


def analyze(doc: dict, flight: Optional[dict] = None) -> dict:
    """The report document: per-replica health + divergence rows, the
    merged-sketch quantile rows, per-objective burn rows (fast and slow
    folded into one row), and the cohort verdicts."""
    health = doc.get("scrape_health") or {}
    timeseries = doc.get("timeseries") or {}
    mean_rates = {
        replica: _mean_rates(samples)
        for replica, samples in timeseries.items()
    }
    divergence = _divergence(mean_rates)
    replicas = []
    for replica in sorted(set(health) | set(timeseries)):
        h = health.get(replica) or {}
        d = divergence.get(replica) or {}
        replicas.append({
            "replica": replica,
            "samples": h.get("samples_retained", len(
                timeseries.get(replica) or ()
            )),
            "scrape_age_s": h.get("scrape_age_s"),
            "scrape_failures": h.get("scrape_failures", 0),
            "counter_resets": h.get("counter_resets", 0),
            "divergence": d.get("divergence", 0.0),
            "divergent_series": d.get("series"),
        })

    sketches = [
        {
            "model": row.get("model", "?"),
            "stage": row.get("stage", "?"),
            "count": row.get("count", 0),
            "p50_us": round((row.get("quantiles") or {}).get("0.5", 0.0), 1),
            "p99_us": round((row.get("quantiles") or {}).get("0.99", 0.0), 1),
            "p999_us": round(
                (row.get("quantiles") or {}).get("0.999", 0.0), 1
            ),
        }
        for row in doc.get("merged_sketches") or []
    ]

    # Fold the per-window burn rows into one row per objective: the
    # fast/slow pair is how multi-window alerting reads them.
    slo = doc.get("slo") or {}
    by_objective: Dict[tuple, dict] = {}
    for row in slo.get("burn") or []:
        key = (row.get("model", ""), row.get("tenant", ""))
        entry = by_objective.setdefault(key, {
            "model": key[0], "tenant": key[1],
            "fast_burn": 0.0, "slow_burn": 0.0,
            "budget_remaining": 1.0, "total": 0, "bad": 0,
        })
        if row.get("window") == "fast":
            entry["fast_burn"] = round(float(row.get("burn_rate", 0.0)), 3)
        else:
            entry["slow_burn"] = round(float(row.get("burn_rate", 0.0)), 3)
            entry["budget_remaining"] = round(
                float(row.get("budget_remaining", 1.0)), 4
            )
            entry["total"] = int(row.get("total", 0))
            entry["bad"] = int(row.get("bad", 0))
    burn = [by_objective[k] for k in sorted(by_objective)]

    cohorts = doc.get("cohorts") or {}
    verdicts = [
        {
            "cohort": v.get("cohort", "?"),
            "verdict": v.get("verdict", "?"),
            "reason": v.get("reason", ""),
            "replicas": v.get("replicas") or [],
            "windows": (
                f"{v.get('windows_regressed', 0)}"
                f"/{v.get('windows_compared', 0)}"
            ),
            "p99_us": round(float(v.get("p99_us", 0.0)), 1),
            "baseline_p99_us": round(
                float(v.get("baseline_p99_us", 0.0)), 1
            ),
            "error_rate": round(float(v.get("error_rate", 0.0)), 4),
            "baseline_error_rate": round(
                float(v.get("baseline_error_rate", 0.0)), 4
            ),
            "samples": v.get("samples", 0),
        }
        for v in cohorts.get("verdicts") or []
    ]

    # Device-memory headroom merge: per-replica rows plus the fleet
    # minimum per model (the placement-relevant number).
    memory = (doc.get("memory") or {}).get("headroom") or {}
    headroom = {
        "replicas": [
            {
                "replica": row.get("replica", "?"),
                "model": row.get("model", "?"),
                "headroom_bytes": int(row.get("headroom_bytes", 0)),
            }
            for row in memory.get("replicas") or []
        ],
        "fleet_min": {
            model: int(value)
            for model, value in (memory.get("fleet_min") or {}).items()
        },
    }

    result = {
        "config": doc.get("config") or {},
        "replicas": replicas,
        "sketches": sketches,
        "headroom": headroom,
        "objectives": slo.get("objectives") or [],
        "burn": burn,
        "assignments": cohorts.get("assignments") or {},
        "cohort_requests": cohorts.get("requests") or {},
        "verdicts": verdicts,
    }
    if flight is not None:
        counts: Dict[str, int] = {}
        for rec in flight.get("records") or []:
            replica = str(rec.get("replica", "?"))
            counts[replica] = counts.get(replica, 0) + 1
        result["flight"] = {
            "replicas": flight.get("replicas") or [],
            "unreachable": flight.get("unreachable") or {},
            "records": counts,
            "counters": flight.get("counters") or {},
        }
    return result


# --------------------------------------------------------------------------- #
# rendering                                                                   #
# --------------------------------------------------------------------------- #


def render(result: dict) -> str:
    config = result.get("config") or {}
    lines = [
        f"fleetscope: bucket {config.get('bucket_s', '?')}s x "
        f"{config.get('windows', '?')} windows, stale after "
        f"{config.get('stale_after_s', '?')}s"
    ]
    lines.append("")
    lines.append(
        f"{'replica':<16} {'samples':>7} {'age_s':>7} {'fail':>5} "
        f"{'resets':>6} {'diverge':>8}  divergent series"
    )
    for row in result["replicas"]:
        age = row["scrape_age_s"]
        age_txt = f"{age:.1f}" if age is not None else "never"
        lines.append(
            f"{row['replica']:<16} {row['samples']:>7} {age_txt:>7} "
            f"{row['scrape_failures']:>5} {row['counter_resets']:>6} "
            f"{row['divergence']:>8.1%}  {row['divergent_series'] or '-'}"
        )
    if result["sketches"]:
        lines.append("")
        lines.append(
            f"{'model':<20} {'stage':<14} {'count':>7} {'p50_us':>9} "
            f"{'p99_us':>9} {'p999_us':>9}"
        )
        for row in result["sketches"]:
            lines.append(
                f"{row['model']:<20} {row['stage']:<14} "
                f"{row['count']:>7} {row['p50_us']:>9} {row['p99_us']:>9} "
                f"{row['p999_us']:>9}"
            )
    headroom = result.get("headroom") or {}
    if headroom.get("replicas"):
        lines.append("")
        lines.append(
            f"{'model':<20} {'replica':<16} {'headroom_bytes':>15}"
        )
        for row in sorted(headroom["replicas"],
                          key=lambda r: (r["model"], r["replica"])):
            lines.append(
                f"{row['model']:<20} {row['replica']:<16} "
                f"{row['headroom_bytes']:>15}"
            )
        for model, value in sorted(headroom["fleet_min"].items()):
            lines.append(f"{model:<20} {'fleet-min':<16} {value:>15}")
    lines.append("")
    if result["burn"]:
        lines.append(
            f"{'objective':<28} {'fast_burn':>9} {'slow_burn':>9} "
            f"{'budget':>7} {'bad/total':>12}"
        )
        for row in result["burn"]:
            name = row["model"] + (
                f"/{row['tenant']}" if row["tenant"] else ""
            )
            if len(name) > 27:
                name = name[:24] + "..."
            lines.append(
                f"{name:<28} {row['fast_burn']:>9} {row['slow_burn']:>9} "
                f"{row['budget_remaining']:>7.1%} "
                f"{row['bad']:>5}/{row['total']}"
            )
    else:
        lines.append("no SLO objectives declared")
    lines.append("")
    if result["verdicts"]:
        lines.append(
            f"{'cohort':<16} {'verdict':<18} {'win':>5} {'p99_us':>9} "
            f"{'base_p99':>9} {'err':>7} {'base_err':>8}  reason"
        )
        for row in result["verdicts"]:
            lines.append(
                f"{row['cohort']:<16} {row['verdict']:<18} "
                f"{row['windows']:>5} {row['p99_us']:>9} "
                f"{row['baseline_p99_us']:>9} {row['error_rate']:>7.1%} "
                f"{row['baseline_error_rate']:>8.1%}  {row['reason']}"
            )
    else:
        lines.append("no non-baseline cohorts")
    if result.get("cohort_requests"):
        lines.append(
            "requests by cohort: " + ", ".join(
                f"{cohort}={count}" for cohort, count in sorted(
                    result["cohort_requests"].items()
                )
            )
        )
    flight = result.get("flight")
    if flight is not None:
        lines.append("")
        recs = ", ".join(
            f"{replica}={count}"
            for replica, count in sorted(flight["records"].items())
        )
        lines.append(
            f"merged flight dump: {sum(flight['records'].values())} "
            f"records ({recs or 'none'})"
        )
        for replica, error in sorted(flight["unreachable"].items()):
            lines.append(f"  unreachable: {replica}: {error}")
    return "\n".join(lines)


# --------------------------------------------------------------------------- #
# self-check                                                                  #
# --------------------------------------------------------------------------- #


def _exposition(requests: int, queue_depth: float,
                headroom: int = 0) -> str:
    """Minimal replica exposition the scrape plane retains."""
    return (
        "# TYPE nv_inference_request_success counter\n"
        f'nv_inference_request_success{{model="m",version="1"}} '
        f"{requests}\n"
        "# TYPE nv_engine_queue_depth gauge\n"
        f'nv_engine_queue_depth{{model="m"}} {queue_depth}\n'
        "# TYPE nv_device_memory_headroom_bytes gauge\n"
        f'nv_device_memory_headroom_bytes{{model="m"}} {headroom}\n'
    )


def self_check() -> int:
    from tritonclient_tpu._sketch import LatencySketch
    from tritonclient_tpu.fleet._fleetscope import FleetScope
    from tritonclient_tpu.fleet._slo import CohortDetector

    failures = 0
    clock = [1000.0]
    scope = FleetScope(
        clock=lambda: clock[0], bucket_s=1.0, windows=120,
        stale_after_s=30.0,
        cohorts=CohortDetector(min_samples=5, confirm_windows=3),
    )
    scope.set_objective({
        "model": "m", "latency_target_us": 10_000, "error_budget": 0.1,
    })
    scope.assign_cohort("r2", "canary")

    # 6 scrape ticks: r2's request counter advances 3x faster than the
    # baseline pair — the seeded divergence answer.
    sketch = LatencySketch()
    for value in (5_000, 6_000, 7_000):
        sketch.insert(value)
    sketches_doc = {
        "kind": "sketches",
        "models": {"m": {"request": sketch.to_dict()}},
    }
    for tick in range(6):
        for replica, slope, headroom in (("r0", 10, 800), ("r1", 10, 500),
                                         ("r2", 30, 300)):
            scope.observe_scrape(
                replica, ok=True,
                metrics_text=_exposition(tick * slope, 2.0,
                                         headroom=headroom),
                sketches_doc=sketches_doc,
            )
        clock[0] += 1.0

    # 4 buckets of routed requests: canary (r2) at 25 ms, baseline at
    # 5 ms, vs the 10 ms objective — r2's requests all burn budget and
    # its cohort regresses for 3+ consecutive windows.
    for _bucket in range(4):
        for _ in range(8):
            scope.record_request("m", "", 5_000, True, "r0")
            scope.record_request("m", "", 5_000, True, "r1")
            scope.record_request("m", "", 25_000, True, "r2")
        clock[0] += 1.0

    result = analyze(scope.dump(["r0", "r1", "r2"]))

    worst = max(result["replicas"], key=lambda r: r["divergence"])
    if worst["replica"] != "r2" or worst["divergence"] < 0.5:
        print(
            f"self-check: divergence picked {worst['replica']} "
            f"({worst['divergence']}), expected r2",
            file=sys.stderr,
        )
        failures += 1
    sketch_rows = {
        (r["model"], r["stage"]): r for r in result["sketches"]
    }
    merged = sketch_rows.get(("m", "request"))
    if merged is None or merged["count"] != 9:
        print(f"self-check: merged sketch rows {sketch_rows} missing "
              "('m', 'request') with count 9 (3 obs x 3 replicas)",
              file=sys.stderr)
        failures += 1
    burn = {(row["model"], row["tenant"]): row for row in result["burn"]}
    row = burn.get(("m", ""))
    # 1/3 of requests are bad vs a 0.1 budget: slow burn 10/3.
    if row is None or not 3.0 < row["slow_burn"] < 3.7:
        print(f"self-check: burn row {row} (expected slow_burn ~3.33)",
              file=sys.stderr)
        failures += 1
    if row is not None and not 0.0 <= row["budget_remaining"] <= 1.0:
        print(f"self-check: budget_remaining {row['budget_remaining']} "
              "outside [0, 1]", file=sys.stderr)
        failures += 1
    verdicts = {v["cohort"]: v for v in result["verdicts"]}
    canary = verdicts.get("canary")
    if canary is None or canary["verdict"] != "regressed":
        print(f"self-check: canary verdict {canary} != regressed",
              file=sys.stderr)
        failures += 1
    # Headroom merge: per-replica rows survive, fleet minimum is the
    # tightest replica's gauge (r2 at 300).
    headroom_rows = {
        (r["model"], r["replica"]): r["headroom_bytes"]
        for r in result["headroom"]["replicas"]
    }
    expected = {("m", "r0"): 800, ("m", "r1"): 500, ("m", "r2"): 300}
    if headroom_rows != expected:
        print(f"self-check [headroom]: rows {headroom_rows} != "
              f"{expected}", file=sys.stderr)
        failures += 1
    if result["headroom"]["fleet_min"] != {"m": 300}:
        print(f"self-check [headroom]: fleet_min "
              f"{result['headroom']['fleet_min']} != {{'m': 300}}",
              file=sys.stderr)
        failures += 1
    text = render(result)
    for needle in ("canary", "regressed", "r2", "fast_burn",
                   "headroom_bytes", "fleet-min"):
        if needle not in text:
            print(f"self-check: render missing {needle!r}",
                  file=sys.stderr)
            failures += 1

    # A stale replica must flip its cohort to insufficient-data: jump
    # the clock past stale_after_s without new scrapes.
    clock[0] += 60.0
    stale_result = analyze(scope.dump(["r0", "r1", "r2"]))
    canary = {
        v["cohort"]: v for v in stale_result["verdicts"]
    }.get("canary")
    if canary is None or canary["verdict"] != "insufficient-data":
        print(f"self-check [stale]: canary verdict {canary} != "
              "insufficient-data", file=sys.stderr)
        failures += 1

    # Flight attribution: counts per replica stamp survive the render.
    flight = {
        "kind": "fleet_flight_recorder",
        "replicas": ["r0", "r2"],
        "unreachable": {"r1": "HTTP 503"},
        "counters": {"offered": 3},
        "records": [
            {"replica": "r0", "duration_us": 1},
            {"replica": "r2", "duration_us": 2},
            {"replica": "router", "duration_us": 3},
        ],
    }
    f_result = analyze(scope.dump(["r0", "r1", "r2"]), flight=flight)
    if f_result["flight"]["records"] != {"r0": 1, "r2": 1, "router": 1}:
        print(f"self-check [flight]: {f_result['flight']['records']}",
              file=sys.stderr)
        failures += 1
    elif "unreachable: r1" not in render(f_result):
        print("self-check [flight]: unreachable line missing",
              file=sys.stderr)
        failures += 1

    if failures:
        print(f"self-check: {failures} failure(s)", file=sys.stderr)
        return 1
    print("self-check: report recovers the divergent replica, the "
          "burning objective, and the cohort verdicts")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="fleet_report",
        description="Fleet SLO report from a fleetscope dump",
    )
    parser.add_argument("dump_file", nargs="?",
                        help="fleetscope dump "
                        "(GET v2/fleet/debug/fleetscope)")
    parser.add_argument("--flight", metavar="FILE",
                        help="merged fleet flight dump "
                        "(GET v2/fleet/debug/flight_recorder)")
    parser.add_argument("--json", dest="as_json", action="store_true",
                        help="emit the report as JSON")
    parser.add_argument("--self-check", action="store_true",
                        help="run the scripted-scenario round trip and "
                        "exit")
    args = parser.parse_args(argv)
    if args.self_check:
        return self_check()
    if not args.dump_file:
        parser.error("a fleetscope dump is required (or --self-check)")
    try:
        doc = load_dump(args.dump_file)
        flight = load_flight(args.flight) if args.flight else None
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"unable to load: {e}", file=sys.stderr)
        return 1
    result = analyze(doc, flight=flight)
    try:
        if args.as_json:
            print(json.dumps(result, indent=2, default=str))
        else:
            print(render(result))
    except BrokenPipeError:
        return 0
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Interleaved A/B of serving-stack configurations at one depth.

Each config gets its own InferenceServer (sharing ONE model instance, so
HBM and compile cost are paid once); windows run round-robin
config1..configN + an in-process comparator window per round, so tunnel
drift hits every variant equally (memory: axon-tunnel-measurement-pitfalls).

Env: AB_DEPTH (32), AB_SECONDS per window (5), AB_ROUNDS (3),
AB_CONFIGS comma list of pool sizes e.g. "32,4,1,0" (0 = inline feeder).
"""

import os
import sys
import time

import numpy as np

os.environ.setdefault("TPU_SERVER_DYNAMIC_BATCH", "0")
sys.setswitchinterval(0.0002)
sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    depth = int(os.environ.get("AB_DEPTH", "32"))
    seconds = float(os.environ.get("AB_SECONDS", "5"))
    rounds = int(os.environ.get("AB_ROUNDS", "3"))
    # Config grammar: "<aio|sync>-<workers|window>[-poolN]"
    configs = os.environ.get(
        "AB_CONFIGS", "sync-workers,aio-workers,sync-window,aio-window"
    ).split(",")
    batch = int(os.environ.get("BENCH_BATCH", "8"))
    seq = int(os.environ.get("BENCH_SEQ", "128"))

    import jax

    from tritonclient_tpu.models.bert import BertBaseModel
    from tritonclient_tpu.perf_analyzer import PerfAnalyzer
    from tritonclient_tpu.server import InferenceServer

    model = BertBaseModel()
    payloads = [
        np.random.randint(0, 30000, (batch, seq)).astype(np.int32)
        for _ in range(16)
    ]
    dispatch = lambda p: model._fwd(model._params, p)  # noqa: E731
    model.warmup()
    # Pre-warm the dynamic batcher's power-of-two row buckets so no
    # measured window pays a through-tunnel XLA compile.
    for rows in (batch, 2 * batch, 4 * batch):
        if rows <= 32:
            jax.block_until_ready(
                dispatch(np.zeros((rows, seq), np.int32))
            )
    from tritonclient_tpu.utils import tpu_shared_memory as tpushm

    co = tpushm.transfer_coalescer()
    if co is not None:
        co.warm((batch, 768), np.float32)

    from statistics import median

    import importlib
    bench = importlib.import_module("bench")

    servers, sessions, names, measures = [], [], [], []
    try:
        for spec in configs:
            parts = spec.split("-")
            aio = parts[0] == "aio"
            window = parts[1] == "window"
            pool = 32
            batch_delay = None
            coalesce = False
            sliced = False
            # Per-config knobs must reset between variants or a 'rateN'/
            # 'shardN' token would leak into every later server/analyzer
            # construction.
            os.environ["TPU_SERVER_BATCH_RATE_FACTOR"] = "1.0"
            os.environ.pop("PA_MUX_SHARD", None)
            os.environ.pop("TPU_SERVER_BATCH_DISPATCHERS", None)
            os.environ.pop("TPU_SERVER_BATCH_SERIAL_RATE", None)
            for p in parts[2:]:
                if p.startswith("pool"):
                    pool = int(p[4:])
                elif p.startswith("batch"):
                    batch_delay = int(p[5:])
                elif p == "coal":
                    coalesce = True
                elif p.startswith("rate"):
                    os.environ["TPU_SERVER_BATCH_RATE_FACTOR"] = p[4:]
                elif p.startswith("disp"):
                    os.environ["TPU_SERVER_BATCH_DISPATCHERS"] = p[4:]
                elif p == "sliced":
                    sliced = True
                elif p.startswith("shard"):
                    os.environ["PA_MUX_SHARD"] = p[5:]
            overlay = {
                "TPU_TRANSFER_COALESCE": "1" if coalesce else "0",
                "TPU_SERVER_BATCH_ROWVIEW": "0" if sliced else "1",
            }
            os.environ["TPU_STREAM_POOL_WORKERS"] = str(pool)
            os.environ["TPU_SERVER_GRPC_AIO"] = "1" if aio else "0"
            if batch_delay is None:
                os.environ["TPU_SERVER_DYNAMIC_BATCH"] = "0"
            else:
                os.environ["TPU_SERVER_DYNAMIC_BATCH"] = "1"
                os.environ["TPU_SERVER_BATCH_DELAY_US"] = str(batch_delay)
            server = InferenceServer(models=[model], http=False)
            server.start()
            analyzer = PerfAnalyzer(
                server.grpc_address, model.name, protocol="grpc",
                batch_size=batch, shared_memory="tpu", streaming=True,
                async_window=window,
                read_outputs=True, measurement_interval_s=seconds,
                warmup_s=1.0 if window else 0.0,
                shape_overrides={"INPUT_IDS": seq},
            )
            servers.append(server)
            names.append(spec)
            if window:
                sessions.append(None)
                analyzer.measure(depth)  # discard (one-shot mode)
                measures.append(
                    lambda a=analyzer, ov=overlay: (
                        os.environ.update(ov),
                        a.measure(depth).summary(),
                    )[1]
                )
            else:
                session = analyzer.session(depth)
                session.__enter__()
                os.environ.update(overlay)
                session.measure(interval_s=1.5)  # discard
                sessions.append(session)
                measures.append(
                    lambda s=session, ov=overlay: (
                        os.environ.update(ov),
                        s.measure(interval_s=seconds).summary(),
                    )[1]
                )

        def proc_cpu():
            with open(f"/proc/{os.getpid()}/stat") as f:
                p = f.read().split()
            return (int(p[13]) + int(p[14])) / os.sysconf("SC_CLK_TCK")

        results = {n: [] for n in names}
        results["inprocess"] = []
        lat = {n: [] for n in names}
        cpu_ms = {n: [] for n in names}
        cpu_ms["inprocess"] = []
        for r in range(rounds):
            c0 = proc_cpu()
            t0 = time.perf_counter()
            ips, _ = bench._pipelined_inprocess(
                dispatch, jax.device_get, payloads, seconds, depth
            )
            cpu_ms["inprocess"].append(
                (proc_cpu() - c0) / max(ips * (time.perf_counter() - t0), 1) * 1e3
            )
            results["inprocess"].append(ips)
            for name, measure in zip(names, measures):
                c0 = proc_cpu()
                t0 = time.perf_counter()
                s = measure()
                wall = time.perf_counter() - t0
                results[name].append(s["throughput_infer_per_sec"])
                cpu_ms[name].append(
                    (proc_cpu() - c0)
                    / max(s["throughput_infer_per_sec"] * wall, 1) * 1e3
                )
                lat[name].append((s["latency_p50_us"], s["latency_p99_us"]))
        inproc = median(results["inprocess"])
        print(f"inprocess: {[round(x,1) for x in results['inprocess']]} "
              f"median {inproc:.1f} cpu/req {median(cpu_ms['inprocess']):.2f}ms")
        for name, server in zip(names, servers):
            med = median(results[name])
            p50s = round(sum(x[0] for x in lat[name]) / rounds / 1000, 1)
            p99s = round(max(x[1] for x in lat[name]) / 1000, 1)
            st = server.core.model_statistics(model.name)[0]
            avg_b = round(
                st["inference_count"] / max(st["execution_count"], 1), 2
            )
            print(f"{name}: {[round(x,1) for x in results[name]]} "
                  f"median {med:.1f} ratio {med/inproc:.3f} "
                  f"p50~{p50s}ms p99max~{p99s}ms avg_batch~{avg_b} "
                  f"cpu/req {median(cpu_ms[name]):.2f}ms")
        if co is not None:
            print("coalescer:", co.stats_snapshot())
    finally:
        for s in sessions:
            try:
                if s is not None:
                    s.__exit__(None, None, None)
            except Exception:
                pass
        for server in servers:
            try:
                server.stop()
            except Exception:
                pass


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Fleet perf gate: 2 replica processes + the router, recorded honestly.

Launches the real process topology (replicas and router are separate
processes — the shared-nothing deployment shape, one device per
replica) and measures four things:

1. **Scale**: aggregate closed-loop throughput through the router with
   ONE replica routable (the other drained via the rolling-restart
   admin path) vs with BOTH — gate: ``>= 1.8x`` at 2 replicas.
2. **Solo baseline**: the in-quota tenant's p50/p99 alone on the fleet.
3. **Unprotected evidence** (recorded, not gated): the same tenant's
   p99 while an UNQUOTED hostile tenant floods the fleet — the damage
   quotas exist to prevent.
4. **Protected mix**: the hostile tenant rides a token-bucket quota;
   gates: in-quota tenant p99 ``<= 1.3x`` its solo p99, and over-quota
   rejections answered with 429s at p99 ``< 5 ms``.

Replica capacity comes from ``fleet_device`` (serve.py): executions
serialize on one device slot for ``--service-ms`` each, so capacity is
additive across replica PROCESSES even on a 1-CPU bench host — the gate
measures routing and admission, not host parallelism.

Results land in ``FLEET_r01.json`` (``--out``); exit is non-zero when a
gate fails. Router ``/metrics`` is scraped at the end and validated
with ``check_metrics_exposition`` so the recorded artifact also proves
the fleet exposition contract.

Usage::

    python scripts/fleet_bench.py [--seconds 8] [--service-ms 40]
        [--concurrency 8] [--out FLEET_r01.json] [--quick]
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

SCRIPTS_DIR = os.path.join(_REPO_ROOT, "scripts")
if SCRIPTS_DIR not in sys.path:
    sys.path.insert(0, SCRIPTS_DIR)

from check_metrics_exposition import check_exposition  # noqa: E402

from tritonclient_tpu.protocol._literals import (  # noqa: E402
    HEADER_TENANT_ID,
    STATUS_OVER_QUOTA,
)

GOLD = "gold"        # the in-quota tenant the fairness gate protects
HOSTILE = "hostile"  # quota-capped flood
MOB = "mob"          # unquoted flood (evidence phase only)


def _log(msg: str):
    print(f"[fleet_bench] {msg}", flush=True)


def _launch(cmd, env):
    return subprocess.Popen(
        cmd, cwd=_REPO_ROOT, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
    )


def _wait_for_file(path: str, timeout_s: float = 60.0) -> dict:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                return json.load(f)
        time.sleep(0.1)  # tpulint: disable=TPU001 (launcher poll)
    raise TimeoutError(f"{path} did not appear within {timeout_s}s")


def _http(address: str, method: str, path: str, body=None) -> bytes:
    req = urllib.request.Request(
        f"http://{address}/{path.lstrip('/')}",
        data=json.dumps(body).encode() if body is not None else None,
        method=method,
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        return resp.read()


class Fleet:
    """The launched topology: N replica processes + one router process."""

    def __init__(self, n_replicas: int, service_ms: float,
                 hostile_quota: str, probe_interval_s: float = 0.3):
        self.tmp = tempfile.TemporaryDirectory(prefix="fleet_bench_")
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        self.procs = []
        replica_files = []
        for i in range(n_replicas):
            path = os.path.join(self.tmp.name, f"replica{i}.json")
            replica_files.append(path)
            self.procs.append(_launch([
                sys.executable, "-m", "tritonclient_tpu.fleet.serve",
                "--name", f"r{i}", "--model-set", "fleet",
                "--service-ms", str(service_ms),
                "--address-file", path,
            ], env))
        self.replicas = [_wait_for_file(p) for p in replica_files]
        router_file = os.path.join(self.tmp.name, "router.json")
        cmd = [
            sys.executable, "-m", "tritonclient_tpu.fleet",
            "--policy", "least-outstanding",
            "--probe-interval", str(probe_interval_s),
            "--quota", f"{HOSTILE}={hostile_quota}",
            "--address-file", router_file,
        ]
        for path in replica_files:
            cmd += ["--replica-address-file", path]
        self.procs.append(_launch(cmd, env))
        self.router = _wait_for_file(router_file)
        self.http = self.router["http"]
        self.grpc = self.router["grpc"]

    def drain(self, name: str):
        _http(self.http, "POST", f"v2/fleet/replicas/{name}/drain",
              {"wait_s": 30})

    def undrain(self, name: str):
        _http(self.http, "POST", f"v2/fleet/replicas/{name}/undrain")

    def routable(self) -> int:
        doc = json.loads(_http(self.http, "GET", "v2/fleet/status"))
        return sum(1 for r in doc["replicas"] if r["state"] == "ready")

    def metrics(self) -> str:
        return _http(self.http, "GET", "metrics").decode()

    def close(self):
        for proc in self.procs:
            proc.terminate()
        for proc in self.procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        self.tmp.cleanup()


def _measure(fleet: Fleet, concurrency: int, seconds: float,
             tenant_id: str = "", warmup_s: float = 1.0):
    from tritonclient_tpu.perf_analyzer import PerfAnalyzer

    analyzer = PerfAnalyzer(
        url=fleet.grpc, model_name="fleet_device", protocol="grpc",
        collect_server_stats=False, tenant_id=tenant_id,
        measurement_interval_s=seconds, warmup_s=warmup_s,
    )
    with analyzer.session(concurrency) as session:
        return session.measure()


def _probe_rejects(fleet: Fleet, n: int = 120):
    """Sequential over-quota probes measuring the 429 path ALONE (the
    PR-7 overload-gate methodology): one thread, idle fleet, so the
    recorded latency is the router's admission answer — not GIL
    contention among a flood's own client threads. Returns rejected
    latencies (ns); the occasional refilled-token 200 is simply
    skipped."""
    body = json.dumps({
        "inputs": [{
            "name": "INPUT", "datatype": "INT32", "shape": [1, 16],
            "data": list(range(16)),
        }]
    }).encode()
    url = f"http://{fleet.http}/v2/models/fleet_device/infer"
    latencies = []
    for _ in range(n):
        req = urllib.request.Request(
            url, data=body, method="POST",
            headers={HEADER_TENANT_ID: HOSTILE,
                     "Content-Type": "application/json"},
        )
        t0 = time.monotonic_ns()
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                resp.read()
        except urllib.error.HTTPError as e:
            e.read()
            if e.code == STATUS_OVER_QUOTA:
                latencies.append(time.monotonic_ns() - t0)
    return latencies


def _measure_pair(fleet: Fleet, gold_c: int, flood_c: int,
                  flood_tenant: str, seconds: float):
    """Gold and the flood tenant load the fleet CONCURRENTLY, each from
    its own closed-loop session, so gold's arrival structure matches its
    solo baseline exactly."""
    results = {}

    def run(key, concurrency, tenant):
        results[key] = _measure(
            fleet, concurrency, seconds, tenant_id=tenant
        )

    threads = [
        threading.Thread(target=run, args=("gold", gold_c, GOLD)),
        threading.Thread(
            target=run, args=("flood", flood_c, flood_tenant)
        ),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results["gold"], results["flood"]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="fleet_bench")
    parser.add_argument("--seconds", type=float, default=8.0,
                        help="measurement window per phase")
    parser.add_argument("--service-ms", type=float, default=40.0,
                        help="modeled device time per execution")
    parser.add_argument("--concurrency", type=int, default=8,
                        help="closed-loop depth for the scale phases")
    # rate 2/s, burst 1: admitted hostile work is SERIAL, so with 2
    # replicas the least-outstanding policy always has a hostile-free
    # replica to give the in-quota tenant — admission shapes the flood,
    # load-aware routing isolates what it admits.
    parser.add_argument("--hostile-quota", default="2:1",
                        help="token-bucket spec for the hostile tenant")
    parser.add_argument("--out", default=os.path.join(
        _REPO_ROOT, "FLEET_r01.json"))
    parser.add_argument("--quick", action="store_true",
                        help="3 s windows (smoke only; gates unreliable)")
    args = parser.parse_args(argv)
    seconds = 3.0 if args.quick else args.seconds

    t_start = time.time()
    _log(f"launching 2 replicas (service {args.service_ms} ms) + router")
    fleet = Fleet(2, args.service_ms, args.hostile_quota)
    try:
        # Phase 1: one replica routable (r1 drained via the rolling-
        # restart path — the same admin surface operators use).
        fleet.drain("r1")
        assert fleet.routable() == 1, "drain did not settle"
        _log(f"phase 1: {args.concurrency}-deep closed loop, 1 replica")
        w1 = _measure(fleet, args.concurrency, seconds)
        t1 = w1.throughput

        # Phase 2: both replicas.
        fleet.undrain("r1")
        deadline = time.monotonic() + 10
        while fleet.routable() < 2 and time.monotonic() < deadline:
            time.sleep(0.1)  # tpulint: disable=TPU001 (rejoin poll)
        assert fleet.routable() == 2, "replica did not rejoin"
        _log(f"phase 2: {args.concurrency}-deep closed loop, 2 replicas")
        w2 = _measure(fleet, args.concurrency, seconds)
        t2 = w2.throughput
        scale = t2 / t1 if t1 else 0.0
        _log(f"aggregate throughput: {t1:.1f} -> {t2:.1f} infer/s "
             f"({scale:.2f}x)")

        # Phase 3: the in-quota tenant alone.
        _log("phase 3: gold tenant solo baseline")
        w_solo = _measure(fleet, 1, seconds, tenant_id=GOLD)
        solo = w_solo.tenant_summary()[GOLD]

        # Phase 4 (evidence): an UNQUOTED flood — what the mix would do
        # to gold without admission control.
        _log("phase 4: unprotected flood (evidence, not gated)")
        w_gold_raw, w_mob = _measure_pair(
            fleet, 1, args.concurrency, MOB, seconds
        )
        unprotected = w_gold_raw.tenant_summary().get(GOLD, {})

        # Phase 5 (gated): the hostile tenant rides its token bucket.
        _log("phase 5: protected hostile mix")
        w_gold_mix, w_hostile = _measure_pair(
            fleet, 1, args.concurrency, HOSTILE, seconds
        )
        mix = w_gold_mix.tenant_summary()[GOLD]
        hostile = w_hostile.summary()

        # Phase 6 (gated): the 429 path measured alone — sequential
        # probes on an otherwise-idle fleet, PR-7 overload-gate style.
        _log("phase 6: sequential over-quota probes (429 latency)")
        # Deliberately-sync settle wait (bench driver thread).
        time.sleep(0.5)  # tpulint: disable=TPU001
        probe_ns = _probe_rejects(fleet)
        probe_ns.sort()
        probe_p99_ms = (
            probe_ns[max(0, int(len(probe_ns) * 0.99) - 1)] / 1e6
            if probe_ns else float("inf")
        )

        metrics_text = fleet.metrics()
        exposition_errors = check_exposition(metrics_text)
        rejection_rows = [
            line for line in metrics_text.splitlines()
            if line.startswith("nv_fleet_tenant_quota_rejections_total{")
            and not line.endswith(" 0")
        ]
    finally:
        fleet.close()

    fairness = (
        mix["latency_p99_us"] / solo["latency_p99_us"]
        if solo["latency_p99_us"] else float("inf")
    )
    gates = {
        "scale_2x_replicas_ge_1.8": scale >= 1.8,
        "gold_mix_p99_le_1.3x_solo": fairness <= 1.3,
        # Gated on the sequential-probe phase: the in-mix reject p99 is
        # recorded beside it but includes the flood's own client-side
        # GIL contention on a 1-CPU bench host.
        "over_quota_429_p99_lt_5ms": (
            len(probe_ns) >= 50 and probe_p99_ms < 5.0
        ),
        "router_exposition_valid": not exposition_errors,
    }
    result = {
        "kind": "fleet_bench",
        "run": "r01",
        "config": {
            "replicas": 2,
            "service_ms": args.service_ms,
            "concurrency": args.concurrency,
            "seconds": seconds,
            "hostile_quota": args.hostile_quota,
            "policy": "least-outstanding",
            "protocol": "grpc (raw-bytes passthrough router)",
            "quick": bool(args.quick),
        },
        "scale": {
            "throughput_1_replica": round(t1, 2),
            "throughput_2_replicas": round(t2, 2),
            "ratio": round(scale, 3),
            "errors": w1.errors + w2.errors,
        },
        "gold_solo": solo,
        "gold_under_unprotected_flood": unprotected,
        "mob_summary": {
            k: w_mob.summary()[k]
            for k in ("count", "errors", "throughput_infer_per_sec")
        },
        "gold_under_protected_mix": mix,
        "fairness_p99_ratio": round(fairness, 3),
        "unprotected_p99_ratio": round(
            unprotected.get("latency_p99_us", 0)
            / solo["latency_p99_us"], 3
        ) if solo["latency_p99_us"] else None,
        "hostile_mix": {
            k: hostile.get(k)
            for k in ("count", "errors", "quota_rejections",
                      "quota_rejection_rate", "reject_p50_us",
                      "reject_p99_us", "throughput_infer_per_sec")
        },
        "reject_probes": {
            "probes": 120,
            "rejected": len(probe_ns),
            "p50_ms": round(
                probe_ns[len(probe_ns) // 2] / 1e6, 3
            ) if probe_ns else None,
            "p99_ms": round(probe_p99_ms, 3),
        },
        "router_metrics": {
            "exposition_errors": exposition_errors,
            "nonzero_rejection_rows": rejection_rows,
        },
        "gates": gates,
        "pass": all(gates.values()),
        "wall_s": round(time.time() - t_start, 1),
    }
    with open(args.out, "w") as f:
        json.dump(result, f, indent=1)
    _log(f"scale {scale:.2f}x | gold p99 solo {solo['latency_p99_us']} us "
         f"-> mix {mix['latency_p99_us']} us ({fairness:.2f}x, "
         f"unprotected {result['unprotected_p99_ratio']}x) | "
         f"429s: {hostile['quota_rejections']} in mix, probe p99 "
         f"{probe_p99_ms:.2f} ms over {len(probe_ns)} rejects")
    _log(f"gates: {gates} -> {'PASS' if result['pass'] else 'FAIL'} "
         f"({args.out})")
    return 0 if result["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())

"""Where does Python time go during a depth-32 serving window?

Samples sys._current_frames() at ~150 Hz from a sampler thread during a
serving window and an in-process window, aggregating by thread-name
bucket and top frame. Also measures GIL scheduling delay (sleep
overshoot) percentiles in both regimes.
"""

import collections
import os
import sys
import threading
import time

import numpy as np

os.environ.setdefault("TPU_SERVER_DYNAMIC_BATCH", "0")
sys.setswitchinterval(0.0002)
sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


class Sampler(threading.Thread):
    def __init__(self):
        super().__init__(daemon=True)
        self.samples = collections.Counter()
        self.delays = []
        self._stop = threading.Event()

    def run(self):
        names = {}
        while not self._stop.is_set():
            t0 = time.perf_counter()
            time.sleep(0.0005)
            self.delays.append(time.perf_counter() - t0 - 0.0005)
            if len(self.delays) % 3:
                continue  # sample stacks at 1/3 rate
            for t in threading.enumerate():
                names[t.ident] = t.name
            me = threading.get_ident()
            for ident, frame in sys._current_frames().items():
                if ident == me:
                    continue
                name = names.get(ident, "?").split("-")[0].split("_")[0]
                code = frame.f_code
                self.samples[
                    f"{name}:{os.path.basename(code.co_filename)}:"
                    f"{code.co_name}"
                ] += 1

    def stop(self):
        self._stop.set()

    def report(self, label, top=18):
        total = sum(self.samples.values())
        d = sorted(self.delays)
        import math

        def pct(p):
            return d[min(len(d) - 1, math.ceil(p / 100 * len(d)) - 1)] * 1000

        print(f"== {label}: {total} stack samples, sched delay "
              f"p50={pct(50):.2f}ms p90={pct(90):.2f}ms p99={pct(99):.2f}ms")
        for key, n in self.samples.most_common(top):
            print(f"  {n/total*100:5.1f}% {key}")


def main():
    depth = int(os.environ.get("PROBE_DEPTH", "32"))
    seconds = float(os.environ.get("PROBE_SECONDS", "6"))
    batch, seq = 8, 128

    import jax

    from tritonclient_tpu.models.bert import BertBaseModel
    from tritonclient_tpu.perf_analyzer import PerfAnalyzer
    from tritonclient_tpu.server import InferenceServer
    import bench

    model = BertBaseModel()
    payloads = [
        np.random.randint(0, 30000, (batch, seq)).astype(np.int32)
        for _ in range(16)
    ]
    dispatch = lambda p: model._fwd(model._params, p)  # noqa: E731
    model.warmup()

    with InferenceServer(models=[model], http=False) as server:
        analyzer = PerfAnalyzer(
            server.grpc_address, model.name, protocol="grpc",
            batch_size=batch, shared_memory="tpu", streaming=True,
            read_outputs=True, measurement_interval_s=seconds,
            warmup_s=0.0, shape_overrides={"INPUT_IDS": seq},
        )
        with analyzer.session(depth) as session:
            session.measure(interval_s=1.5)  # discard
            s1 = Sampler()
            s1.start()
            w = session.measure(interval_s=seconds)
            s1.stop()
            print("serving ips:", w.summary()["throughput_infer_per_sec"])
            s1.report("serving window")

            s2 = Sampler()
            s2.start()
            ips, _ = bench._pipelined_inprocess(
                dispatch, jax.device_get, payloads, seconds, depth
            )
            s2.stop()
            print("inprocess ips:", round(ips, 1))
            s2.report("in-process window")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Validate Prometheus exposition output from the server's /metrics.

Invoked from tier-1 tests (tests/test_observability.py) against the live
endpoint, and usable standalone::

    curl -s http://HOST:PORT/metrics | python scripts/check_metrics_exposition.py
    python scripts/check_metrics_exposition.py metrics.txt

Checks (exit 1 with one line per violation):
  * every sample's metric family is preceded by ``# HELP`` and ``# TYPE``
  * ``# TYPE`` names a valid Prometheus type
  * sample lines parse, with correctly escaped label values
    (backslash, quote, and newline must be escaped)
  * histogram families: ``le`` bucket bounds strictly ascending, cumulative
    bucket values non-decreasing, a ``+Inf`` bucket present, ``_count``
    equal to the ``+Inf`` bucket, and ``_sum`` present and >= 0
  * summary families (the sketch-backed ``*_quantiles`` rows): every
    ``quantile`` label in [0, 1], values monotone non-decreasing in the
    quantile, ``_sum``/``_count`` present and >= 0
  * counter samples non-negative; gauges reporting ages (``*_age_us``)
    non-negative (a negative age means a broken clock, not a quiet queue)
  * the ``nv_inference_shed_total`` family: every sample carries exactly
    the {model, version, reason} label set with ``reason`` drawn from the
    canonical shed vocabulary, and all three reasons are present per
    (model, version) series so reason sums are well-defined
  * the ``nv_inference_invalid_request_total`` family (PR 19): exactly
    {model, version, reason} with ``reason`` drawn from the canonical
    invalid-request vocabulary (``protocol._literals.INVALID_REASONS``)
    and EVERY reason row rendered per (model, version) series (zeros
    included) — rejection-rate dashboards must never guess
    absent-as-zero, and a non-canonical reason means a front-end
    bypassed ``protocol/_validate``
  * the fleet-router families: ``nv_fleet_tenant_quota_rejections_total``
    carries exactly {tenant, reason} with canonical quota reasons and
    every reason row present per tenant;
    ``nv_fleet_replica_up`` is a per-replica gauge valued 0/1;
    ``nv_fleet_replica_outstanding`` / ``nv_fleet_replica_queue_depth``
    carry a replica label and are non-negative
  * the stepscope families: ``nv_engine_step_duration_us_quantiles``
    quantile rows carry exactly {model, phase, stage, quantile} with
    ``stage``/``phase`` drawn from the canonical stepscope vocabularies
    (and the shared summary checks — quantile monotonicity, _sum/_count);
    ``nv_engine_collectives_total`` carries exactly {model, op}
  * the overlap families: ``nv_engine_collective_overlap_us_total``
    carries exactly {model, kind} with ``kind`` drawn from the canonical
    overlap vocabulary and both kind rows present per model (so the
    overlap ratio is computable from one scrape);
    ``nv_engine_inflight_steps`` carries exactly {model}, non-negative
  * the paged-KV families: ``nv_engine_kv_blocks_used`` /
    ``nv_engine_kv_blocks_total`` carry exactly {model}, are
    non-negative, and used <= total per model;
    ``nv_engine_prefix_cache_events_total`` carries exactly
    {model, event} with ``event`` drawn from the canonical prefix-cache
    vocabulary and every event row present per model (so hit rates are
    computable from any single scrape)
  * the fleetscope families (PR 16): ``nv_fleet_scrape_age_s`` carries
    exactly {replica} and is non-negative;
    ``nv_fleet_scrape_failures_total`` carries exactly {replica};
    ``nv_fleet_slo_burn_rate`` carries exactly {model, tenant, window}
    with ``window`` drawn from the canonical SLO window vocabulary and
    a non-negative value; ``nv_fleet_slo_budget_remaining`` carries
    exactly {model, tenant} with a value in [0, 1];
    ``nv_fleet_cohort_requests_total`` carries exactly {cohort} with
    the cohort label in canonical (lowercase slug) form;
    ``nv_engine_kv_bytes_touched_total`` carries exactly
    {model, phase} with ``phase`` from the stepscope vocabulary
  * the compile-plane families (PR 20): ``nv_engine_compile_cache_entries``
    carries exactly {model, callable} with a value >= 1 (a row exists
    only once a dispatch signature was recorded);
    ``nv_engine_retrace_total`` carries exactly {model, callable}; and
    per (model, callable) series retraces <= entries - 1 (every retrace
    is a distinct signature beyond the first, so a counter exceeding
    that means double-counted compiles)
  * the memscope families (PR 18): ``nv_device_memory_bytes`` carries
    exactly {model, pool, kind} with ``pool``/``kind`` drawn from the
    canonical memscope vocabularies and non-negative values, with
    live <= peak per (model, pool);
    ``nv_device_memory_events_total`` carries exactly
    {model, pool, event} with canonical events and EVERY event row
    rendered per (model, pool) cell (zeros included);
    ``nv_device_memory_headroom_bytes`` carries exactly {model} and is
    non-negative
"""

import os
import re
import sys
from typing import Dict, List, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

try:
    from tritonclient_tpu.protocol._literals import (
        HEDGE_OUTCOMES,
        INVALID_REASONS,
        QUOTA_REASONS,
        RETRY_REASONS,
        SHED_REASONS,
    )
except ImportError:  # standalone copy of the script: keep it usable
    SHED_REASONS = ("admission", "expired", "cancelled")
    QUOTA_REASONS = ("rate", "concurrency", "pressure")
    RETRY_REASONS = ("connect", "send", "status", "idempotent")
    HEDGE_OUTCOMES = ("primary", "hedge", "failed")
    INVALID_REASONS = ("malformed", "invalid_shape", "invalid_dtype",
                       "data_mismatch", "shm_bounds", "too_large")

try:
    from tritonclient_tpu._stepscope import STEP_PHASES, STEP_STAGES
except ImportError:  # standalone copy of the script: keep it usable
    STEP_STAGES = ("dispatch", "device", "other")
    STEP_PHASES = ("prefill", "prefill_chunk", "decode", "compute")

try:
    from tritonclient_tpu.protocol._literals import PREFIX_EVENTS
except ImportError:  # standalone copy of the script: keep it usable
    PREFIX_EVENTS = ("hit", "miss", "evict")

try:
    from tritonclient_tpu.protocol._literals import OVERLAP_KINDS
except ImportError:  # standalone copy of the script: keep it usable
    OVERLAP_KINDS = ("exposed", "hidden")

try:
    from tritonclient_tpu.protocol._literals import (
        COHORT_LABEL_RE,
        SLO_WINDOWS,
    )
except ImportError:  # standalone copy of the script: keep it usable
    SLO_WINDOWS = ("fast", "slow")
    COHORT_LABEL_RE = re.compile(r"^[a-z0-9][a-z0-9_-]*$")

try:
    from tritonclient_tpu.protocol._literals import (
        MEM_EVENTS,
        MEM_KINDS,
        MEM_POOLS,
    )
except ImportError:  # standalone copy of the script: keep it usable
    MEM_POOLS = ("kv", "params", "shm", "scratch")
    MEM_KINDS = ("live", "peak", "reserved")
    MEM_EVENTS = ("alloc", "free", "park", "evict")

_SHED_FAMILY = "nv_inference_shed_total"
# Invalid-request counter (PR 19): boundary-validation rejections with
# the same stable-label-set discipline as the shed counter — canonical
# reasons only, every reason row rendered per (model, version).
_INVALID_FAMILY = "nv_inference_invalid_request_total"
# Fleet-router families (served by the router's own /metrics): same
# stable-label-set discipline as the shed counter.
_QUOTA_FAMILY = "nv_fleet_tenant_quota_rejections_total"
_REPLICA_UP_FAMILY = "nv_fleet_replica_up"
_REPLICA_GAUGE_FAMILIES = (
    "nv_fleet_replica_outstanding",
    "nv_fleet_replica_queue_depth",
)
# Resilience families (PR 9): canonical-vocabulary counters with every
# row always rendered, plus the breaker-state gauge's 3-value encoding.
_RETRY_FAMILY = "nv_client_retries_total"
_HEDGE_FAMILY = "nv_fleet_hedges_total"
_RESTARTS_FAMILY = "nv_fleet_replica_restarts_total"
_BREAKER_FAMILY = "nv_client_breaker_state"
# Stepscope families (engine step profiling): fixed label sets with
# canonical stage/phase vocabularies so dashboards can group blindly.
_STEP_FAMILY = "nv_engine_step_duration_us_quantiles"
_COLLECTIVES_FAMILY = "nv_engine_collectives_total"
# Paged-KV families (block pool occupancy + prefix-cache events).
_KV_USED_FAMILY = "nv_engine_kv_blocks_used"
_KV_TOTAL_FAMILY = "nv_engine_kv_blocks_total"
_PREFIX_FAMILY = "nv_engine_prefix_cache_events_total"
# Overlap plane (PR 13): exposed-vs-hidden collective time counter with
# the canonical kind vocabulary, plus the pipelined-dispatch depth gauge.
_OVERLAP_FAMILY = "nv_engine_collective_overlap_us_total"
_INFLIGHT_FAMILY = "nv_engine_inflight_steps"
# Fleetscope families (PR 16): scrape-health gauges/counters on the
# router plus the SLO plane (burn rates, budget, cohort attribution)
# and the engine's per-phase KV traffic counter.
_SCRAPE_AGE_FAMILY = "nv_fleet_scrape_age_s"
_SCRAPE_FAILURES_FAMILY = "nv_fleet_scrape_failures_total"
_BURN_FAMILY = "nv_fleet_slo_burn_rate"
_BUDGET_FAMILY = "nv_fleet_slo_budget_remaining"
_COHORT_FAMILY = "nv_fleet_cohort_requests_total"
_KV_BYTES_FAMILY = "nv_engine_kv_bytes_touched_total"
# Memscope families (PR 18): the device-memory ledger's byte gauges,
# event counters, and the admission headroom gauge.
_MEM_BYTES_FAMILY = "nv_device_memory_bytes"
_MEM_EVENTS_FAMILY = "nv_device_memory_events_total"
_MEM_HEADROOM_FAMILY = "nv_device_memory_headroom_bytes"
# Compile-plane families (PR 20): distinct dispatch signatures per
# jitted callable (compile cache entries) and retrace events beyond the
# first compile — the runtime face of TPU017 bucket discipline.
_COMPILE_FAMILY = "nv_engine_compile_cache_entries"
_RETRACE_FAMILY = "nv_engine_retrace_total"

_VALID_TYPES = {"counter", "gauge", "histogram", "summary", "untyped"}
_METRIC_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_HELP_RE = re.compile(rf"^# HELP ({_METRIC_NAME}) (.*)$")
_TYPE_RE = re.compile(rf"^# TYPE ({_METRIC_NAME}) (\S+)$")
_SAMPLE_RE = re.compile(
    rf"^({_METRIC_NAME})(\{{.*\}})? ([^ ]+)( [0-9]+)?$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\[\\"n])*)"')


def _parse_labels(raw: str, errors: List[str], lineno: int) -> Dict[str, str]:
    """Parse {k="v",...}; any residue after consuming valid pairs means a
    malformed pair or bad escaping."""
    body = raw[1:-1]
    labels: Dict[str, str] = {}
    pos = 0
    while pos < len(body):
        m = _LABEL_RE.match(body, pos)
        if m is None:
            errors.append(
                f"line {lineno}: bad label syntax or escaping near "
                f"{body[pos:pos + 40]!r}"
            )
            return labels
        labels[m.group(1)] = m.group(2)
        pos = m.end()
        if pos < len(body):
            if body[pos] != ",":
                errors.append(
                    f"line {lineno}: expected ',' between labels, got "
                    f"{body[pos]!r}"
                )
                return labels
            pos += 1
    return labels


def _family_of(name: str, types: Dict[str, str]) -> str:
    """Map a sample name back to its declared family (histogram/summary
    series carry _bucket/_sum/_count suffixes)."""
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix) and name[: -len(suffix)] in types:
            return name[: -len(suffix)]
    return name


def check_exposition(text: str) -> List[str]:
    """Return a list of violations (empty = valid)."""
    errors: List[str] = []
    helps: Dict[str, str] = {}
    types: Dict[str, str] = {}
    # family -> list of (labels, float value, sample name, lineno)
    samples: Dict[str, List[Tuple[Dict[str, str], float, str, int]]] = {}

    for lineno, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        if line.startswith("#"):
            m = _HELP_RE.match(line)
            if m:
                helps[m.group(1)] = m.group(2)
                continue
            m = _TYPE_RE.match(line)
            if m:
                if m.group(2) not in _VALID_TYPES:
                    errors.append(
                        f"line {lineno}: invalid TYPE '{m.group(2)}' for "
                        f"{m.group(1)}"
                    )
                if m.group(1) in samples:
                    errors.append(
                        f"line {lineno}: # TYPE {m.group(1)} appears after "
                        "its samples"
                    )
                types[m.group(1)] = m.group(2)
                continue
            continue  # other comments are legal
        m = _SAMPLE_RE.match(line)
        if m is None:
            errors.append(f"line {lineno}: unparseable sample: {line!r}")
            continue
        name, raw_labels, value = m.group(1), m.group(2), m.group(3)
        labels = (
            _parse_labels(raw_labels, errors, lineno) if raw_labels else {}
        )
        try:
            fvalue = float(value)
        except ValueError:
            errors.append(f"line {lineno}: non-numeric value {value!r}")
            continue
        family = _family_of(name, types)
        samples.setdefault(family, []).append((labels, fvalue, name, lineno))

    for family in samples:
        if family not in helps:
            errors.append(f"metric family {family} has no # HELP")
        if family not in types:
            errors.append(f"metric family {family} has no # TYPE")

    for family, ftype in types.items():
        if ftype == "counter":
            for labels, value, name, lineno in samples.get(family, []):
                if value < 0:
                    errors.append(
                        f"line {lineno}: counter {name} value {value} < 0"
                    )
            if family == _SHED_FAMILY:
                # Shed-counter contract: fixed {model, version, reason}
                # label set, canonical reasons only, and every reason row
                # present per series (so reasons provably sum to the
                # observed sheds).
                series_reasons: Dict[tuple, set] = {}
                for labels, value, name, lineno in samples.get(family, []):
                    if set(labels) != {"model", "version", "reason"}:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != "
                            "['model', 'reason', 'version']"
                        )
                        continue
                    if labels["reason"] not in SHED_REASONS:
                        errors.append(
                            f"line {lineno}: {family} reason "
                            f"{labels['reason']!r} not in "
                            f"{list(SHED_REASONS)}"
                        )
                        continue
                    series_reasons.setdefault(
                        (labels["model"], labels["version"]), set()
                    ).add(labels["reason"])
                for (model, version), reasons in series_reasons.items():
                    missing = [r for r in SHED_REASONS if r not in reasons]
                    if missing:
                        errors.append(
                            f'{family}{{model="{model}",'
                            f'version="{version}"}}: missing reason '
                            f"rows {missing}"
                        )
            if family == _INVALID_FAMILY:
                # Invalid-request contract: fixed {model, version, reason}
                # label set, reasons drawn from the canonical
                # INVALID_REASONS vocabulary (a stray reason means a
                # front-end invented its own classification instead of
                # going through protocol/_validate), and every reason row
                # present per series so rejection sums never need
                # absent-as-zero guessing.
                series_reasons: Dict[tuple, set] = {}
                for labels, value, name, lineno in samples.get(family, []):
                    if set(labels) != {"model", "version", "reason"}:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != "
                            "['model', 'reason', 'version']"
                        )
                        continue
                    if labels["reason"] not in INVALID_REASONS:
                        errors.append(
                            f"line {lineno}: {family} reason "
                            f"{labels['reason']!r} not in "
                            f"{list(INVALID_REASONS)}"
                        )
                        continue
                    series_reasons.setdefault(
                        (labels["model"], labels["version"]), set()
                    ).add(labels["reason"])
                for (model, version), reasons in series_reasons.items():
                    missing = [
                        r for r in INVALID_REASONS if r not in reasons
                    ]
                    if missing:
                        errors.append(
                            f'{family}{{model="{model}",'
                            f'version="{version}"}}: missing reason '
                            f"rows {missing}"
                        )
            if family == _QUOTA_FAMILY:
                # Quota-rejection contract: fixed {tenant, reason} label
                # set, canonical reasons, every reason row present per
                # tenant (so per-tenant rejection sums are well-defined).
                tenant_reasons: Dict[str, set] = {}
                for labels, value, name, lineno in samples.get(family, []):
                    if set(labels) != {"tenant", "reason"}:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != ['reason', 'tenant']"
                        )
                        continue
                    if labels["reason"] not in QUOTA_REASONS:
                        errors.append(
                            f"line {lineno}: {family} reason "
                            f"{labels['reason']!r} not in "
                            f"{list(QUOTA_REASONS)}"
                        )
                        continue
                    tenant_reasons.setdefault(
                        labels["tenant"], set()
                    ).add(labels["reason"])
                for tenant, reasons in tenant_reasons.items():
                    missing = [r for r in QUOTA_REASONS if r not in reasons]
                    if missing:
                        errors.append(
                            f'{family}{{tenant="{tenant}"}}: missing '
                            f"reason rows {missing}"
                        )
            if family in (_RETRY_FAMILY, _HEDGE_FAMILY):
                # Canonical-vocabulary counters: one label, canonical
                # values only, EVERY canonical row rendered (zeros
                # included) so rates are always well-defined.
                label, vocab = (
                    ("reason", RETRY_REASONS)
                    if family == _RETRY_FAMILY
                    else ("outcome", HEDGE_OUTCOMES)
                )
                seen = set()
                for labels, value, name, lineno in samples.get(family, []):
                    if set(labels) != {label}:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != ['{label}']"
                        )
                        continue
                    if labels[label] not in vocab:
                        errors.append(
                            f"line {lineno}: {family} {label} "
                            f"{labels[label]!r} not in {list(vocab)}"
                        )
                        continue
                    seen.add(labels[label])
                if samples.get(family):
                    missing = [v for v in vocab if v not in seen]
                    if missing:
                        errors.append(
                            f"{family}: missing {label} rows {missing}"
                        )
            if family == _RESTARTS_FAMILY:
                for labels, value, name, lineno in samples.get(family, []):
                    if set(labels) != {"replica"}:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != ['replica']"
                        )
            if family == _PREFIX_FAMILY:
                # Prefix-cache event contract: fixed {model, event} label
                # set, canonical events only, every event row present per
                # model (hit rate = hit / (hit + miss) must be computable
                # from one scrape without guessing at absent-as-zero).
                model_events: Dict[str, set] = {}
                for labels, value, name, lineno in samples.get(family, []):
                    if set(labels) != {"model", "event"}:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != ['event', 'model']"
                        )
                        continue
                    if labels["event"] not in PREFIX_EVENTS:
                        errors.append(
                            f"line {lineno}: {family} event "
                            f"{labels['event']!r} not in "
                            f"{list(PREFIX_EVENTS)}"
                        )
                        continue
                    model_events.setdefault(
                        labels["model"], set()
                    ).add(labels["event"])
                for model, events in model_events.items():
                    missing = [e for e in PREFIX_EVENTS if e not in events]
                    if missing:
                        errors.append(
                            f'{family}{{model="{model}"}}: missing event '
                            f"rows {missing}"
                        )
            if family == _OVERLAP_FAMILY:
                # Overlap contract: fixed {model, kind} label set,
                # canonical kinds only, and BOTH kinds present per model
                # (the overlap ratio hidden / (hidden + exposed) must be
                # computable from one scrape without absent-as-zero
                # guessing).
                model_kinds: Dict[str, set] = {}
                for labels, value, name, lineno in samples.get(family, []):
                    if set(labels) != {"model", "kind"}:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != ['kind', 'model']"
                        )
                        continue
                    if labels["kind"] not in OVERLAP_KINDS:
                        errors.append(
                            f"line {lineno}: {family} kind "
                            f"{labels['kind']!r} not in "
                            f"{list(OVERLAP_KINDS)}"
                        )
                        continue
                    model_kinds.setdefault(
                        labels["model"], set()
                    ).add(labels["kind"])
                for model, kinds in model_kinds.items():
                    missing = [k for k in OVERLAP_KINDS if k not in kinds]
                    if missing:
                        errors.append(
                            f'{family}{{model="{model}"}}: missing kind '
                            f"rows {missing}"
                        )
            if family == _SCRAPE_FAILURES_FAMILY:
                for labels, value, name, lineno in samples.get(family, []):
                    if set(labels) != {"replica"}:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != ['replica']"
                        )
            if family == _COHORT_FAMILY:
                # Cohort attribution: exactly {cohort} with the label in
                # canonical (lowercase slug) form — uncanonicalized
                # cohort names would split one cohort's series in two.
                for labels, value, name, lineno in samples.get(family, []):
                    if set(labels) != {"cohort"}:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != ['cohort']"
                        )
                        continue
                    if not COHORT_LABEL_RE.match(labels["cohort"]):
                        errors.append(
                            f"line {lineno}: {family} cohort "
                            f"{labels['cohort']!r} is not a canonical "
                            "lowercase slug"
                        )
            if family == _KV_BYTES_FAMILY:
                # KV traffic counter: exactly {model, phase} with phase
                # from the stepscope vocabulary (value non-negativity is
                # the generic counter check above).
                for labels, value, name, lineno in samples.get(family, []):
                    if set(labels) != {"model", "phase"}:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != ['model', 'phase']"
                        )
                        continue
                    if labels["phase"] not in STEP_PHASES:
                        errors.append(
                            f"line {lineno}: {family} phase "
                            f"{labels['phase']!r} not in "
                            f"{list(STEP_PHASES)}"
                        )
            if family == _MEM_EVENTS_FAMILY:
                # Memscope event contract: fixed {model, pool, event}
                # label set, canonical pools/events only, and EVERY
                # canonical event row present per (model, pool) cell so
                # churn rates never need absent-as-zero guessing.
                cell_events: Dict[tuple, set] = {}
                for labels, value, name, lineno in samples.get(family, []):
                    if set(labels) != {"model", "pool", "event"}:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != "
                            "['event', 'model', 'pool']"
                        )
                        continue
                    if labels["pool"] not in MEM_POOLS:
                        errors.append(
                            f"line {lineno}: {family} pool "
                            f"{labels['pool']!r} not in {list(MEM_POOLS)}"
                        )
                        continue
                    if labels["event"] not in MEM_EVENTS:
                        errors.append(
                            f"line {lineno}: {family} event "
                            f"{labels['event']!r} not in "
                            f"{list(MEM_EVENTS)}"
                        )
                        continue
                    cell_events.setdefault(
                        (labels["model"], labels["pool"]), set()
                    ).add(labels["event"])
                for (model, pool), events in cell_events.items():
                    missing = [e for e in MEM_EVENTS if e not in events]
                    if missing:
                        errors.append(
                            f'{family}{{model="{model}",pool="{pool}"}}: '
                            f"missing event rows {missing}"
                        )
            if family == _RETRACE_FAMILY:
                # Retrace counter: exactly {model, callable} (value
                # non-negativity is the generic counter check above; the
                # retraces-vs-entries bound is the cross-family check at
                # the bottom).
                for labels, value, name, lineno in samples.get(family, []):
                    if set(labels) != {"model", "callable"}:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != ['callable', 'model']"
                        )
            if family == _COLLECTIVES_FAMILY:
                # Stepscope collectives: fixed {model, op} label set (the
                # op value is open vocabulary — psum/ppermute/all_to_all
                # today, whatever the parallel plane adds tomorrow).
                for labels, value, name, lineno in samples.get(family, []):
                    if set(labels) != {"model", "op"}:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != ['model', 'op']"
                        )
            continue
        if ftype == "gauge":
            if family.endswith("_age_us"):
                for labels, value, name, lineno in samples.get(family, []):
                    if value < 0:
                        errors.append(
                            f"line {lineno}: age gauge {name} value "
                            f"{value} < 0"
                        )
            if family == _REPLICA_UP_FAMILY:
                # Membership gauge: one {replica} label, value 0 or 1.
                for labels, value, name, lineno in samples.get(family, []):
                    if set(labels) != {"replica"}:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != ['replica']"
                        )
                    if value not in (0.0, 1.0):
                        errors.append(
                            f"line {lineno}: {family} value {value} "
                            "not in {0, 1}"
                        )
            if family == _BREAKER_FAMILY:
                # Breaker-state gauge: one {endpoint} label, value in
                # the 3-state encoding (0=closed, 1=half_open, 2=open).
                for labels, value, name, lineno in samples.get(family, []):
                    if set(labels) != {"endpoint"}:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != ['endpoint']"
                        )
                    if value not in (0.0, 1.0, 2.0):
                        errors.append(
                            f"line {lineno}: {family} value {value} "
                            "not in {0, 1, 2}"
                        )
            if family in _REPLICA_GAUGE_FAMILIES:
                for labels, value, name, lineno in samples.get(family, []):
                    if "replica" not in labels:
                        errors.append(
                            f"line {lineno}: {family} sample without a "
                            "'replica' label"
                        )
                    if value < 0:
                        errors.append(
                            f"line {lineno}: {family} value {value} < 0 "
                            "(outstanding/depth cannot be negative)"
                        )
            if family == _INFLIGHT_FAMILY:
                # Dispatch-depth gauge: exactly {model}, non-negative (a
                # negative depth means the submit/deliver accounting
                # leaked, not an idle engine).
                for labels, value, name, lineno in samples.get(family, []):
                    if set(labels) != {"model"}:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != ['model']"
                        )
                    if value < 0:
                        errors.append(
                            f"line {lineno}: {family} value {value} < 0 "
                            "(in-flight depth cannot be negative)"
                        )
            if family == _SCRAPE_AGE_FAMILY:
                # Staleness gauge: exactly {replica}, non-negative (a
                # negative age means a broken clock, not a fresh scrape).
                for labels, value, name, lineno in samples.get(family, []):
                    if set(labels) != {"replica"}:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != ['replica']"
                        )
                    if value < 0:
                        errors.append(
                            f"line {lineno}: {family} value {value} < 0 "
                            "(scrape age cannot be negative)"
                        )
            if family == _BURN_FAMILY:
                # Burn-rate gauge: exactly {model, tenant, window} with
                # the window drawn from the canonical SLO vocabulary,
                # non-negative (burn is a rate of budget consumption).
                for labels, value, name, lineno in samples.get(family, []):
                    if set(labels) != {"model", "tenant", "window"}:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != "
                            "['model', 'tenant', 'window']"
                        )
                        continue
                    if labels["window"] not in SLO_WINDOWS:
                        errors.append(
                            f"line {lineno}: {family} window "
                            f"{labels['window']!r} not in "
                            f"{list(SLO_WINDOWS)}"
                        )
                    if value < 0:
                        errors.append(
                            f"line {lineno}: {family} value {value} < 0 "
                            "(burn rate cannot be negative)"
                        )
            if family == _BUDGET_FAMILY:
                # Budget gauge: exactly {model, tenant} (slow-window
                # rows only, so no window label), value a fraction.
                for labels, value, name, lineno in samples.get(family, []):
                    if set(labels) != {"model", "tenant"}:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != ['model', 'tenant']"
                        )
                    if not 0.0 <= value <= 1.0:
                        errors.append(
                            f"line {lineno}: {family} value {value} "
                            "outside [0, 1]"
                        )
            if family == _MEM_BYTES_FAMILY:
                # Memscope byte gauge: fixed {model, pool, kind} label
                # set, canonical pools/kinds, non-negative (live <= peak
                # is the cross-family check at the bottom).
                for labels, value, name, lineno in samples.get(family, []):
                    if set(labels) != {"model", "pool", "kind"}:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != "
                            "['kind', 'model', 'pool']"
                        )
                        continue
                    if labels["pool"] not in MEM_POOLS:
                        errors.append(
                            f"line {lineno}: {family} pool "
                            f"{labels['pool']!r} not in {list(MEM_POOLS)}"
                        )
                    if labels["kind"] not in MEM_KINDS:
                        errors.append(
                            f"line {lineno}: {family} kind "
                            f"{labels['kind']!r} not in {list(MEM_KINDS)}"
                        )
                    if value < 0:
                        errors.append(
                            f"line {lineno}: {family} value {value} < 0 "
                            "(resident bytes cannot be negative)"
                        )
            if family == _MEM_HEADROOM_FAMILY:
                # Headroom gauge: exactly {model}, non-negative (the
                # ledger clamps at zero; a negative value means the
                # capacity bookkeeping broke).
                for labels, value, name, lineno in samples.get(family, []):
                    if set(labels) != {"model"}:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != ['model']"
                        )
                    if value < 0:
                        errors.append(
                            f"line {lineno}: {family} value {value} < 0 "
                            "(headroom cannot be negative)"
                        )
            if family == _COMPILE_FAMILY:
                # Compile-cache gauge: exactly {model, callable}, value
                # >= 1 (a series renders only once a dispatch signature
                # was recorded, and the first dispatch is an entry).
                for labels, value, name, lineno in samples.get(family, []):
                    if set(labels) != {"model", "callable"}:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != ['callable', 'model']"
                        )
                        continue
                    if value < 1:
                        errors.append(
                            f"line {lineno}: {family} value {value} < 1 "
                            "(a rendered series has at least one entry)"
                        )
            if family in (_KV_USED_FAMILY, _KV_TOTAL_FAMILY):
                # Pool-occupancy gauges: exactly {model}, non-negative.
                for labels, value, name, lineno in samples.get(family, []):
                    if set(labels) != {"model"}:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != ['model']"
                        )
                    if value < 0:
                        errors.append(
                            f"line {lineno}: {family} value {value} < 0 "
                            "(block counts cannot be negative)"
                        )
            continue
        if ftype == "summary":
            if family == _STEP_FAMILY:
                # Stepscope step-duration summary: quantile rows carry
                # exactly {model, phase, stage, quantile}; _sum/_count
                # rows drop the quantile label; stage and phase come from
                # the canonical stepscope vocabularies.
                for labels, value, name, lineno in samples.get(family, []):
                    want = {"model", "phase", "stage"}
                    if name == family:
                        want = want | {"quantile"}
                    if set(labels) != want:
                        errors.append(
                            f"line {lineno}: {family} label set "
                            f"{sorted(labels)} != {sorted(want)}"
                        )
                        continue
                    if labels["stage"] not in STEP_STAGES:
                        errors.append(
                            f"line {lineno}: {family} stage "
                            f"{labels['stage']!r} not in "
                            f"{list(STEP_STAGES)}"
                        )
                    if labels["phase"] not in STEP_PHASES:
                        errors.append(
                            f"line {lineno}: {family} phase "
                            f"{labels['phase']!r} not in "
                            f"{list(STEP_PHASES)}"
                        )
            # Group per label set (minus 'quantile'); quantile rows must be
            # valid quantiles and monotone non-decreasing in q, _sum/_count
            # present and non-negative.
            series: Dict[tuple, dict] = {}
            for labels, value, name, lineno in samples.get(family, []):
                key = tuple(sorted(
                    (k, v) for k, v in labels.items() if k != "quantile"
                ))
                entry = series.setdefault(
                    key, {"quantiles": [], "sum": None, "count": None}
                )
                if name == family:
                    if "quantile" not in labels:
                        errors.append(
                            f"line {lineno}: summary sample without "
                            "'quantile' label"
                        )
                        continue
                    try:
                        q = float(labels["quantile"])
                    except ValueError:
                        errors.append(
                            f"line {lineno}: non-numeric quantile "
                            f"{labels['quantile']!r}"
                        )
                        continue
                    if not 0.0 <= q <= 1.0:
                        errors.append(
                            f"line {lineno}: quantile {q} outside [0, 1]"
                        )
                    entry["quantiles"].append((q, value, lineno))
                elif name == family + "_sum":
                    entry["sum"] = value
                elif name == family + "_count":
                    entry["count"] = value
            for key, entry in series.items():
                label_desc = "{%s}" % ",".join(
                    f'{k}="{v}"' for k, v in key
                )
                prev = None
                for q, value, lineno in sorted(entry["quantiles"]):
                    if value < 0:
                        errors.append(
                            f"line {lineno}: {family}{label_desc} "
                            f'quantile="{q}" value {value} < 0'
                        )
                    if prev is not None and value < prev:
                        errors.append(
                            f"line {lineno}: {family}{label_desc} "
                            f'quantile="{q}" value {value} < previous '
                            f"{prev} (quantiles must be non-decreasing "
                            "in q)"
                        )
                    prev = value
                if entry["sum"] is None:
                    errors.append(f"{family}{label_desc}: missing _sum")
                elif entry["sum"] < 0:
                    errors.append(
                        f"{family}{label_desc}: _sum {entry['sum']} < 0"
                    )
                if entry["count"] is None:
                    errors.append(f"{family}{label_desc}: missing _count")
                elif entry["count"] < 0:
                    errors.append(
                        f"{family}{label_desc}: _count {entry['count']} < 0"
                    )
            continue
        if ftype != "histogram":
            continue
        # Group this family's series per label set (minus 'le').
        series: Dict[tuple, dict] = {}
        for labels, value, name, lineno in samples.get(family, []):
            key = tuple(sorted(
                (k, v) for k, v in labels.items() if k != "le"
            ))
            entry = series.setdefault(
                key, {"buckets": [], "sum": None, "count": None}
            )
            if name == family + "_bucket":
                if "le" not in labels:
                    errors.append(
                        f"line {lineno}: histogram bucket without 'le' label"
                    )
                    continue
                le = labels["le"]
                bound = float("inf") if le == "+Inf" else float(le)
                entry["buckets"].append((bound, value, lineno))
            elif name == family + "_sum":
                entry["sum"] = value
            elif name == family + "_count":
                entry["count"] = value
        for key, entry in series.items():
            label_desc = "{%s}" % ",".join(f'{k}="{v}"' for k, v in key)
            buckets = sorted(entry["buckets"])
            if not buckets:
                continue
            bounds = [b for b, _, _ in buckets]
            if len(set(bounds)) != len(bounds):
                errors.append(
                    f"{family}{label_desc}: duplicate bucket bounds"
                )
            if bounds[-1] != float("inf"):
                errors.append(f"{family}{label_desc}: missing +Inf bucket")
            prev = None
            for bound, value, lineno in buckets:
                if prev is not None and value < prev:
                    errors.append(
                        f"line {lineno}: {family}{label_desc} bucket "
                        f'le="{bound}" value {value} < previous {prev} '
                        "(non-monotonic histogram)"
                    )
                prev = value
            if entry["sum"] is None:
                errors.append(f"{family}{label_desc}: missing _sum")
            elif entry["sum"] < 0:
                errors.append(
                    f"{family}{label_desc}: _sum {entry['sum']} < 0 "
                    "(durations cannot be negative)"
                )
            if entry["count"] is None:
                errors.append(f"{family}{label_desc}: missing _count")
            elif bounds[-1] == float("inf") and entry["count"] != buckets[-1][1]:
                errors.append(
                    f"{family}{label_desc}: _count {entry['count']} != "
                    f"+Inf bucket {buckets[-1][1]}"
                )
    # Cross-family paged-KV invariant: a model can never reference more
    # blocks than its pool holds (used > total means broken accounting,
    # e.g. a leaked refcount, not heavy load).
    totals = {
        labels.get("model"): value
        for labels, value, _name, _lineno in samples.get(_KV_TOTAL_FAMILY, [])
    }
    for labels, value, name, lineno in samples.get(_KV_USED_FAMILY, []):
        model = labels.get("model")
        if model in totals and value > totals[model]:
            errors.append(
                f"line {lineno}: {_KV_USED_FAMILY}{{model=\"{model}\"}} "
                f"{value} > {_KV_TOTAL_FAMILY} {totals[model]}"
            )
    # Cross-family compile-plane invariant: every retrace is a distinct
    # dispatch signature seen after the first, so per (model, callable)
    # series retraces can never exceed entries - 1 (a violation means
    # the watcher double-counted compiles or the gauge went stale).
    entries_by_series = {
        (labels.get("model"), labels.get("callable")): value
        for labels, value, _name, _lineno in samples.get(_COMPILE_FAMILY, [])
    }
    for labels, value, name, lineno in samples.get(_RETRACE_FAMILY, []):
        key = (labels.get("model"), labels.get("callable"))
        if key in entries_by_series and value > entries_by_series[key] - 1:
            errors.append(
                f'line {lineno}: {_RETRACE_FAMILY}{{model="{key[0]}",'
                f'callable="{key[1]}"}} {value} > '
                f"{_COMPILE_FAMILY} - 1 ({entries_by_series[key] - 1})"
            )
    # Cross-kind memscope invariant: live can never exceed peak for a
    # (model, pool) cell — peak is by definition the high-water of live,
    # so a violation means the ledger's peak tracking broke.
    mem_kind: Dict[tuple, Dict[str, Tuple[float, int]]] = {}
    for labels, value, _name, lineno in samples.get(_MEM_BYTES_FAMILY, []):
        if {"model", "pool", "kind"} <= set(labels):
            mem_kind.setdefault(
                (labels["model"], labels["pool"]), {}
            )[labels["kind"]] = (value, lineno)
    for (model, pool), kinds in mem_kind.items():
        if "live" in kinds and "peak" in kinds:
            live, lineno = kinds["live"]
            peak, _ = kinds["peak"]
            if live > peak:
                errors.append(
                    f"line {lineno}: {_MEM_BYTES_FAMILY}"
                    f'{{model="{model}",pool="{pool}"}} live {live} > '
                    f"peak {peak}"
                )
    return errors


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv:
        with open(argv[0]) as f:
            text = f.read()
    else:
        text = sys.stdin.read()
    errors = check_exposition(text)
    for error in errors:
        print(error, file=sys.stderr)
    if errors:
        print(f"{len(errors)} exposition violation(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

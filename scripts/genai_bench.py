"""LLM serving-plane benchmark artifact (VERDICT r3 #6; paged-KV round).

Drives the paged-KV continuous-batching engine (models/gpt_engine.py)
through the full gRPC streaming stack with the genai_perf instrument and
writes GENAI_r{N}.json at the repo root:

  * TTFT/ITL percentiles and token throughput at concurrency
    {1, 4, 8, 16}, each window extended until it holds >= 150 requests;
  * a mixed prompt-length point (--prompt-len-dist short:8,long:1) with
    per-bucket TTFT rows;
  * the prefix-caching pair: a cold window (unique prompts) vs a
    shared-prefix window (identical first tokens across requests), with
    the measured hit rate from the engine's own event counters and the
    TTFT win recorded;
  * the paged-vs-contiguous no-regression point: the engine at the
    SAME workload (input 32 / output 16 / c8 / same window) as the
    contiguous-bank baseline captured on this host before the rework;
  * the single-loop GptModel comparator at c=8 (the engine's throughput
    claim, recorded instead of asserted).

Run:  python scripts/genai_bench.py [round_number]
"""

import json
import os
import sys
import time

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.setswitchinterval(0.0002)

MIN_REQUESTS = 150


def _drain(req):
    while True:
        tok = req.out.get(timeout=300)
        if tok is None:
            return
        if isinstance(tok, BaseException):
            raise tok  # surface warmup compile/engine errors immediately


def _wait_idle(engine, timeout=60.0):
    """The warm request's slot-free travels through the delivery thread;
    warm_admission requires the engine to have PROCESSED it, not just
    the terminator to have been consumed."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(r is None for r in engine._slot_req):
            return
        time.sleep(0.05)  # tpulint: disable=TPU001 (sync bench poll)
    raise RuntimeError(f"engine not idle after warmup: {engine._slot_req}")


def _measure_min_requests(perf, c, initial_s, min_req=MIN_REQUESTS,
                          max_s=1800.0):
    """One window, re-measured once with a scaled interval if the first
    held too few requests (CPU hosts are slow enough that a fixed window
    cannot satisfy a request-count floor at every concurrency)."""
    perf.measurement_interval_s = min(initial_s, max_s)
    summary = perf.measure(c)
    if 0 < summary["requests"] < min_req:
        scale = min_req / summary["requests"] * 1.15
        perf.measurement_interval_s = min(
            perf.measurement_interval_s * scale, max_s
        )
        print(f"  c{c}: {summary['requests']} requests < {min_req}; "
              f"re-measuring over {perf.measurement_interval_s:.0f}s",
              file=sys.stderr)
        summary = perf.measure(c)
    return summary


def main():
    rnd = sys.argv[1] if len(sys.argv) > 1 else os.environ.get("ROUND", "06")
    out_tokens = int(os.environ.get("GENAI_OUTPUT_TOKENS", "8"))

    import jax

    from tritonclient_tpu import _memscope, _stepscope
    from tritonclient_tpu.genai_perf import GenAIPerf
    from tritonclient_tpu.models.gpt import GptModel
    from tritonclient_tpu.models.gpt_engine import GptEngineModel
    from tritonclient_tpu.server import InferenceServer

    import numpy as np

    engine_model = GptEngineModel()
    loop_model = GptModel()
    engine_model.warmup()
    loop_model.warmup()
    engine = engine_model.engine
    # Warm the chunked-prefill and decode shapes at the measured prompt
    # lengths (32 / 128 / 160): first-use compiles must not land inside
    # a window.
    for warm_len in (32, 128, 160):
        _drain(engine.submit(np.ones((1, warm_len), np.int32), 2))
    _wait_idle(engine)
    # Deterministically compile the vectorized admission ops for every
    # burst size k (a racy concurrent-submit warmup can skip
    # intermediate k values, leaving first-use compiles to land inside
    # a measured window).
    engine.warm_admission()
    # ... and the batched chunk-prefill family: every lane bucket ×
    # the context buckets the measured prompt lengths pass through
    # (chunks of a 160-token prompt traverse ceil(end/bs) = 2..10 →
    # buckets {2,4,8,16}). A synchronized churn burst otherwise hits
    # its first k>1 lane shape mid-window, paying a multi-second XLA
    # compile inside the measurement.
    bs = engine.block_size
    ctx = set()
    for warm_len in (32, 128, 160):
        end = 0
        while end < warm_len:
            end = min(end + engine.prefill_chunk, warm_len)
            ctx.add(-(-end // bs))
    engine.warm_prefill(ctx_blocks=sorted(ctx))
    for tok in loop_model.infer(
        {"INPUT_IDS": np.ones((1, 32), np.int32),
         "MAX_TOKENS": np.array([2], np.int32)}
    ):
        pass

    # Contiguous-bank baseline captured on this host BEFORE the paged
    # rework (same model, same workload knobs): the no-regression
    # denominator. Absent file -> the comparison is skipped, not faked.
    contig = None
    for path in (
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "CONTIG_BASELINE_c8.json"),
        "/tmp/contig_baseline_c8.json",
    ):
        if os.path.exists(path):
            with open(path) as f:
                contig = json.load(f)
            break

    result = {
        "round": rnd,
        "platform": jax.devices()[0].platform,
        "output_tokens": out_tokens,
        "kv": {
            "block_size": engine.block_size,
            "n_blocks": engine._pool.n_blocks,
            "prefill_chunk": engine.prefill_chunk,
        },
        "engine": {},   # gpt_engine: continuous batching over the block pool
        "single_loop_c8": None,  # GptModel: one generation loop per request
    }
    with InferenceServer(models=[engine_model, loop_model],
                         http=False) as server:
        perf = GenAIPerf(
            server.grpc_address,
            model_name="gpt_engine",
            input_tokens=32,
            output_tokens=out_tokens,
            vocab_size=engine_model.cfg.vocab_size,
            warmup_s=2.0,
        )
        # -- main sweep: c{1,4,8,16}, >= 150 requests per level ------------
        per_worker_rps = None
        for c in (1, 4, 8, 16):
            if per_worker_rps:
                # Seed the window from the previous level's request rate
                # (batching efficiency only improves it).
                initial = min(max(MIN_REQUESTS / (per_worker_rps * c)
                                  * 1.25, 45.0), 1800.0)
            else:
                initial = 60.0
            summary = _measure_min_requests(perf, c, initial)
            per_worker_rps = (summary["requests"]
                              / summary["duration_s"] / c) or None
            result["engine"][f"c{c}"] = {
                "concurrency": c,
                "requests": summary["requests"],
                "errors": summary["errors"],
                "duration_s": summary["duration_s"],
                "output_token_throughput_per_sec": summary[
                    "output_token_throughput_per_sec"],
                "request_throughput_per_sec": summary[
                    "request_throughput_per_sec"],
                "ttft_ms": summary["time_to_first_token"],
                "itl_ms": summary["inter_token_latency"],
            }
            if _memscope.enabled():
                # Peak KV/device bytes at this concurrency so memory
                # growth across the sweep is visible next to throughput.
                result["engine"][f"c{c}"].update(
                    _memscope.peaks("gpt_engine"))
            print(f"gpt_engine c{c}: {summary['requests']} req, "
                  f"{summary['output_token_throughput_per_sec']} tok/s, "
                  f"ttft p99 "
                  f"{summary['time_to_first_token']['p99_ms']} ms",
                  file=sys.stderr)

        # -- mixed prompt lengths (short:8,long:1 at c8) -------------------
        mixed = GenAIPerf(
            server.grpc_address,
            model_name="gpt_engine",
            input_tokens=32,
            output_tokens=out_tokens,
            vocab_size=engine_model.cfg.vocab_size,
            warmup_s=2.0,
            prompt_len_dist="short:8,long:1",  # short=32, long=128
        )
        summary = _measure_min_requests(
            mixed, 8, initial_s=MIN_REQUESTS / (per_worker_rps * 8) * 1.6
        )
        result["mixed_prompt_len_c8"] = {
            "prompt_len_dist": "short:8,long:1",
            "requests": summary["requests"],
            "errors": summary["errors"],
            "output_token_throughput_per_sec": summary[
                "output_token_throughput_per_sec"],
            "ttft_ms": summary["time_to_first_token"],
            "ttft_by_prompt_len": summary["ttft_by_prompt_len"],
            "itl_ms": summary["inter_token_latency"],
        }
        print(f"mixed-length c8: {summary['requests']} req, per-bucket "
              f"ttft {summary['ttft_by_prompt_len']}", file=sys.stderr)

        # -- prefix caching: cold vs shared-prefix TTFT --------------------
        # Same prompt length (160 = 10 blocks) both windows; the shared
        # window's prompts agree on their first 144 tokens (9 full
        # blocks), so admissions after the first resolve 9 of 10 pages
        # from cache. Cold first: its unique prompts never hit.
        prefix_kw = dict(
            url=server.grpc_address, model_name="gpt_engine",
            input_tokens=160, output_tokens=out_tokens,
            vocab_size=engine_model.cfg.vocab_size, warmup_s=2.0,
        )
        cold = GenAIPerf(**prefix_kw)
        cold_summary = _measure_min_requests(
            cold, 4, initial_s=60.0, min_req=100
        )
        ev0 = engine._prefix.snapshot_events()
        shared = GenAIPerf(**prefix_kw, shared_prefix_tokens=144)
        shared_summary = _measure_min_requests(
            shared, 4, initial_s=60.0, min_req=100
        )
        ev1 = engine._prefix.snapshot_events()
        hits = ev1["hit"] - ev0["hit"]
        misses = ev1["miss"] - ev0["miss"]
        hit_rate = round(hits / (hits + misses), 4) if hits + misses else 0.0
        cold_ttft = cold_summary["time_to_first_token"]
        shared_ttft = shared_summary["time_to_first_token"]
        result["prefix_cache_c4"] = {
            "prompt_tokens": 160,
            "shared_prefix_tokens": 144,
            "cold": {
                "requests": cold_summary["requests"],
                "ttft_ms": cold_ttft,
                "output_token_throughput_per_sec": cold_summary[
                    "output_token_throughput_per_sec"],
            },
            "shared": {
                "requests": shared_summary["requests"],
                "ttft_ms": shared_ttft,
                "output_token_throughput_per_sec": shared_summary[
                    "output_token_throughput_per_sec"],
            },
            "prefix_hit_rate": hit_rate,
            "prefix_events_delta": {"hit": hits, "miss": misses,
                                    "evict": ev1["evict"] - ev0["evict"]},
            "ttft_p50_win": round(
                cold_ttft["p50_ms"] / shared_ttft["p50_ms"], 3
            ) if shared_ttft["p50_ms"] else None,
        }
        print(f"prefix cache: hit rate {hit_rate}, ttft p50 "
              f"{cold_ttft['p50_ms']} -> {shared_ttft['p50_ms']} ms "
              f"(win {result['prefix_cache_c4']['ttft_p50_win']}x)",
              file=sys.stderr)

        # -- paged vs contiguous, same workload ----------------------------
        # Mirror the pre-rework baseline exactly: input 32 / output 16 /
        # c8 / 45 s window on this host. stepscope counters run through
        # this window to attribute per-phase overhead (PERF.md).
        _stepscope.configure(_stepscope.MODE_COUNTERS)
        _stepscope.reset()
        regress = GenAIPerf(
            server.grpc_address, model_name="gpt_engine",
            input_tokens=32, output_tokens=16,
            vocab_size=engine_model.cfg.vocab_size,
            measurement_interval_s=float(
                (contig or {}).get("interval_s", 45.0)),
            warmup_s=2.0,
        )
        reg_summary = regress.measure(8)
        phase_us = {}
        for rec in _stepscope.dump()["records"]:
            phase_us.setdefault(rec["phase"], []).append(rec["total_us"])
        _stepscope.configure(_stepscope.MODE_OFF)
        result["stepscope_per_phase_us"] = {
            phase: {
                "n": len(vals),
                "p50_us": sorted(vals)[len(vals) // 2],
                "mean_us": round(sum(vals) / len(vals), 1),
            }
            for phase, vals in sorted(phase_us.items())
        }
        result["paged_c8_contig_workload"] = {
            "input_tokens": 32, "output_tokens": 16,
            "requests": reg_summary["requests"],
            "errors": reg_summary["errors"],
            "output_token_throughput_per_sec": reg_summary[
                "output_token_throughput_per_sec"],
            "ttft_ms": reg_summary["time_to_first_token"],
            "itl_ms": reg_summary["inter_token_latency"],
        }
        if contig:
            result["contiguous_baseline_c8"] = contig
            base = contig["output_token_throughput_per_sec"]
            result["paged_vs_contiguous_c8"] = round(
                reg_summary["output_token_throughput_per_sec"] / base, 4
            )
            print(f"paged vs contiguous c8: "
                  f"{reg_summary['output_token_throughput_per_sec']} vs "
                  f"{base} tok/s "
                  f"({result['paged_vs_contiguous_c8']}x)", file=sys.stderr)

        # -- single-loop comparator ----------------------------------------
        loop_perf = GenAIPerf(
            server.grpc_address, model_name="gpt",
            input_tokens=32, output_tokens=out_tokens,
            vocab_size=engine_model.cfg.vocab_size,
            measurement_interval_s=90.0, warmup_s=2.0,
        )
        summary = loop_perf.measure(8)
        result["single_loop_c8"] = {
            "concurrency": 8,
            "requests": summary["requests"],
            "errors": summary["errors"],
            "output_token_throughput_per_sec": summary[
                "output_token_throughput_per_sec"],
            "ttft_ms": summary["time_to_first_token"],
            "itl_ms": summary["inter_token_latency"],
        }
        print(f"gpt (single loop) c8: "
              f"{summary['output_token_throughput_per_sec']} tok/s",
              file=sys.stderr)

    eng8 = result["engine"].get("c8", {})
    eng1 = result["engine"].get("c1", {})
    single = result["single_loop_c8"] or {}
    if single.get("output_token_throughput_per_sec"):
        result["engine_speedup_c8"] = round(
            eng8.get("output_token_throughput_per_sec", 0)
            / single["output_token_throughput_per_sec"], 2
        )
    # Gate (VERDICT r4 #4, extended for the paged round): the engine must
    # buy throughput WITHOUT selling TTFT — >= 1.3x single-loop token
    # throughput at c8 AND TTFT p99 at c8 <= 2.5x its own c1 value — and
    # the paged pool must hold >= 0.95x of the contiguous bank on the
    # same workload. genai_vs_baseline >= 1.0 means all hold; the min
    # names the binding constraint.
    ttft8 = (eng8.get("ttft_ms") or {}).get("p99_ms", 0)
    ttft1 = (eng1.get("ttft_ms") or {}).get("p99_ms", 0)
    if ttft1 and ttft8 and result.get("engine_speedup_c8"):
        result["ttft_p99_c8_over_c1"] = round(ttft8 / ttft1, 2)
        terms = [
            result["engine_speedup_c8"] / 1.3,
            2.5 / result["ttft_p99_c8_over_c1"],
        ]
        if result.get("paged_vs_contiguous_c8"):
            terms.append(result["paged_vs_contiguous_c8"] / 0.95)
        result["genai_vs_baseline"] = round(min(terms), 4)
    else:
        # A degenerate run (empty window, failed comparator) must read
        # as a FAILED gate, not an absent one.
        result["genai_vs_baseline"] = 0.0
        result["gate_inputs_missing"] = True
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        f"GENAI_r{rnd}.json",
    )
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    # Compact driver/judge-parseable line; the full detail is in the file.
    print(json.dumps({
        "metric": "gpt_engine_c8_token_throughput",
        "value": eng8.get("output_token_throughput_per_sec"),
        "unit": "tok/s",
        "engine_speedup_c8": result.get("engine_speedup_c8"),
        "ttft_p99_c8_over_c1": result.get("ttft_p99_c8_over_c1"),
        "paged_vs_contiguous_c8": result.get("paged_vs_contiguous_c8"),
        "prefix_hit_rate": result.get("prefix_cache_c4", {}).get(
            "prefix_hit_rate"),
        "genai_vs_baseline": result.get("genai_vs_baseline"),
        "detail_file": os.path.basename(path),
    }))


if __name__ == "__main__":
    main()

"""LLM serving-plane benchmark artifact (VERDICT r3 #6).

Drives the continuous-batching engine (models/gpt_engine.py) through the
full gRPC streaming stack with the genai_perf instrument and writes
GENAI_r{N}.json at the repo root: TTFT/ITL percentiles and token
throughput at concurrency {1, 4, 8}, plus the single-loop GptModel at
c=8 as the non-batched comparator (the engine's ~Nx token-throughput
claim, recorded instead of asserted).

Run on the TPU:  python scripts/genai_bench.py [round_number]
"""

import json
import os
import sys

sys.path.append(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.setswitchinterval(0.0002)


def main():
    rnd = sys.argv[1] if len(sys.argv) > 1 else os.environ.get("ROUND", "04")
    interval = float(os.environ.get("GENAI_SECONDS", "10"))
    out_tokens = int(os.environ.get("GENAI_OUTPUT_TOKENS", "16"))

    import jax

    from tritonclient_tpu.genai_perf import GenAIPerf
    from tritonclient_tpu.models.gpt import GptModel
    from tritonclient_tpu.models.gpt_engine import GptEngineModel
    from tritonclient_tpu.server import InferenceServer

    import numpy as np

    engine_model = GptEngineModel()
    loop_model = GptModel()
    engine_model.warmup()
    loop_model.warmup()
    # Warm the 32-token prefill bucket (the measured prompt length):
    # model.warmup() uses an 8-token prompt, and a first-use bucket
    # compile (~20-40 s through the tunnel) would eat the c=1 window.
    warm_prompt = np.ones((1, 32), np.int32)
    q = engine_model.engine.submit(warm_prompt, 2).out
    while True:
        tok = q.get(timeout=300)
        if tok is None:
            break
        if isinstance(tok, BaseException):
            raise tok  # surface warmup compile/engine errors immediately
    # Deterministically compile the vectorized admission ops for every
    # burst size k (a racy concurrent-submit warmup can skip
    # intermediate k values, leaving first-use compiles to land inside
    # a measured window).
    engine_model.engine.warm_admission()
    for tok in loop_model.infer(
        {"INPUT_IDS": warm_prompt, "MAX_TOKENS": np.array([2], np.int32)}
    ):
        pass

    result = {
        "round": rnd,
        "platform": jax.devices()[0].platform,
        "output_tokens": out_tokens,
        "engine": {},  # gpt_engine: continuous batching over the slot bank
        "single_loop_c8": None,  # GptModel: one generation loop per request
    }
    with InferenceServer(models=[engine_model, loop_model], http=False) as server:
        for model_name, levels, key in (
            ("gpt_engine", (1, 4, 8), "engine"),
            ("gpt", (8,), "single_loop_c8"),
        ):
            perf = GenAIPerf(
                server.grpc_address,
                model_name=model_name,
                input_tokens=32,
                output_tokens=out_tokens,
                vocab_size=engine_model.cfg.vocab_size,
                measurement_interval_s=interval,
                warmup_s=2.0,
            )
            for c in levels:
                if key == "engine" and c == 1:
                    # c1 is the TTFT gate's DENOMINATOR: at ~2 req/s a
                    # default window holds ~20 requests and its p99 is
                    # a coin flip. 3x the window stabilizes it.
                    perf.measurement_interval_s = interval * 3
                else:
                    perf.measurement_interval_s = interval
                summary = perf.measure(c)
                keep = {
                    "concurrency": c,
                    "requests": summary["requests"],
                    "errors": summary["errors"],
                    "output_token_throughput_per_sec": summary[
                        "output_token_throughput_per_sec"
                    ],
                    "ttft_ms": summary["time_to_first_token"],
                    "itl_ms": summary["inter_token_latency"],
                }
                if key == "engine":
                    result["engine"][f"c{c}"] = keep
                else:
                    result[key] = keep
                print(f"{model_name} c{c}: "
                      f"{keep['output_token_throughput_per_sec']} tok/s, "
                      f"ttft p99 {keep['ttft_ms'].get('p99_ms')} ms",
                      file=sys.stderr)
    eng8 = result["engine"].get("c8", {})
    eng1 = result["engine"].get("c1", {})
    single = result["single_loop_c8"] or {}
    if single.get("output_token_throughput_per_sec"):
        result["engine_speedup_c8"] = round(
            eng8.get("output_token_throughput_per_sec", 0)
            / single["output_token_throughput_per_sec"], 2
        )
    # Gate (VERDICT r4 #4): the engine must buy throughput WITHOUT
    # selling TTFT — >= 1.3x single-loop token throughput at c8 AND
    # TTFT p99 at c8 <= 2.5x its own c1 value. genai_vs_baseline >= 1.0
    # means both hold; the min names the binding constraint.
    ttft8 = (eng8.get("ttft_ms") or {}).get("p99_ms", 0)
    ttft1 = (eng1.get("ttft_ms") or {}).get("p99_ms", 0)
    if ttft1 and ttft8 and result.get("engine_speedup_c8"):
        result["ttft_p99_c8_over_c1"] = round(ttft8 / ttft1, 2)
        result["genai_vs_baseline"] = round(
            min(
                result["engine_speedup_c8"] / 1.3,
                2.5 / result["ttft_p99_c8_over_c1"],
            ), 4
        )
    else:
        # A degenerate run (empty window, failed comparator) must read
        # as a FAILED gate, not an absent one.
        result["genai_vs_baseline"] = 0.0
        result["gate_inputs_missing"] = True
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        f"GENAI_r{rnd}.json",
    )
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    # Compact driver/judge-parseable line; the full detail is in the file.
    print(json.dumps({
        "metric": "gpt_engine_c8_token_throughput",
        "value": eng8.get("output_token_throughput_per_sec"),
        "unit": "tok/s",
        "engine_speedup_c8": result.get("engine_speedup_c8"),
        "ttft_p99_c8_over_c1": result.get("ttft_p99_c8_over_c1"),
        "genai_vs_baseline": result.get("genai_vs_baseline"),
        "detail_file": os.path.basename(path),
    }))


if __name__ == "__main__":
    main()

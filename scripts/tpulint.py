#!/usr/bin/env python
"""tpulint launcher that works from a source checkout without installation.

Equivalent to ``python -m tritonclient_tpu.analysis`` with the repo root on
``sys.path``; see ``python scripts/tpulint.py --list-rules`` for the rule
table and the README "Static analysis" section for suppression syntax.
"""

import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from tritonclient_tpu.analysis import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())

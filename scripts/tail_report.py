#!/usr/bin/env python
"""What makes p99 p99: per-stage tail attribution from a flight-recorder
dump or trace files.

``trace_report.py`` summarizes *sampled* traces; this report answers the
tail question the admission-control work (ROADMAP item 1) needs evidence
for: which stage's time separates the slowest requests from typical
ones, and does the batcher backlog predict it. It consumes

* a flight-recorder dump (``GET v2/debug/flight_recorder`` /
  ``client.get_flight_recorder()`` saved to a file) — the primary input:
  tail-retained records with stage clocks and batcher context; or
* a merged *fleet* flight dump (``GET v2/fleet/debug/flight_recorder``
  on the router) — the same records replica-stamped and interleaved
  with the router's proxy spans, reported with per-replica attribution;
  or
* any ``trace_mode`` trace file (triton / otlp / perfetto, including
  perf_analyzer ``--trace-out`` merged files) — stages are re-derived
  from the span tree.

and reports:

* **per-stage share** of request time for requests at/above the tail
  quantile (default p95) vs at/below the head quantile (default p50),
  plus each stage's share of the tail *excess* (mean tail minus mean
  head) — the excess column names the dominant stage;
* **backlog correlation**: Pearson r between
  ``batcher.backlog_at_admission`` and request duration, with mean
  backlog in the tail vs head groups;
* **per-signature breakdown** (``batcher.signature``, falling back to
  the model name): count, p50/p99, tail share, mean backlog.

Usage::

    python scripts/tail_report.py DUMP_OR_TRACE_FILE [--json]
        [--tail-q 0.95] [--head-q 0.5] [--slowest N]
    python scripts/tail_report.py --self-check

``--self-check`` synthesizes a dump with a known dominant stage and a
seeded backlog/duration relationship, runs the full pipeline, and exits
non-zero unless the report recovers both — the CI smoke test for the
attribution path.
"""

import argparse
import json
import math
import os
import sys
from typing import Dict, List, Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from tritonclient_tpu import _otel  # noqa: E402
from tritonclient_tpu._tracing import STAGE_ORDER, stage_clocks  # noqa: E402

# Span-name -> stage-name mapping for trace-file inputs (the span tree
# has no ingress/batch-formation resolution; those stages exist only in
# flight-recorder dumps, which carry the raw stage clocks).
_SPAN_STAGES = {
    _otel.SPAN_QUEUE_WAIT: "queue-wait",
    _otel.SPAN_COMPUTE: "compute",
    _otel.SPAN_RESPONSE_MARSHAL: "response-marshal",
}


def _percentile(sorted_values, pct: float):
    if not sorted_values:
        return 0
    idx = min(
        len(sorted_values) - 1,
        math.ceil(pct / 100.0 * len(sorted_values)) - 1,
    )
    return sorted_values[max(idx, 0)]


# --------------------------------------------------------------------------- #
# loading                                                                     #
# --------------------------------------------------------------------------- #


def _record_from_flight(rec: dict) -> Optional[dict]:
    stages = rec.get("stages_us")
    if stages is None:
        ts = rec.get("timestamps") or {}
        stages = {k: v // 1000 for k, v in stage_clocks(ts).items()}
    duration = rec.get("duration_us")
    if duration is None:
        duration = sum(stages.values())
    attrs = rec.get("attributes") or {}
    return {
        "duration_us": int(duration),
        "stages_us": {k: int(v) for k, v in stages.items()},
        "model": rec.get("model_name", ""),
        "request_id": rec.get("request_id", ""),
        "status": rec.get("status", "ok"),
        "shed_reason": attrs.get("shed.reason"),
        "steps_completed": attrs.get("steps_completed"),
        "kv_pages_held": attrs.get("kv_pages_held"),
        "tenant": attrs.get("tenant"),
        "signature": attrs.get(
            "batcher.signature", rec.get("model_name", "") or "?"
        ),
        "backlog": attrs.get("batcher.backlog_at_admission"),
        "batch_size": attrs.get("batch.size"),
        # Fleet dumps stamp every record with the replica it came from
        # ("router" for the proxy half); single-node dumps leave it out.
        "replica": rec.get("replica"),
        "attributes": attrs,
    }


def _records_from_spans(spans: List[dict]) -> List[dict]:
    by_trace: Dict[str, List[dict]] = {}
    for span in spans:
        by_trace.setdefault(span["trace_id"], []).append(span)
    records = []
    for members in by_trace.values():
        handler = next(
            (m for m in members if m["name"] == _otel.SPAN_REQUEST_HANDLER),
            None,
        )
        if handler is None:
            continue
        stages: Dict[str, int] = {}
        attrs: Dict[str, object] = {}
        for m in members:
            stage = _SPAN_STAGES.get(m["name"])
            if stage is not None:
                stages[stage] = m["duration_ns"] // 1000
            for key, value in (m.get("attributes") or {}).items():
                attrs.setdefault(key, value)
        records.append({
            "duration_us": handler["duration_ns"] // 1000,
            "stages_us": stages,
            "model": attrs.get("model", attrs.get("model.name", "")),
            "request_id": attrs.get(
                "request_id", attrs.get("request.id", "")
            ),
            "status": attrs.get("flight.status", "ok"),
            "shed_reason": attrs.get("shed.reason"),
            "steps_completed": attrs.get("steps_completed"),
            "kv_pages_held": attrs.get("kv_pages_held"),
            "tenant": attrs.get("tenant"),
            "signature": attrs.get(
                "batcher.signature",
                attrs.get("model", attrs.get("model.name", "")) or "?",
            ),
            "backlog": attrs.get("batcher.backlog_at_admission"),
            "batch_size": attrs.get("batch.size"),
            "attributes": attrs,
        })
    return records


def load_records(path: str) -> List[dict]:
    """Normalize a flight dump or any trace-mode file to analysis records:
    {duration_us, stages_us, model, signature, backlog, status, ...}."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and doc.get("kind") in (
        "flight_recorder", "fleet_flight_recorder"
    ):
        out = [_record_from_flight(r) for r in doc.get("records", [])]
        return [r for r in out if r is not None]
    return _records_from_spans(_otel.load_spans(doc))


# --------------------------------------------------------------------------- #
# analysis                                                                    #
# --------------------------------------------------------------------------- #


def _stage_names(records: List[dict]) -> List[str]:
    seen = {s for r in records for s in r["stages_us"]}
    ordered = [s for s in STAGE_ORDER if s in seen]
    return ordered + sorted(seen - set(ordered))


def _group_stats(records: List[dict], stages: List[str]) -> dict:
    total = sum(r["duration_us"] for r in records)
    mean = total / len(records) if records else 0.0
    sums = {
        s: sum(r["stages_us"].get(s, 0) for r in records) for s in stages
    }
    staged = sum(sums.values())
    return {
        "count": len(records),
        "mean_us": round(mean, 1),
        "stage_mean_us": {
            s: round(sums[s] / len(records), 1) if records else 0.0
            for s in stages
        },
        # Share of the *staged* time (the clocks partition the request,
        # but partial records may miss stages; normalizing by the staged
        # sum keeps the shares summing to 1).
        "stage_share": {
            s: round(sums[s] / staged, 4) if staged else 0.0
            for s in stages
        },
    }


def _pearson(xs: List[float], ys: List[float]) -> Optional[float]:
    n = len(xs)
    if n < 3:
        return None
    mx, my = sum(xs) / n, sum(ys) / n
    cov = sum((x - mx) * (y - my) for x, y in zip(xs, ys))
    vx = sum((x - mx) ** 2 for x in xs)
    vy = sum((y - my) ** 2 for y in ys)
    if vx <= 0 or vy <= 0:
        return None
    return cov / math.sqrt(vx * vy)


def analyze(records: List[dict], tail_q: float = 0.95,
            head_q: float = 0.50) -> dict:
    """The attribution document: tail vs head stage shares, the dominant
    stage of the tail excess, backlog correlation, per-signature rows,
    and the shed-vs-served split.

    Shed requests (``shed.reason`` stamped by the batcher: admission /
    expired / cancelled) are summarized separately and EXCLUDED from the
    stage attribution — a sub-millisecond 504 carries no stage timeline
    and would dilute the head group the tail is compared against.
    """
    if not records:
        raise ValueError("no records to analyze")
    all_records = records
    sheds = [r for r in records if r.get("shed_reason")]
    records = [r for r in records if not r.get("shed_reason")] or records
    stages = _stage_names(records)
    durations = sorted(r["duration_us"] for r in records)
    tail_cut = _percentile(durations, tail_q * 100)
    head_cut = _percentile(durations, head_q * 100)
    tail = [r for r in records if r["duration_us"] >= tail_cut]
    head = [r for r in records if r["duration_us"] <= head_cut]
    tail_stats = _group_stats(tail, stages)
    head_stats = _group_stats(head, stages)

    # The tail *excess*: how much more of each stage a tail request pays
    # than a head request. Its largest positive component is the answer
    # to "what makes p99 p99".
    excess = {
        s: max(
            tail_stats["stage_mean_us"][s] - head_stats["stage_mean_us"][s],
            0.0,
        )
        for s in stages
    }
    excess_total = sum(excess.values())
    excess_share = {
        s: round(v / excess_total, 4) if excess_total else 0.0
        for s, v in excess.items()
    }
    dominant = (
        max(excess_share, key=lambda s: excess_share[s])
        if excess_total else None
    )

    # Backlog-depth correlation over every record that carries the
    # admission stamp.
    stamped = [r for r in records if r["backlog"] is not None]
    corr = _pearson(
        [float(r["backlog"]) for r in stamped],
        [float(r["duration_us"]) for r in stamped],
    )

    def mean_backlog(group):
        vals = [float(r["backlog"]) for r in group if r["backlog"] is not None]
        return round(sum(vals) / len(vals), 2) if vals else None

    # Per-signature rows: the router/admission work consumes these.
    by_sig: Dict[str, List[dict]] = {}
    for r in records:
        by_sig.setdefault(str(r["signature"]), []).append(r)
    tail_ids = {id(r) for r in tail}
    signatures = []
    for sig, members in sorted(by_sig.items(),
                               key=lambda kv: -len(kv[1])):
        ds = sorted(m["duration_us"] for m in members)
        signatures.append({
            "signature": sig,
            "model": members[0]["model"],
            "count": len(members),
            "p50_us": _percentile(ds, 50),
            "p99_us": _percentile(ds, 99),
            "tail_count": sum(1 for m in members if id(m) in tail_ids),
            "mean_backlog": mean_backlog(members),
        })

    # Per-tenant rows (records carrying the fleet tenant stamp): a
    # fairness regression attributes to a TENANT, not just a signature —
    # served latency split per tenant, sheds counted beside it.
    by_tenant: Dict[str, List[dict]] = {}
    for r in all_records:
        if r.get("tenant"):
            by_tenant.setdefault(str(r["tenant"]), []).append(r)
    tenants = []
    for tenant, members in sorted(by_tenant.items(),
                                  key=lambda kv: -len(kv[1])):
        served = [m for m in members if not m.get("shed_reason")]
        ds = sorted(m["duration_us"] for m in served)
        tenants.append({
            "tenant": tenant,
            "count": len(members),
            "served": len(served),
            "shed": len(members) - len(served),
            "p50_us": _percentile(ds, 50),
            "p99_us": _percentile(ds, 99),
            "tail_count": sum(
                1 for m in served if id(m) in tail_ids
            ),
            "mean_backlog": mean_backlog(served),
        })

    # Per-replica rows (fleet dumps stamp each record with its source):
    # a divergent replica shows up as an outsized tail_count or error
    # count relative to its share of traffic.
    by_replica: Dict[str, List[dict]] = {}
    for r in all_records:
        if r.get("replica"):
            by_replica.setdefault(str(r["replica"]), []).append(r)
    replica_rows = []
    for replica, members in sorted(by_replica.items(),
                                   key=lambda kv: -len(kv[1])):
        served = [m for m in members if not m.get("shed_reason")]
        ds = sorted(m["duration_us"] for m in served)
        replica_rows.append({
            "replica": replica,
            "count": len(members),
            "errors": sum(1 for m in members if m["status"] != "ok"),
            "p50_us": _percentile(ds, 50),
            "p99_us": _percentile(ds, 99),
            "tail_count": sum(1 for m in served if id(m) in tail_ids),
        })

    shed_lat = sorted(r["duration_us"] for r in sheds)
    # Where in the decode loop cancelled requests died (steps_completed
    # stamped at shed/cancel finalization; engine models count delivered
    # tokens, batcher models stamp 0).
    shed_steps = sorted(
        int(r["steps_completed"]) for r in sheds
        if r.get("steps_completed") is not None
    )
    # KV pages the shed requests were holding when they died
    # (kv_pages_held stamped beside steps_completed): a nonzero p50
    # means cancellations are releasing real pool memory — the memory
    # column of the shed analysis.
    shed_pages = sorted(
        int(r["kv_pages_held"]) for r in sheds
        if r.get("kv_pages_held") is not None
    )
    return {
        "records": len(all_records),
        "statuses": {
            status: sum(1 for r in all_records if r["status"] == status)
            for status in sorted({r["status"] for r in all_records})
        },
        # Shed-vs-served: how much of the offered tail was answered with
        # a fast 504 instead of being served late.
        "sheds": {
            "count": len(sheds),
            "served": len(all_records) - len(sheds),
            "by_reason": {
                reason: sum(
                    1 for r in sheds if r["shed_reason"] == reason
                )
                for reason in sorted({r["shed_reason"] for r in sheds})
            },
            "shed_p99_us": _percentile(shed_lat, 99),
            "steps_completed": {
                "stamped": len(shed_steps),
                "p50": _percentile(shed_steps, 50),
                "max": shed_steps[-1] if shed_steps else 0,
            },
            "kv_pages_held": {
                "stamped": len(shed_pages),
                "p50": _percentile(shed_pages, 50),
                "max": shed_pages[-1] if shed_pages else 0,
            },
        },
        "tail_q": tail_q,
        "head_q": head_q,
        "tail_cut_us": tail_cut,
        "head_cut_us": head_cut,
        "tail": tail_stats,
        "head": head_stats,
        "excess_us": {s: round(v, 1) for s, v in excess.items()},
        "excess_share": excess_share,
        "dominant_stage": dominant,
        "backlog": {
            "stamped": len(stamped),
            "pearson_r": round(corr, 4) if corr is not None else None,
            "tail_mean": mean_backlog(tail),
            "head_mean": mean_backlog(head),
        },
        "signatures": signatures,
        "tenants": tenants,
        "replicas": replica_rows,
    }


# --------------------------------------------------------------------------- #
# rendering                                                                   #
# --------------------------------------------------------------------------- #


def render(result: dict, slowest: List[dict]) -> str:
    lines = [
        f"{result['records']} records "
        f"({', '.join(f'{k}={v}' for k, v in result['statuses'].items())}); "
        f"tail >= p{result['tail_q'] * 100:g} ({result['tail_cut_us']} us), "
        f"head <= p{result['head_q'] * 100:g} ({result['head_cut_us']} us)"
    ]
    stages = list(result["excess_share"])
    lines.append("")
    lines.append(
        f"{'stage':<18} {'tail_mean':>10} {'head_mean':>10} "
        f"{'tail_share':>10} {'excess_share':>13}"
    )
    for s in stages:
        lines.append(
            f"{s:<18} {result['tail']['stage_mean_us'][s]:>10} "
            f"{result['head']['stage_mean_us'][s]:>10} "
            f"{result['tail']['stage_share'][s]:>10.1%} "
            f"{result['excess_share'][s]:>13.1%}"
        )
    dom = result["dominant_stage"]
    lines.append("")
    lines.append(
        f"dominant tail stage: {dom or '(no excess — tail == head)'}"
    )
    sheds = result.get("sheds") or {}
    if sheds.get("count"):
        reasons = ", ".join(
            f"{k}={v}" for k, v in sheds["by_reason"].items()
        )
        lines.append(
            f"shed vs served: {sheds['count']} shed ({reasons}, "
            f"p99 {sheds['shed_p99_us']} us) / {sheds['served']} served "
            "— stage attribution above covers served requests only"
        )
        steps = sheds.get("steps_completed") or {}
        if steps.get("stamped"):
            lines.append(
                f"  died in the decode loop: {steps['stamped']} stamped, "
                f"steps completed p50={steps['p50']} max={steps['max']} "
                "(0 = shed before the first token)"
            )
        pages = sheds.get("kv_pages_held") or {}
        if pages.get("stamped"):
            lines.append(
                f"  memory held at death: {pages['stamped']} stamped, "
                f"kv pages p50={pages['p50']} max={pages['max']} "
                "(0 = never reserved pool pages)"
            )
    b = result["backlog"]
    if b["stamped"]:
        r_txt = "n/a" if b["pearson_r"] is None else f"{b['pearson_r']:+.3f}"
        lines.append(
            f"backlog at admission: pearson r={r_txt} over {b['stamped']} "
            f"stamped records; tail mean={b['tail_mean']} "
            f"head mean={b['head_mean']}"
        )
    else:
        lines.append("backlog at admission: no stamped records")
    lines.append("")
    lines.append(
        f"{'signature':<44} {'count':>6} {'p50_us':>8} {'p99_us':>9} "
        f"{'tail':>5} {'backlog':>8}"
    )
    for row in result["signatures"][:10]:
        sig = row["signature"]
        if len(sig) > 43:
            sig = sig[:40] + "..."
        lines.append(
            f"{sig:<44} {row['count']:>6} {row['p50_us']:>8} "
            f"{row['p99_us']:>9} {row['tail_count']:>5} "
            f"{row['mean_backlog'] if row['mean_backlog'] is not None else '-':>8}"
        )
    if result.get("tenants"):
        lines.append("")
        lines.append(
            f"{'tenant':<24} {'count':>6} {'served':>7} {'shed':>5} "
            f"{'p50_us':>8} {'p99_us':>9} {'tail':>5}"
        )
        for row in result["tenants"][:10]:
            tenant = row["tenant"]
            if len(tenant) > 23:
                tenant = tenant[:20] + "..."
            lines.append(
                f"{tenant:<24} {row['count']:>6} {row['served']:>7} "
                f"{row['shed']:>5} {row['p50_us']:>8} {row['p99_us']:>9} "
                f"{row['tail_count']:>5}"
            )
    if result.get("replicas"):
        lines.append("")
        lines.append(
            f"{'replica':<24} {'count':>6} {'errors':>7} "
            f"{'p50_us':>8} {'p99_us':>9} {'tail':>5}"
        )
        for row in result["replicas"][:10]:
            replica = row["replica"]
            if len(replica) > 23:
                replica = replica[:20] + "..."
            lines.append(
                f"{replica:<24} {row['count']:>6} {row['errors']:>7} "
                f"{row['p50_us']:>8} {row['p99_us']:>9} "
                f"{row['tail_count']:>5}"
            )
    if slowest:
        lines.append("")
        lines.append(f"slowest {len(slowest)} record(s):")
        for r in slowest:
            stack = ", ".join(
                f"{k}={v}us" for k, v in r["stages_us"].items()
            )
            label = r["model"] or "?"
            if r["request_id"]:
                label += f" id={r['request_id']}"
            lines.append(
                f"  {r['duration_us']} us [{label}] ({r['status']}) {stack}"
            )
    return "\n".join(lines)


# --------------------------------------------------------------------------- #
# self-check                                                                  #
# --------------------------------------------------------------------------- #


def _synthetic_dump(n: int = 400, slow: int = 20) -> dict:
    """A dump whose tail is queue-wait-dominated by construction and whose
    backlog rises with duration — the known answer the self-check asserts.
    Deterministic (no RNG): the check must not flake."""
    records = []
    base = 1_000_000_000
    for i in range(n):
        is_slow = i < slow
        queue_us = 60_000 + 2_000 * i if is_slow else 200 + (i % 50)
        compute_us = 2_000 + (i % 100)
        recv = base + i * 10_000_000
        ts = {
            "REQUEST_RECV": recv,
            "QUEUE_START": recv + 50_000,
            "BATCH_FORM": recv + 50_000 + queue_us * 1000,
            "COMPUTE_INPUT": recv + 55_000 + queue_us * 1000,
            "COMPUTE_INFER": recv + 100_000 + queue_us * 1000,
            "COMPUTE_OUTPUT": recv + 100_000 + (queue_us + compute_us) * 1000,
            "RESPONSE_SEND": recv + 200_000 + (queue_us + compute_us) * 1000,
        }
        duration_ns = ts["RESPONSE_SEND"] - ts["REQUEST_RECV"]
        records.append({
            "seq": i,
            "model_name": "synthetic",
            "model_version": "1",
            "request_id": f"r{i}",
            "trace_id": "",
            "parent_span_id": "",
            "duration_us": duration_ns // 1000,
            "status": "ok",
            "error": None,
            "stages_us": {
                k: v // 1000 for k, v in stage_clocks(ts).items()
            },
            "timestamps": ts,
            "attributes": {
                # Backlog tracks queue time: the correlation the report
                # must recover.
                "batcher.backlog_at_admission": queue_us // 2_000,
                "batcher.signature": (
                    "('INPUT', 'INT32', (16,))" if i % 3 else
                    "('INPUT', 'FP32', (16,))"
                ),
                "batch.size": 4,
            },
            "wall_time_s": 0.0,
        })
    return {
        "kind": "flight_recorder",
        "config": {"slowest_k": slow, "window_s": 10.0, "windows": 6,
                   "max_errors": 256, "enabled": True},
        "counters": {"offered": n, "retained_slow": slow, "errors": 0,
                     "deadline_misses": 0},
        "records": records,
    }


def self_check() -> int:
    import tempfile

    failures = 0
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "flight.json")
        with open(path, "w") as f:
            json.dump(_synthetic_dump(), f)
        records = load_records(path)
        if len(records) != 400:
            print(f"self-check: loaded {len(records)} records != 400",
                  file=sys.stderr)
            failures += 1
        result = analyze(records)
        if result["dominant_stage"] != "queue-wait":
            print(
                "self-check: dominant stage "
                f"{result['dominant_stage']!r} != 'queue-wait' "
                f"(excess_share={result['excess_share']})",
                file=sys.stderr,
            )
            failures += 1
        if result["excess_share"].get("queue-wait", 0) < 0.9:
            print(
                "self-check: queue-wait excess share "
                f"{result['excess_share']} < 0.9",
                file=sys.stderr,
            )
            failures += 1
        r = result["backlog"]["pearson_r"]
        if r is None or r < 0.8:
            print(f"self-check: backlog correlation {r} < 0.8",
                  file=sys.stderr)
            failures += 1
        if len(result["signatures"]) != 2:
            print(
                f"self-check: {len(result['signatures'])} signatures != 2",
                file=sys.stderr,
            )
            failures += 1
        render(result, records[:3])  # must not raise
        # The trace-file path must agree on the dominant stage: export the
        # same timeline through the triton exporter and re-analyze.
        trace_path = os.path.join(tmp, "trace.json")
        trace_doc = []
        for rec in _synthetic_dump()["records"]:
            trace_doc.append({
                "id": rec["seq"],
                "model_name": rec["model_name"],
                "model_version": "1",
                "request_id": rec["request_id"],
                "trace_id": _otel.new_trace_id(),
                "parent_span_id": "",
                "timestamps": [
                    {"name": k, "ns": v}
                    for k, v in rec["timestamps"].items()
                    if k in _otel.TIMESTAMP_ORDER
                ],
                "attributes": rec["attributes"],
            })
        with open(trace_path, "w") as f:
            json.dump(trace_doc, f)
        t_result = analyze(load_records(trace_path))
        if t_result["dominant_stage"] != "queue-wait":
            print(
                "self-check [trace path]: dominant stage "
                f"{t_result['dominant_stage']!r} != 'queue-wait'",
                file=sys.stderr,
            )
            failures += 1
        # Shed rows carry steps_completed (stamped at shed/cancel
        # finalization): the report must surface where in the decode loop
        # cancelled requests died.
        shed_doc = _synthetic_dump(n=40, slow=4)
        for i, (steps, pages) in enumerate(
            zip([0, 2, 5, 9], [0, 1, 3, 7])
        ):
            rec = shed_doc["records"][i]
            rec["status"] = "cancel"
            rec["attributes"]["shed.reason"] = (
                "cancelled" if steps else "admission"
            )
            rec["attributes"]["steps_completed"] = steps
            rec["attributes"]["kv_pages_held"] = pages
        shed_path = os.path.join(tmp, "shed.json")
        with open(shed_path, "w") as f:
            json.dump(shed_doc, f)
        s_result = analyze(load_records(shed_path))
        got_steps = s_result["sheds"].get("steps_completed") or {}
        if got_steps != {"stamped": 4, "p50": 2, "max": 9}:
            print(f"self-check [shed steps]: {got_steps} != "
                  "{'stamped': 4, 'p50': 2, 'max': 9}", file=sys.stderr)
            failures += 1
        elif "died in the decode loop" not in render(s_result, []):
            print("self-check [shed steps]: steps_completed line missing "
                  "from render", file=sys.stderr)
            failures += 1
        got_pages = s_result["sheds"].get("kv_pages_held") or {}
        if got_pages != {"stamped": 4, "p50": 1, "max": 7}:
            print(f"self-check [shed pages]: {got_pages} != "
                  "{'stamped': 4, 'p50': 1, 'max': 7}", file=sys.stderr)
            failures += 1
        elif "memory held at death" not in render(s_result, []):
            print("self-check [shed pages]: kv_pages_held line missing "
                  "from render", file=sys.stderr)
            failures += 1
        # Fleet dumps: replica-stamped records (plus the router's proxy
        # spans) must load and produce per-replica attribution rows.
        fleet_doc = _synthetic_dump(n=60, slow=6)
        fleet_doc["kind"] = "fleet_flight_recorder"
        fleet_doc["replicas"] = ["r0", "r1"]
        fleet_doc["unreachable"] = {}
        for i, rec in enumerate(fleet_doc["records"]):
            rec["replica"] = "r0" if i % 2 else "r1"
        fleet_doc["records"].append({
            "seq": 10_000,
            "model_name": "synthetic",
            "duration_us": 70_000,
            "status": "ok",
            "stages_us": {"proxy": 70_000},
            "timestamps": {},
            "attributes": {"tenant": "acme", "fleet.replica": "r0"},
            "replica": "router",
        })
        fleet_path = os.path.join(tmp, "fleet.json")
        with open(fleet_path, "w") as f:
            json.dump(fleet_doc, f)
        f_result = analyze(load_records(fleet_path))
        got = {row["replica"]: row["count"] for row in f_result["replicas"]}
        if got != {"r0": 30, "r1": 30, "router": 1}:
            print(f"self-check [fleet dump]: replica rows {got} != "
                  "{'r0': 30, 'r1': 30, 'router': 1}", file=sys.stderr)
            failures += 1
        elif "router" not in render(f_result, []):
            print("self-check [fleet dump]: replica table missing from "
                  "render", file=sys.stderr)
            failures += 1
    if failures:
        print(f"self-check: {failures} failure(s)", file=sys.stderr)
        return 1
    print("self-check: attribution recovers the seeded dominant stage, "
          "backlog correlation, and signature split")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="tail_report",
        description="Per-stage tail attribution from a flight-recorder "
        "dump or trace file",
    )
    parser.add_argument("dump_file", nargs="?",
                        help="flight-recorder dump or trace_mode file")
    parser.add_argument("--tail-q", type=float, default=0.95,
                        help="tail quantile cut (default 0.95)")
    parser.add_argument("--head-q", type=float, default=0.5,
                        help="head quantile cut (default 0.5)")
    parser.add_argument("--slowest", type=int, default=5, metavar="N",
                        help="how many slowest records to list (default 5)")
    parser.add_argument("--json", dest="as_json", action="store_true",
                        help="emit the report as JSON")
    parser.add_argument("--self-check", action="store_true",
                        help="run the synthetic-dump round trip and exit")
    args = parser.parse_args(argv)
    if args.self_check:
        return self_check()
    if not args.dump_file:
        parser.error("a dump/trace file is required (or --self-check)")
    try:
        records = load_records(args.dump_file)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"unable to load {args.dump_file}: {e}", file=sys.stderr)
        return 1
    if not records:
        print(f"{args.dump_file}: no records", file=sys.stderr)
        return 1
    result = analyze(records, tail_q=args.tail_q, head_q=args.head_q)
    slowest = sorted(
        records, key=lambda r: r["duration_us"], reverse=True
    )[:args.slowest]
    try:
        if args.as_json:
            print(json.dumps(
                {"analysis": result, "slowest": slowest}, indent=2,
                default=str,
            ))
        else:
            print(render(result, slowest))
    except BrokenPipeError:
        return 0
    return 0


if __name__ == "__main__":
    sys.exit(main())

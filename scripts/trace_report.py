#!/usr/bin/env python
"""Per-span latency breakdown for a trace file written by the server.

Loads any of the three ``trace_mode`` exporter formats — ``triton``
(Triton-shaped JSON array), ``otlp`` (OTLP/JSON), or ``perfetto``
(Chrome trace-event JSON, including perf_analyzer ``--trace-out`` merged
files) — normalizes them to one span list, and prints:

* per-span-name latency percentiles (count, p50/p95/p99/max, in us);
* the N slowest traces (root-span duration), with their span stack.

Usage::

    python scripts/trace_report.py TRACE_FILE [--slowest N] [--json]
    python scripts/trace_report.py --self-check

``--self-check`` synthesizes a trace, round-trips it through every
exporter and this loader, and exits non-zero on any disagreement — the CI
smoke test for the whole exporter/loader pipeline.
"""

import argparse
import json
import os
import sys
import tempfile
from typing import Dict, List

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from tritonclient_tpu import _otel  # noqa: E402


def _percentile(sorted_values: List[int], pct: float) -> int:
    if not sorted_values:
        return 0
    import math

    idx = min(
        len(sorted_values) - 1,
        math.ceil(pct / 100.0 * len(sorted_values)) - 1,
    )
    return sorted_values[max(idx, 0)]


def breakdown(spans: List[dict]) -> List[dict]:
    """Per-span-name duration stats, slowest-p99 first."""
    by_name: Dict[str, List[int]] = {}
    for span in spans:
        by_name.setdefault(span.get("name", ""), []).append(
            int(span.get("duration_ns", 0))
        )
    rows = []
    for name, durations in by_name.items():
        durations.sort()
        rows.append({
            "span": name,
            "count": len(durations),
            "p50_us": _percentile(durations, 50) // 1000,
            "p95_us": _percentile(durations, 95) // 1000,
            "p99_us": _percentile(durations, 99) // 1000,
            "max_us": durations[-1] // 1000,
        })
    rows.sort(key=lambda r: r["p99_us"], reverse=True)
    return rows


def slowest_traces(spans: List[dict], n: int) -> List[dict]:
    """Traces ranked by root-span duration (falling back to the trace's
    span envelope when no parentless span was captured)."""
    by_trace: Dict[str, List[dict]] = {}
    for span in spans:
        by_trace.setdefault(span.get("trace_id", ""), []).append(span)
    ranked = []
    for trace_id, members in by_trace.items():
        # Defensive .get() throughout: thread-scoped tracks (stepscope
        # engine steps, foreign tool output) are legal input — their
        # events carry no span/parent ids, and a missing key must read
        # as "orphan", not crash the parent lookup.
        ids = {m.get("span_id", "") for m in members} - {""}
        roots = [m for m in members
                 if m.get("parent_span_id", "") not in ids]
        duration = (
            max(int(m.get("duration_ns", 0)) for m in roots)
            if roots
            else max(int(m.get("end_ns", 0)) for m in members)
            - min(int(m.get("start_ns", 0)) for m in members)
        )
        attrs: Dict[str, str] = {}
        for m in members:  # client spans carry no model/request id
            for key, value in (m.get("attributes") or {}).items():
                attrs.setdefault(key, value)
        ranked.append({
            "trace_id": trace_id,
            "duration_us": duration // 1000,
            "spans": {
                m.get("name", ""): int(m.get("duration_ns", 0)) // 1000
                for m in sorted(members,
                                key=lambda m: int(m.get("start_ns", 0)))
            },
            "model": attrs.get("model", attrs.get("model.name", "")),
            "request_id": attrs.get("request_id", attrs.get("request.id", "")),
        })
    ranked.sort(key=lambda t: t["duration_us"], reverse=True)
    return ranked[:n]


def report(spans: List[dict], slowest: int, as_json: bool) -> str:
    rows = breakdown(spans)
    worst = slowest_traces(spans, slowest)
    if as_json:
        return json.dumps({"breakdown": rows, "slowest": worst}, indent=2)
    n_traces = len({s.get("trace_id", "") for s in spans})
    lines = [f"{len(spans)} spans, {n_traces} traces"]
    lines.append(
        f"{'span':<18} {'count':>6} {'p50_us':>8} {'p95_us':>8} "
        f"{'p99_us':>8} {'max_us':>8}"
    )
    for r in rows:
        lines.append(
            f"{r['span']:<18} {r['count']:>6} {r['p50_us']:>8} "
            f"{r['p95_us']:>8} {r['p99_us']:>8} {r['max_us']:>8}"
        )
    if worst:
        lines.append("")
        lines.append(f"slowest {len(worst)} trace(s):")
        for t in worst:
            label = t["model"] or "?"
            if t["request_id"]:
                label += f" id={t['request_id']}"
            stack = ", ".join(
                f"{name}={us}us" for name, us in t["spans"].items()
            )
            lines.append(
                f"  {t['trace_id'][:16]}… {t['duration_us']} us "
                f"[{label}] {stack}"
            )
    return "\n".join(lines)


def self_check() -> int:
    """Round-trip a synthetic trace through every exporter and the loader."""
    base = 1_000_000_000
    timestamps = {
        "REQUEST_RECV": base,
        "QUEUE_START": base + 100_000,
        "COMPUTE_INPUT": base + 400_000,
        "COMPUTE_INFER": base + 500_000,
        "COMPUTE_OUTPUT": base + 2_400_000,
        "RESPONSE_SEND": base + 2_600_000,
    }
    trace_id, parent = _otel.new_trace_id(), _otel.new_span_id()
    record = _otel.TraceRecord(
        seq_id=1, model_name="selfcheck", model_version="1",
        request_id="sc-1", trace_id=trace_id, parent_span_id=parent,
        spans=_otel.build_span_tree(
            trace_id, parent, timestamps, {"batch.id": 7}
        ),
        timestamps=timestamps,
    )
    expected = {
        ("request-handler", 2_600_000),
        ("batch-queue-wait", 300_000),
        ("compute", 2_000_000),
        ("response-marshal", 200_000),
    }
    failures = 0
    with tempfile.TemporaryDirectory() as tmp:
        for mode in _otel.TRACE_MODES:
            path = os.path.join(tmp, f"trace.{mode}.json")
            with open(path, "w") as f:
                f.write(_otel.render_trace_file(mode, [record], epoch_ns=0))
            json.load(open(path))  # every exporter's output is valid JSON
            spans = _otel.load_trace_file(path)
            got = {(s["name"], s["duration_ns"]) for s in spans}
            if got != expected:
                print(f"self-check [{mode}]: spans {got} != {expected}",
                      file=sys.stderr)
                failures += 1
                continue
            ids = {s["trace_id"] for s in spans}
            if ids != {trace_id}:
                print(f"self-check [{mode}]: trace id not preserved: {ids}",
                      file=sys.stderr)
                failures += 1
                continue
            handlers = [s for s in spans if s["name"] == "request-handler"]
            if mode != "triton" and handlers[0]["parent_span_id"] != parent:
                # (The triton loader re-derives the tree, so only the
                # span-native formats must preserve the inbound parent.)
                print(f"self-check [{mode}]: parent span id lost",
                      file=sys.stderr)
                failures += 1
                continue
            report(spans, slowest=1, as_json=False)  # must not raise
            print(f"self-check [{mode}]: ok")
    failures += _self_check_orphan_tracks()
    if failures:
        print(f"self-check: {failures} failure(s)", file=sys.stderr)
        return 1
    print("self-check: all exporters round-trip")
    return 0


def _self_check_orphan_tracks() -> int:
    """Perfetto files may carry thread-scoped tracks with no request
    parent (stepscope engine-step tracks; foreign tool output). They must
    load with per-track identity — not collapse into one '' trace — and
    the report must render them without a parent lookup crash."""
    doc = {
        "displayTimeUnit": "ns",
        "traceEvents": [
            # Metadata events are not spans and must be skipped.
            {"name": "thread_name", "ph": "M", "pid": 7, "tid": 42,
             "args": {"name": "stepscope:gpt-engine"}},
            {"name": "gpt_engine/decode[0]", "cat": "stepscope",
             "ph": "X", "ts": 1000.0, "dur": 250.0, "pid": 7, "tid": 42,
             "args": {"phase": "decode", "dispatch_us": "80"}},
            {"name": "gpt_engine/decode[1]", "cat": "stepscope",
             "ph": "X", "ts": 1300.0, "dur": 200.0, "pid": 7, "tid": 42,
             "args": {"phase": "decode"}},
            # A second thread's track, and one event with no args at all.
            {"name": "gpt_engine/prefill[0]", "cat": "stepscope",
             "ph": "X", "ts": 900.0, "dur": 400.0, "pid": 7, "tid": 43,
             "args": {}},
            {"name": "bare", "ph": "X", "ts": 2000.0, "dur": 10.0,
             "pid": 7, "tid": 44},
            # A request-level span in the same file keeps its identity.
            {"name": "request-handler", "cat": "server", "ph": "X",
             "ts": 500.0, "dur": 3000.0, "pid": 7, "tid": 1,
             "args": {"trace_id": "t-req", "span_id": "s1",
                      "parent_span_id": ""}},
        ],
    }
    try:
        spans = _otel.load_spans(doc)
        got_traces = {s["trace_id"] for s in spans}
        want = {"track-7-42", "track-7-43", "track-7-44", "t-req"}
        if got_traces != want:
            print(f"self-check [orphan]: trace grouping {got_traces} != "
                  f"{want}", file=sys.stderr)
            return 1
        rendered = report(spans, slowest=10, as_json=False)
        if "gpt_engine/decode[0]" not in rendered:
            print("self-check [orphan]: orphan span missing from report",
                  file=sys.stderr)
            return 1
        ranked = slowest_traces(spans, 10)
        if len(ranked) != 4:
            print(f"self-check [orphan]: expected 4 traces, got "
                  f"{len(ranked)}", file=sys.stderr)
            return 1
    except Exception as e:  # the crash this case exists to prevent
        print(f"self-check [orphan]: raised {type(e).__name__}: {e}",
              file=sys.stderr)
        return 1
    print("self-check [orphan-tracks]: ok")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="trace_report",
        description="Per-span latency breakdown for server trace files",
    )
    parser.add_argument("trace_file", nargs="?",
                        help="trace file in any trace_mode format")
    parser.add_argument("--slowest", type=int, default=5, metavar="N",
                        help="how many slowest traces to list (default 5)")
    parser.add_argument("--json", dest="as_json", action="store_true",
                        help="emit the report as JSON")
    parser.add_argument("--self-check", action="store_true",
                        help="round-trip every exporter format and exit")
    args = parser.parse_args(argv)
    if args.self_check:
        return self_check()
    if not args.trace_file:
        parser.error("a trace file is required (or --self-check)")
    try:
        spans = _otel.load_trace_file(args.trace_file)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"unable to load {args.trace_file}: {e}", file=sys.stderr)
        return 1
    if not spans:
        print(f"{args.trace_file}: no spans", file=sys.stderr)
        return 1
    try:
        print(report(spans, args.slowest, args.as_json))
    except BrokenPipeError:  # e.g. piped into head
        return 0
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Diff tpulint's static picture against a tpusan runtime report.

Closing the static/dynamic loop needs an answer to three questions per
paired rule (TPU001 async-blocking, TPU006 shm-lifecycle, TPU007
lock-order, TPU009 guarded-by — the Eraser lockset witness, TPU011
condvar discipline — witnessed by the tpumc schedule explorer rather
than the passive sanitizer; TPU015 donation discipline, TPU016 sharding
drift, and TPU017 bucket discipline — witnessed by the ``sanitize/_jax``
donation poisoner, transfer guard, and compile-cache watcher; TPU010 is
diffed too, static-only, so its hot-path findings appear in the
unexercised column rather than vanishing from the report):

* **witnessed** — statically flagged AND observed at runtime: the static
  finding is real and the suite exercises it (these should be zero on a
  fixed tree; anything here is an unfixed true positive).
* **unexercised** — statically flagged, never observed: either a
  suppressed/baselined deliberate violation, or a COVERAGE GAP — the
  suite never drives that path (deliberate test sleeps land here).
* **unpredicted** — observed at runtime with no static counterpart in
  the same file: a RULE GAP. File each as a new lint fixture (the seeded
  violations in tests/test_tpusan.py are the canonical examples: runtime
  constructions the AST rules cannot see).

Usage:
    python scripts/tpusan_report.py --dynamic tpusan.json [paths...]
    python scripts/tpusan_report.py --dynamic tpusan.sarif --rules TPU006

``--dynamic`` takes the file ``TPUSAN_REPORT`` wrote (JSON or SARIF) or
a tpumc report (``scripts/tpumc.py --json``/``--sarif`` — a list of
per-harness results whose findings then witness TPU007/TPU009/TPU011);
pass it repeatedly to merge sanitizer and model-checker evidence.
Static findings come from running tpulint in-process over ``paths``
(default: tritonclient_tpu scripts tests) WITHOUT baseline filtering —
the diff wants the complete static picture. Matching is by (rule, file):
line-level matching would break whenever an unrelated edit shifts code,
exactly what the fingerprint machinery avoids.

Exit status: 0 always unless ``--fail-on-witnessed`` is given and a
witnessed pair exists (the CI lane's gate: a statically-known violation
the suite can reproduce must not survive).
"""

import argparse
import json
import os
import sys
from collections import defaultdict

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

DEFAULT_RULES = ("TPU001", "TPU006", "TPU007", "TPU009", "TPU010",
                 "TPU011", "TPU013", "TPU015", "TPU016", "TPU017")


def load_dynamic(path: str):
    if path.endswith(".sarif"):
        from tritonclient_tpu.analysis._sarif import load_sarif_findings

        return load_sarif_findings(path)
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if isinstance(doc, list):
        # tpumc --json: a list of per-harness ExploreResult dicts.
        return [f for r in doc for f in r.get("findings", [])]
    return list(doc.get("findings", []))


def run_static(paths, rules):
    from tritonclient_tpu.analysis import run_analysis

    findings, _ = run_analysis(paths, select=set(rules))
    return [
        {"rule": f.rule, "path": f.path, "line": f.line, "message": f.message}
        for f in findings
    ]


def classify(static, dynamic):
    """Split into (witnessed, unexercised, unpredicted) by (rule, file).

    witnessed: [(static_finding, [runtime records])]; unexercised:
    static-only; unpredicted: runtime-only. Line-level matching is
    deliberately avoided — see the module docstring.
    """
    dyn_by_key = defaultdict(list)
    for f in dynamic:
        dyn_by_key[(f["rule"], f["path"])].append(f)

    witnessed, unexercised = [], []
    matched_keys = set()
    for f in static:
        key = (f["rule"], f["path"])
        if dyn_by_key.get(key):
            witnessed.append((f, dyn_by_key[key]))
            matched_keys.add(key)
        else:
            unexercised.append(f)
    unpredicted = [
        f for key, fs in sorted(dyn_by_key.items())
        if key not in matched_keys for f in fs
    ]
    return witnessed, unexercised, unpredicted


def self_check() -> int:
    """Synthetic records with a known classification through all three
    columns — the TPU009 pair mirrors what a real run produces: the
    static guarded-by finding in a file plus the runtime empty-lockset
    record from the same file."""
    static = [
        {"rule": "TPU009", "path": "pkg/a.py", "line": 10,
         "message": "unguarded write to `self.count` (inferred guard "
         "'A._lock')"},
        {"rule": "TPU010", "path": "pkg/b.py", "line": 20,
         "message": "device->host sync in hot path"},
    ]
    dynamic = [
        {"rule": "TPU009", "path": "pkg/a.py", "line": 12,
         "message": "unsynchronized shared access witnessed on "
         "`A.count`: no common lock held across threads"},
        {"rule": "TPU007", "path": "pkg/c.py", "line": 30,
         "message": "lock-order cycle witnessed at runtime"},
    ]
    witnessed, unexercised, unpredicted = classify(static, dynamic)
    failures = 0
    if [f["path"] for f, _ in witnessed] != ["pkg/a.py"]:
        print("self-check: TPU009 pair not classified as witnessed",
              file=sys.stderr)
        failures += 1
    if [f["path"] for f in unexercised] != ["pkg/b.py"]:
        print("self-check: static-only TPU010 not classified as "
              "unexercised", file=sys.stderr)
        failures += 1
    if [f["path"] for f in unpredicted] != ["pkg/c.py"]:
        print("self-check: dynamic-only TPU007 not classified as "
              "unpredicted", file=sys.stderr)
        failures += 1
    if failures:
        print(f"self-check: {failures} failure(s)", file=sys.stderr)
        return 1
    print("self-check: witnessed/unexercised/unpredicted columns recover "
          "the seeded classification")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument(
        "paths", nargs="*", default=["tritonclient_tpu", "scripts", "tests"],
        help="paths for the static run (default: the tpulint scope)",
    )
    parser.add_argument(
        "--dynamic", metavar="FILE", action="append",
        help="runtime report: tpusan (TPUSAN=1 suite run) or tpumc "
        "(scripts/tpumc.py --json/--sarif); repeat to merge evidence",
    )
    parser.add_argument(
        "--rules", default=",".join(DEFAULT_RULES),
        help="comma-separated rule ids to diff (default: the paired set)",
    )
    parser.add_argument(
        "--fail-on-witnessed", action="store_true",
        help="exit 1 if any static finding was witnessed at runtime",
    )
    parser.add_argument(
        "--self-check", action="store_true",
        help="classify synthetic records with a known answer and exit",
    )
    args = parser.parse_args(argv)
    if args.self_check:
        return self_check()
    if not args.dynamic:
        parser.error("--dynamic is required (or --self-check)")
    rules = {r.strip().upper() for r in args.rules.split(",") if r.strip()}

    try:
        dynamic = [
            f for path in args.dynamic for f in load_dynamic(path)
            if f.get("rule") in rules
        ]
    except (OSError, ValueError) as e:
        print(f"tpusan_report: cannot load dynamic report: {e}",
              file=sys.stderr)
        return 2
    static = run_static(args.paths, rules)
    witnessed, unexercised, unpredicted = classify(static, dynamic)

    def show(f):
        return f"  {f['path']}:{f.get('line', 1)}: {f['rule']} {f['message']}"

    print(f"tpusan_report: rules={','.join(sorted(rules))} "
          f"static={len(static)} dynamic={len(dynamic)}")
    print(f"\nwitnessed (static finding observed at runtime): "
          f"{len(witnessed)}")
    for f, dyn in witnessed:
        print(show(f))
        for d in dyn:
            print(f"    runtime: {d['message']}")
    print(f"\nunexercised (static finding never observed — coverage gap "
          f"or deliberate/baselined): {len(unexercised)}")
    for f in unexercised:
        print(show(f))
    print(f"\nunpredicted (runtime finding with no static counterpart — "
          f"rule gap, file as a lint fixture): {len(unpredicted)}")
    for f in unpredicted:
        print(show(f))

    if args.fail_on_witnessed and witnessed:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

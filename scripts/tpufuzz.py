#!/usr/bin/env python
"""tpufuzz: seeded deterministic protocol fuzzer for the request plane.

Drives structure-aware mutations of committed KServe v2 corpus seeds at
a live in-process server over HTTP and gRPC, asserting the
no-500/no-hang/no-leak contract, and emits a byte-deterministic JSON
report plus TPU013 SARIF for ``scripts/tpusan_report.py``.

    python scripts/tpufuzz.py --seed 20260807 --requests 500 \
        --json out/fuzz.json --sarif out/fuzz.sarif

``--self-check`` runs the offline determinism harness (no server, no
sockets): same-seed stream equality, different-seed divergence,
per-mutation encodability on both planes, and a SARIF round-trip.
"""

import argparse
import hashlib
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)


def _dump(report) -> str:
    return json.dumps(report, sort_keys=True, indent=2) + "\n"


def _self_check() -> int:
    """Offline determinism harness; returns a process exit code."""
    import random

    from tritonclient_tpu import fuzz
    from tritonclient_tpu.analysis._sarif import load_sarif_findings
    from tritonclient_tpu.fuzz import _run

    failures = []
    seeds = fuzz.load_corpus()
    if len(seeds) < 3:
        failures.append(f"corpus has {len(seeds)} seeds, expected >= 3")

    def stream(seed, n=120):
        rng = random.Random(seed)
        return fuzz.generate_specs(
            seeds, rng, n, ("http", "grpc"),
            expressible=fuzz.expressible)

    a, b = stream(7), stream(7)
    if json.dumps(a, sort_keys=True) != json.dumps(b, sort_keys=True):
        failures.append("same seed produced different mutation streams")
    c = stream(8)
    if json.dumps(a, sort_keys=True) == json.dumps(c, sort_keys=True):
        failures.append("different seeds produced identical streams")

    # Every catalog mutation must be JSON-serializable and must stay
    # expressible on at least one plane for at least one seed.
    rng = random.Random(11)
    for name, (planes, fn) in sorted(fuzz.CATALOG.items()):
        hit = 0
        for seed_doc in seeds:
            for _ in range(8):
                spec = fn(seed_doc, rng)
                if spec is None:
                    continue
                spec["id"] = "case-check"
                spec["planes"] = [
                    p for p in planes if fuzz.expressible(spec, p)]
                try:
                    json.dumps(spec, sort_keys=True)
                except (TypeError, ValueError):
                    failures.append(
                        f"mutation {name} produced a non-JSON spec")
                    break
                if "http" in spec["planes"]:
                    try:
                        _run._http_payload(spec)
                    except Exception as e:  # pragma: no cover - harness
                        failures.append(
                            f"mutation {name} not HTTP-encodable: {e}")
                        break
                hit += len(spec["planes"])
        if hit == 0:
            failures.append(
                f"mutation {name} never expressible on any plane")

    # SARIF round-trip: a synthetic failure must survive render+load
    # with its fingerprint intact.
    fake = {
        "failures": [{
            "case": "case-00000", "plane": "http", "seed": "simple-int32",
            "mutation": "shape_huge", "outcome": "http-500",
            "detail": "HTTP 500 (server error)",
        }],
    }
    sarif_text = fuzz.render_sarif(fake)
    path = os.path.join("/tmp", "tpufuzz_selfcheck.sarif")
    with open(path, "w") as f:
        f.write(sarif_text)
    loaded = load_sarif_findings(path)
    os.unlink(path)
    if (len(loaded) != 1 or loaded[0]["rule"] != "TPU013"
            or loaded[0]["path"] != "tritonclient_tpu/server/_http.py"):
        failures.append(f"SARIF round-trip mismatch: {loaded}")

    for msg in failures:
        print(f"tpufuzz --self-check: FAIL: {msg}")
    if not failures:
        print(f"tpufuzz --self-check: OK "
              f"({len(fuzz.CATALOG)} mutations, {len(seeds)} seeds)")
    return 1 if failures else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tpufuzz", description=__doc__)
    ap.add_argument("--seed", type=int, default=20260807)
    ap.add_argument("--requests", type=int, default=500,
                    help="cases to execute per plane")
    ap.add_argument("--plane", choices=("http", "grpc", "both"),
                    default="both")
    ap.add_argument("--corpus", default=None,
                    help="seed directory (default: committed corpus)")
    ap.add_argument("--json", dest="json_out", default=None,
                    help="write the deterministic report here")
    ap.add_argument("--sarif", default=None,
                    help="write failures as TPU013 SARIF here")
    ap.add_argument("--self-check", action="store_true",
                    help="offline determinism harness (no server)")
    args = ap.parse_args(argv)

    if args.self_check:
        return _self_check()

    from tritonclient_tpu import fuzz

    planes = ("http", "grpc") if args.plane == "both" else (args.plane,)
    report = fuzz.run_fuzz(args.seed, args.requests, planes=planes,
                           corpus_dir=args.corpus)
    text = _dump(report)
    if args.json_out:
        os.makedirs(os.path.dirname(args.json_out) or ".", exist_ok=True)
        with open(args.json_out, "w") as f:
            f.write(text)
    if args.sarif:
        os.makedirs(os.path.dirname(args.sarif) or ".", exist_ok=True)
        with open(args.sarif, "w") as f:
            f.write(fuzz.render_sarif(report))

    digest = hashlib.sha256(text.encode()).hexdigest()
    executed = ", ".join(
        f"{p}={n}" for p, n in sorted(report["executed"].items()))
    print(f"tpufuzz: seed={report['seed']} executed [{executed}] "
          f"failures={len(report['failures'])} report-sha256={digest[:16]}")
    for f in report["failures"][:20]:
        print(f"  {f['case']}:{f['plane']} [{f['mutation']}] {f['detail']}")
    if len(report["failures"]) > 20:
        print(f"  ... and {len(report['failures']) - 20} more")
    return 1 if report["failures"] else 0


if __name__ == "__main__":
    sys.exit(main())

"""Mergeable relative-error quantile sketch (DDSketch-style).

The tail-observability plane needs quantiles that (a) cost bounded
memory on the serving hot path, (b) merge exactly across windows, runs,
and processes — pooled p99 must come from pooled *data*, not a
min-over-runs of per-run p99s — and (c) carry a worst-case accuracy
guarantee so a `/metrics` quantile row is evidence, not an estimate of
unknown quality. Fixed-bucket histograms fail (a→accuracy): the tail
lands in one wide bucket and p99 smears by the bucket width.

``LatencySketch`` is the standard relative-error design (DDSketch,
arxiv 1908.10693): values map to geometric buckets ``gamma^i`` with
``gamma = (1+alpha)/(1-alpha)``; any reported quantile is within
``alpha`` relative error of the exact sample quantile (default
``alpha=0.01`` — well inside the 2% budget the metrics contract
promises). Merging is bucket-wise counter addition, so merge is exact,
associative, and commutative: merging per-window sketches equals
sketching the concatenated samples.

Memory is bounded by ``max_buckets``: on overflow the lowest buckets
collapse into the floor bucket (tail accuracy is the point; the extreme
low end degrades first, and only after ~4096 distinct geometric buckets
≈ 35 decades of range at the default alpha).

Values are arbitrary non-negative floats (latencies in any unit);
negatives are clamped to the zero bucket rather than rejected so a
jittery caller cannot crash the metrics path.
"""

import json
import math
from typing import Dict, Iterable, List, Optional

DEFAULT_ALPHA = 0.01
DEFAULT_MAX_BUCKETS = 4096


class LatencySketch:
    """DDSketch-style quantile sketch: bounded memory, ``alpha`` relative
    error, exact merge, JSON-serializable."""

    __slots__ = ("alpha", "gamma", "_log_gamma", "max_buckets",
                 "_buckets", "zero_count", "count", "sum", "min", "max")

    def __init__(self, alpha: float = DEFAULT_ALPHA,
                 max_buckets: int = DEFAULT_MAX_BUCKETS):
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"alpha must be in (0, 1), got {alpha}")
        self.alpha = float(alpha)
        self.gamma = (1.0 + alpha) / (1.0 - alpha)
        self._log_gamma = math.log(self.gamma)
        self.max_buckets = max(int(max_buckets), 16)
        self._buckets: Dict[int, int] = {}
        self.zero_count = 0
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    # -- building -------------------------------------------------------------

    def _key(self, value: float) -> int:
        return math.ceil(math.log(value) / self._log_gamma)

    def _bucket_value(self, key: int) -> float:
        # Midpoint of (gamma^(i-1), gamma^i] in the relative sense: within
        # alpha of every value the bucket covers.
        return 2.0 * self.gamma ** key / (self.gamma + 1.0)

    def insert(self, value: float, count: int = 1):
        if count <= 0:
            return
        value = float(value)
        self.count += count
        self.sum += value * count
        if value < self.min:
            self.min = value
        if value > self.max:
            self.max = value
        # Sub-resolution values (including zero and clamped negatives) land
        # in the dedicated zero bucket; alpha relative error of ~0 is ~0.
        if value <= 0.0 or value < 1e-12:
            self.zero_count += count
            return
        key = self._key(value)
        self._buckets[key] = self._buckets.get(key, 0) + count
        if len(self._buckets) > self.max_buckets:
            self._collapse()

    def _collapse(self):
        """Fold the lowest buckets into one floor bucket until within the
        cap. Tail (high) buckets keep full resolution."""
        keys = sorted(self._buckets)
        while len(keys) > self.max_buckets:
            lowest, second = keys[0], keys[1]
            self._buckets[second] = (
                self._buckets.get(second, 0) + self._buckets.pop(lowest)
            )
            keys = keys[1:]

    def extend(self, values: Iterable[float]):
        for v in values:
            self.insert(v)

    # -- querying -------------------------------------------------------------

    def quantile(self, q: float) -> float:
        """Sample quantile at ``q`` in [0, 1], within ``alpha`` relative
        error of the exact nearest-rank quantile. 0.0 on an empty sketch."""
        if self.count == 0:
            return 0.0
        q = min(max(q, 0.0), 1.0)
        rank = max(int(math.ceil(q * self.count)), 1)
        if rank <= self.zero_count:
            return 0.0
        seen = self.zero_count
        for key in sorted(self._buckets):
            seen += self._buckets[key]
            if seen >= rank:
                return self._bucket_value(key)
        return self._bucket_value(max(self._buckets))  # numeric safety net

    def quantiles(self, qs: Iterable[float]) -> List[float]:
        return [self.quantile(q) for q in qs]

    @property
    def avg(self) -> float:
        return self.sum / self.count if self.count else 0.0

    # -- merging --------------------------------------------------------------

    def merge(self, other: "LatencySketch") -> "LatencySketch":
        """Fold ``other`` into this sketch (bucket-wise addition; exact).

        Requires matching ``alpha`` — merging incompatible geometries would
        silently corrupt the accuracy guarantee.
        """
        if abs(other.alpha - self.alpha) > 1e-12:
            raise ValueError(
                f"cannot merge sketches with alpha {other.alpha} != "
                f"{self.alpha}"
            )
        if other.count == 0:
            return self
        for key, n in other._buckets.items():
            self._buckets[key] = self._buckets.get(key, 0) + n
        self.zero_count += other.zero_count
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)
        if len(self._buckets) > self.max_buckets:
            self._collapse()
        return self

    @classmethod
    def merged(cls, sketches: Iterable["LatencySketch"],
               alpha: Optional[float] = None) -> "LatencySketch":
        out = None
        for s in sketches:
            if out is None:
                out = cls(alpha=alpha if alpha is not None else s.alpha,
                          max_buckets=s.max_buckets)
            out.merge(s)
        return out if out is not None else cls(
            alpha=alpha if alpha is not None else DEFAULT_ALPHA
        )

    # -- serialization --------------------------------------------------------

    def to_dict(self) -> dict:
        return {
            "alpha": self.alpha,
            "zero": self.zero_count,
            "count": self.count,
            "sum": self.sum,
            "min": self.min if self.count else None,
            "max": self.max if self.count else None,
            # JSON objects require string keys; parse back with int().
            "buckets": {str(k): v for k, v in self._buckets.items()},
        }

    @classmethod
    def from_dict(cls, doc: dict,
                  max_buckets: int = DEFAULT_MAX_BUCKETS) -> "LatencySketch":
        sketch = cls(alpha=float(doc.get("alpha", DEFAULT_ALPHA)),
                     max_buckets=max_buckets)
        sketch.zero_count = int(doc.get("zero", 0))
        sketch.count = int(doc.get("count", 0))
        sketch.sum = float(doc.get("sum", 0.0))
        sketch.min = (
            float(doc["min"]) if doc.get("min") is not None else math.inf
        )
        sketch.max = (
            float(doc["max"]) if doc.get("max") is not None else -math.inf
        )
        sketch._buckets = {
            int(k): int(v) for k, v in (doc.get("buckets") or {}).items()
        }
        if len(sketch._buckets) > sketch.max_buckets:
            sketch._collapse()
        return sketch

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @classmethod
    def from_json(cls, payload: str) -> "LatencySketch":
        return cls.from_dict(json.loads(payload))

    def __repr__(self):
        return (
            f"LatencySketch(alpha={self.alpha}, count={self.count}, "
            f"p50={self.quantile(0.5):.1f}, p99={self.quantile(0.99):.1f})"
        )

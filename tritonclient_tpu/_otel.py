"""W3C Trace Context + span tree + pluggable trace exporters.

PR 1's tracing recorded six flat timestamps per sampled request; the client
and server were separate worlds joined only by an opaque request id. This
module makes the trace plane *distributed*:

* a minimal W3C Trace Context implementation — ``traceparent`` header
  generate/parse/inject/extract (https://www.w3.org/TR/trace-context/) —
  so a client-initiated trace id survives HTTP headers and gRPC metadata
  into server records;
* a parent/child ``Span`` model (client-send, transport, request-handler,
  batch-queue-wait, compute, response-marshal) that replaces the flat
  timestamp record as the internal trace representation, built from the
  same monotonic-ns event stream the front-ends/batcher/core already stamp;
* pluggable exporters selected by the ``trace_mode`` trace setting:
  ``triton`` (the Triton-shaped JSON array PR 1 emitted, kept for
  compatibility), ``otlp`` (OTLP/JSON spans a collector file-receiver or
  any OpenTelemetry tooling can ingest; ``opentelemetry`` is accepted as
  an alias), and ``perfetto`` (Chrome trace-event JSON that loads directly
  in Perfetto / chrome://tracing).

All span boundaries are ``time.monotonic_ns()`` values — the clock shared
with the statistics plane — and are shifted onto the unix epoch only at
export time via a per-process offset, so spans recorded by a co-located
client and server land on one consistent timeline.
"""

import json
import os
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# Span names, client side first, then the server-side tree under
# request-handler. One fixed vocabulary so exporters, the report CLI, and
# tests agree on spelling.
SPAN_CLIENT_SEND = "client-send"
SPAN_TRANSPORT = "transport"
SPAN_REQUEST_HANDLER = "request-handler"
SPAN_QUEUE_WAIT = "batch-queue-wait"
SPAN_COMPUTE = "compute"
SPAN_RESPONSE_MARSHAL = "response-marshal"

# Canonical order of the Triton-shaped timestamp names (PR 1 contract; the
# triton exporter and the report CLI's triton loader both rely on it).
TIMESTAMP_ORDER = (
    "REQUEST_RECV",
    "QUEUE_START",
    "COMPUTE_INPUT",
    "COMPUTE_INFER",
    "COMPUTE_OUTPUT",
    "RESPONSE_SEND",
)

TRACE_MODES = ("triton", "otlp", "perfetto")

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


def new_trace_id() -> str:
    """A random 128-bit trace id as 32 lowercase hex chars (never all-zero,
    which the W3C spec reserves as invalid)."""
    while True:
        tid = os.urandom(16).hex()
        if tid != "0" * 32:
            return tid


def new_span_id() -> str:
    """A random 64-bit span id as 16 lowercase hex chars (never all-zero)."""
    while True:
        sid = os.urandom(8).hex()
        if sid != "0" * 16:
            return sid


def parse_traceparent(header: Optional[str]) -> Optional[Tuple[str, str, int]]:
    """Parse a ``traceparent`` header into (trace_id, parent_span_id, flags).

    Returns None for anything malformed — per the W3C spec a receiver that
    cannot parse the header MUST restart the trace rather than fail the
    request, so callers treat None as "no inbound context".
    """
    if not header or not isinstance(header, str):
        return None
    m = _TRACEPARENT_RE.match(header.strip())
    if m is None:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff":  # forbidden version value
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id, int(flags, 16)


def format_traceparent(trace_id: str, span_id: str, sampled: bool = True) -> str:
    """Render version-00 ``traceparent`` for injection into a header or
    gRPC metadata."""
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


def epoch_offset_ns() -> int:
    """ns to add to a ``time.monotonic_ns()`` stamp to place it on the unix
    epoch. Captured per process; co-located processes agree to wall-clock
    precision, which is what a merged client+server timeline needs."""
    return time.time_ns() - time.monotonic_ns()


@dataclass
class Span:
    """One node of a trace: a named interval with W3C identity.

    ``start_ns``/``end_ns`` are monotonic-ns; exporters shift them to unix
    time. ``parent_span_id`` empty means root (no inbound traceparent).
    """

    name: str
    trace_id: str
    span_id: str
    parent_span_id: str
    start_ns: int
    end_ns: int
    attributes: Dict[str, object] = field(default_factory=dict)

    @property
    def duration_ns(self) -> int:
        return max(self.end_ns - self.start_ns, 0)


@dataclass
class TraceRecord:
    """One finished trace: identity + span tree + the raw timestamp events.

    This is the collector's internal representation (the flat six-timestamp
    dict of PR 1 survives only as the ``timestamps`` field, kept so the
    ``triton`` exporter can emit the exact compatibility shape).
    """

    seq_id: int
    model_name: str
    model_version: str
    request_id: str
    trace_id: str
    parent_span_id: str
    spans: List[Span] = field(default_factory=list)
    timestamps: Dict[str, int] = field(default_factory=dict)
    # Request-level span attributes (e.g. the dynamic batcher's batch id);
    # build_span_tree puts them on the queue-wait/compute spans, and the
    # triton exporter carries them so its loader can rebuild the same tree.
    attributes: Dict[str, object] = field(default_factory=dict)
    tensors: Optional[List[dict]] = None


def build_span_tree(
    trace_id: str,
    parent_span_id: str,
    timestamps: Dict[str, int],
    attributes: Optional[Dict[str, object]] = None,
) -> List[Span]:
    """Assemble the server-side span tree from the recorded event stream.

    request-handler covers the whole request (REQUEST_RECV..RESPONSE_SEND,
    falling back to the observed extremes for partial/error traces); its
    children are batch-queue-wait (QUEUE_START..COMPUTE_INPUT), compute
    (COMPUTE_INPUT..COMPUTE_OUTPUT, with the COMPUTE_INFER boundary kept as
    an attribute), and response-marshal (COMPUTE_OUTPUT..RESPONSE_SEND).
    ``attributes`` (e.g. the dynamic batcher's batch id) land on the
    queue-wait and compute spans — the two intervals batching shapes.
    """
    ts = timestamps
    values = list(ts.values())
    if not values:
        return []
    recv = ts.get("REQUEST_RECV", min(values))
    send = ts.get("RESPONSE_SEND", max(values))
    handler = Span(
        SPAN_REQUEST_HANDLER, trace_id, new_span_id(), parent_span_id,
        recv, send,
    )
    spans = [handler]
    attributes = dict(attributes or {})
    if "QUEUE_START" in ts and "COMPUTE_INPUT" in ts:
        spans.append(
            Span(SPAN_QUEUE_WAIT, trace_id, new_span_id(), handler.span_id,
                 ts["QUEUE_START"], ts["COMPUTE_INPUT"], dict(attributes))
        )
    if "COMPUTE_INPUT" in ts and "COMPUTE_OUTPUT" in ts:
        attrs = dict(attributes)
        if "COMPUTE_INFER" in ts:
            # The input-resolve/model-dispatch boundary inside the compute
            # span; kept as an attribute rather than a sub-span so the tree
            # stays the documented three children.
            attrs["compute.infer_start_ns"] = ts["COMPUTE_INFER"]
        spans.append(
            Span(SPAN_COMPUTE, trace_id, new_span_id(), handler.span_id,
                 ts["COMPUTE_INPUT"], ts["COMPUTE_OUTPUT"], attrs)
        )
    if "COMPUTE_OUTPUT" in ts and "RESPONSE_SEND" in ts:
        spans.append(
            Span(SPAN_RESPONSE_MARSHAL, trace_id, new_span_id(),
                 handler.span_id, ts["COMPUTE_OUTPUT"], ts["RESPONSE_SEND"])
        )
    return spans


# --------------------------------------------------------------------------- #
# exporters                                                                   #
# --------------------------------------------------------------------------- #


def normalize_trace_mode(mode: str) -> str:
    """Collapse aliases / unknown values onto the supported exporter set."""
    mode = (mode or "").strip().lower()
    if mode == "opentelemetry":
        return "otlp"
    return mode if mode in TRACE_MODES else "triton"


def triton_record(record: TraceRecord) -> dict:
    """The PR-1-compatible Triton-shaped record, plus the W3C identity as
    extra keys (``trace_id``/``parent_span_id``) so files remain joinable
    with client-side spans without breaking existing readers."""
    out = {
        "id": record.seq_id,
        "model_name": record.model_name,
        "model_version": record.model_version or "1",
        "request_id": record.request_id,
        "trace_id": record.trace_id,
        "parent_span_id": record.parent_span_id,
        "timestamps": [
            {"name": name, "ns": record.timestamps[name]}
            for name in TIMESTAMP_ORDER
            if name in record.timestamps
        ]
        + [
            {"name": name, "ns": ns}
            for name, ns in record.timestamps.items()
            if name not in TIMESTAMP_ORDER
        ],
    }
    if record.attributes:
        out["attributes"] = dict(record.attributes)
    if record.tensors is not None:
        out["tensors"] = record.tensors
    return out


def render_triton(records: List[TraceRecord], epoch_ns: int = 0) -> str:
    return json.dumps([triton_record(r) for r in records])


def _otlp_attr_value(value) -> dict:
    if isinstance(value, bool):
        return {"boolValue": value}
    if isinstance(value, int):
        return {"intValue": str(value)}
    if isinstance(value, float):
        return {"doubleValue": value}
    return {"stringValue": str(value)}


def spans_to_otlp(spans: List[Span], epoch_ns: int,
                  extra_attrs: Optional[Dict[str, object]] = None) -> List[dict]:
    out = []
    for span in spans:
        attrs = dict(extra_attrs or {})
        attrs.update(span.attributes)
        out.append({
            "traceId": span.trace_id,
            "spanId": span.span_id,
            "parentSpanId": span.parent_span_id,
            "name": span.name,
            "kind": 2,  # SPAN_KIND_SERVER
            "startTimeUnixNano": str(span.start_ns + epoch_ns),
            "endTimeUnixNano": str(span.end_ns + epoch_ns),
            "attributes": [
                {"key": k, "value": _otlp_attr_value(v)}
                for k, v in attrs.items()
            ],
        })
    return out


def render_otlp(records: List[TraceRecord], epoch_ns: int) -> str:
    """OTLP/JSON (the ExportTraceServiceRequest JSON encoding): one
    resourceSpans entry, one scope, all spans flattened under it."""
    spans = []
    for record in records:
        spans.extend(spans_to_otlp(record.spans, epoch_ns, {
            "model.name": record.model_name,
            "model.version": record.model_version or "1",
            "request.id": record.request_id,
        }))
    doc = {
        "resourceSpans": [{
            "resource": {
                "attributes": [{
                    "key": "service.name",
                    "value": {"stringValue": "triton-tpu"},
                }],
            },
            "scopeSpans": [{
                "scope": {"name": "tritonclient_tpu"},
                "spans": spans,
            }],
        }],
    }
    return json.dumps(doc)


def spans_to_perfetto(spans: List[Span], epoch_ns: int, pid: int,
                      tid: int, cat: str,
                      extra_args: Optional[Dict[str, object]] = None) -> List[dict]:
    """Chrome trace-event complete events ('X'): ts/dur in microseconds."""
    events = []
    for span in spans:
        args = {
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "parent_span_id": span.parent_span_id,
        }
        args.update(extra_args or {})
        args.update({k: str(v) for k, v in span.attributes.items()})
        events.append({
            "name": span.name,
            "cat": cat,
            "ph": "X",
            "ts": (span.start_ns + epoch_ns) / 1000.0,
            "dur": span.duration_ns / 1000.0,
            "pid": pid,
            "tid": tid,
            "args": args,
        })
    return events


def render_perfetto(records: List[TraceRecord], epoch_ns: int,
                    extra_events: Optional[List[dict]] = None) -> str:
    """``extra_events`` are pre-built Chrome trace events appended
    verbatim — thread-scoped tracks (e.g. stepscope engine steps) that
    have no request span to parent under."""
    pid = os.getpid()
    events = []
    for record in records:
        events.extend(spans_to_perfetto(
            record.spans, epoch_ns, pid,
            # One track per trace keeps a request's span tree visually
            # stacked in the Perfetto UI.
            tid=record.seq_id, cat="server",
            extra_args={
                "model": record.model_name,
                "request_id": record.request_id,
            },
        ))
    if extra_events:
        events.extend(extra_events)
    return json.dumps({"displayTimeUnit": "ns", "traceEvents": events})


def render_merged_perfetto(client_spans: List[Span],
                           server_spans: List[dict],
                           epoch_ns: int,
                           extra_events: Optional[List[dict]] = None) -> str:
    """One Perfetto file for a client+server window (perf_analyzer
    ``--trace-out``).

    ``client_spans`` are live Span objects from a ClientSpanCollector;
    ``server_spans`` are ``load_spans``-shaped dicts read back from the
    server's trace file. Spans sharing a trace id land on one track (tid)
    so a request's client-send / transport / request-handler / queue /
    compute stack reads top-to-bottom in the Perfetto UI; category
    separates the two processes' contributions.
    """
    pid = os.getpid()
    tids: Dict[str, int] = {}

    def tid_of(trace_id: str) -> int:
        return tids.setdefault(trace_id, len(tids) + 1)

    events = []
    for span in client_spans:
        events.extend(spans_to_perfetto(
            [span], epoch_ns, pid, tid_of(span.trace_id), cat="client",
        ))
    for s in server_spans:
        args = {
            "trace_id": s.get("trace_id", ""),
            "span_id": s.get("span_id", ""),
            "parent_span_id": s.get("parent_span_id", ""),
        }
        args.update({
            k: str(v) for k, v in (s.get("attributes") or {}).items()
        })
        events.append({
            "name": s.get("name", ""),
            "cat": "server",
            "ph": "X",
            "ts": (int(s.get("start_ns", 0)) + epoch_ns) / 1000.0,
            "dur": max(int(s.get("duration_ns", 0)), 0) / 1000.0,
            "pid": pid,
            "tid": tid_of(s.get("trace_id", "")),
            "args": args,
        })
    if extra_events:
        events.extend(extra_events)
    return json.dumps({"displayTimeUnit": "ns", "traceEvents": events})


_RENDERERS = {
    "triton": render_triton,
    "otlp": render_otlp,
    "perfetto": render_perfetto,
}


def render_trace_file(mode: str, records: List[TraceRecord],
                      epoch_ns: int) -> str:
    return _RENDERERS[normalize_trace_mode(mode)](records, epoch_ns)


# --------------------------------------------------------------------------- #
# loaders (trace_report.py + tests round-trip through these)                  #
# --------------------------------------------------------------------------- #


def detect_trace_format(doc) -> str:
    if isinstance(doc, list):
        return "triton"
    if isinstance(doc, dict) and "resourceSpans" in doc:
        return "otlp"
    if isinstance(doc, dict) and "traceEvents" in doc:
        return "perfetto"
    raise ValueError("unrecognized trace file format")


def load_spans(doc) -> List[dict]:
    """Normalize any exporter's output to flat span dicts:
    {name, trace_id, span_id, parent_span_id, start_ns, end_ns,
    duration_ns, attributes}. Triton-shaped records are re-derived through
    build_span_tree so all three formats report identical breakdowns."""
    fmt = detect_trace_format(doc)
    spans: List[dict] = []
    if fmt == "triton":
        for record in doc:
            ts = {t["name"]: int(t["ns"]) for t in record.get("timestamps", [])}
            trace_id = record.get("trace_id") or new_trace_id()
            for span in build_span_tree(
                trace_id, record.get("parent_span_id", ""), ts,
                record.get("attributes"),
            ):
                attrs = {
                    "model": record.get("model_name", ""),
                    "request_id": record.get("request_id", ""),
                }
                attrs.update(span.attributes)
                spans.append({
                    "name": span.name,
                    "trace_id": span.trace_id,
                    "span_id": span.span_id,
                    "parent_span_id": span.parent_span_id,
                    "start_ns": span.start_ns,
                    "end_ns": span.end_ns,
                    "duration_ns": span.duration_ns,
                    "attributes": attrs,
                })
    elif fmt == "otlp":
        for rs in doc.get("resourceSpans", []):
            for ss in rs.get("scopeSpans", []):
                for s in ss.get("spans", []):
                    start = int(s.get("startTimeUnixNano", 0))
                    end = int(s.get("endTimeUnixNano", 0))
                    spans.append({
                        "name": s.get("name", ""),
                        "trace_id": s.get("traceId", ""),
                        "span_id": s.get("spanId", ""),
                        "parent_span_id": s.get("parentSpanId", ""),
                        "start_ns": start,
                        "end_ns": end,
                        "duration_ns": max(end - start, 0),
                        "attributes": {
                            a["key"]: next(iter(a["value"].values()))
                            for a in s.get("attributes", [])
                        },
                    })
    else:  # perfetto
        for e in doc.get("traceEvents", []):
            if e.get("ph") != "X":
                continue
            start = int(float(e.get("ts", 0)) * 1000)
            dur = int(float(e.get("dur", 0)) * 1000)
            args = dict(e.get("args", {}))
            trace_id = args.get("trace_id", "")
            if not trace_id:
                # Thread-scoped track with no request parent (stepscope
                # engine steps, foreign tool output): keep per-track
                # identity so orphan events group by their track instead
                # of every trackless event collapsing into one "" trace.
                trace_id = f"track-{e.get('pid', 0)}-{e.get('tid', 0)}"
            spans.append({
                "name": e.get("name", ""),
                "trace_id": trace_id,
                "span_id": args.get("span_id", ""),
                "parent_span_id": args.get("parent_span_id", ""),
                "start_ns": start,
                "end_ns": start + dur,
                "duration_ns": dur,
                "attributes": args,
            })
    return spans


def load_trace_file(path: str) -> List[dict]:
    with open(path) as f:
        return load_spans(json.load(f))


# --------------------------------------------------------------------------- #
# client-side spans (perf_analyzer --trace-out)                               #
# --------------------------------------------------------------------------- #


class ClientSpanCollector:
    """Thread-safe sink for client-side request spans.

    ``begin()`` mints a new trace with a ``client-send`` root span and
    returns the ``traceparent`` to inject plus an opaque handle;
    ``finish(handle, timers)`` closes the root span from a RequestTimers
    and adds the ``transport`` child (send_end..recv_start — wire plus
    server time as seen from the client). The server's request-handler
    span, extracted from the propagated traceparent, nests inside it.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._spans: List[Span] = []

    def begin(self) -> Tuple[str, Tuple[str, str]]:
        trace_id, span_id = new_trace_id(), new_span_id()
        return format_traceparent(trace_id, span_id), (trace_id, span_id)

    def finish(self, handle: Tuple[str, str], timers) -> None:
        trace_id, span_id = handle
        root = Span(
            SPAN_CLIENT_SEND, trace_id, span_id, "",
            timers.request_start, timers.request_end,
        )
        spans = [root]
        if timers.send_end and timers.recv_start:
            spans.append(Span(
                SPAN_TRANSPORT, trace_id, new_span_id(), span_id,
                timers.send_end, timers.recv_start,
            ))
        with self._lock:
            self._spans.extend(spans)

    def drain(self) -> List[Span]:
        with self._lock:
            spans, self._spans = self._spans, []
        return spans

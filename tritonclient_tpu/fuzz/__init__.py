"""tpufuzz: seeded, deterministic, structure-aware protocol fuzzing for
the untrusted request plane.

tpufuzz is the dynamic half of the TPU013 story. The static taint rule
(``tritonclient_tpu/analysis/_tpu013_taint.py``) proves that
request-derived integers cannot reach allocation/indexing sinks without
a ``validate_*`` sanitizer; tpufuzz *witnesses* the same boundary from
outside by mutating well-formed KServe v2 requests (committed corpus
seeds under ``corpus/``) and asserting the server's contract on both
planes:

* no 5xx / no unclassified gRPC status for malformed input — every
  rejection must be a typed 4xx with a JSON error body (HTTP) or a
  mapped status such as ``INVALID_ARGUMENT`` (gRPC);
* no hang — each case is bounded by a client-side deadline, and a
  final well-formed probe per plane proves the server still serves;
* no leak — the run executes under ``sanitize`` report mode and folds
  any sanitizer findings (including ``check_leaks``) into its failures.

Everything is deterministic: the only entropy is a seeded
``random.Random``, corpus and mutation catalogs iterate in sorted
order, and the report contains no timestamps, ports, or addresses.
Same seed + same corpus -> byte-identical report and SARIF, which is
what lets CI diff two consecutive runs and fail on any drift.

Entry point: ``scripts/tpufuzz.py`` (see ``--self-check`` for the
offline determinism harness). Failures render as SARIF rule TPU013 so
``scripts/tpusan_report.py`` can classify them against the static
findings stream.
"""

from tritonclient_tpu.fuzz._mutate import (  # noqa: F401
    CATALOG,
    FUZZ_MAX_REQUEST_BYTES,
    generate_specs,
    load_corpus,
)
from tritonclient_tpu.fuzz._run import (  # noqa: F401
    Inexpressible,
    build_grpc_request,
    expressible,
    render_sarif,
    report_findings,
    run_fuzz,
)

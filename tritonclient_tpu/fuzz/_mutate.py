"""Structure-aware, seeded mutation of KServe v2 inference requests.

Every mutation is a pure function ``(seed_request, rng) -> spec``: the
spec is a plain JSON-serializable dict that fully describes one fuzz
case — the (possibly broken) inference-header JSON, optional binary
tails, optional raw-body override, header lies, or an shm-register
payload. Plane encoders in ``_run.py`` turn a spec into an actual HTTP
request or protobuf message; a spec the gRPC plane cannot express
(e.g. a dict where the proto wants an int64) is skipped there, and the
skip itself is deterministic because it depends only on the spec.

Determinism contract: the ONLY entropy source is the ``random.Random``
the caller seeds. No wall clock, no os.urandom, no dict-order
dependence (catalog and corpus iterate sorted). Same seed + same corpus
=> byte-identical spec stream, which is what lets CI diff two
consecutive runs.
"""

import copy
import json
import os
from typing import Callable, Dict, List, Tuple

#: Body cap the fuzz server is configured with; the content-length-bomb
#: and oversized-message mutations size themselves against it.
FUZZ_MAX_REQUEST_BYTES = 1 << 20

_CORPUS_DIR = os.path.join(os.path.dirname(__file__), "corpus")


def load_corpus(corpus_dir: str = _CORPUS_DIR) -> List[dict]:
    """Committed seed requests, sorted by file name for determinism."""
    seeds = []
    for fname in sorted(os.listdir(corpus_dir)):
        if not fname.endswith(".json"):
            continue
        with open(os.path.join(corpus_dir, fname), "r",
                  encoding="utf-8") as f:
            seed = json.load(f)
        seed.setdefault("name", fname[:-5])
        seeds.append(seed)
    return seeds


def _base_spec(seed: dict, mutation: str) -> dict:
    return {
        "seed": seed["name"],
        "mutation": mutation,
        "model": seed["model"],
        "endpoint": "infer",
        "js": {
            "inputs": copy.deepcopy(seed.get("inputs", [])),
            "outputs": copy.deepcopy(seed.get("outputs", [])),
        },
        "binary": None,        # {input_name: {"claim": .., "blob_hex": ..}}
        "raw_body": None,      # hex-encoded body override (HTTP only)
        "content_length": None,  # Content-Length lie (HTTP only)
        "header_len": None,    # Inference-Header-Content-Length override
        "shm": None,           # shm-register payload
    }


def _pick_input(spec: dict, rng) -> dict:
    inputs = spec["js"]["inputs"]
    return inputs[rng.randrange(len(inputs))]


# -- the catalog -----------------------------------------------------------


def m_baseline_valid(seed, rng):
    """Unmutated seed: must succeed — catches over-rejection drift."""
    return _base_spec(seed, "baseline_valid")


def m_missing_inputs(seed, rng):
    spec = _base_spec(seed, "missing_inputs")
    if rng.random() < 0.5:
        spec["js"]["inputs"] = []
    else:
        spec["js"]["inputs"] = spec["js"]["inputs"][:1]
    return spec


def m_drop_required(seed, rng):
    spec = _base_spec(seed, "drop_required")
    t = _pick_input(spec, rng)
    t.pop(rng.choice(["name", "datatype", "shape"]), None)
    return spec


def m_type_confusion(seed, rng):
    spec = _base_spec(seed, "type_confusion")
    t = _pick_input(spec, rng)
    field = rng.choice(["shape", "datatype", "data"])
    t[field] = rng.choice(["16", 16, None, {"x": 1}, [[1, 2]], True])
    return spec


def m_shape_negative(seed, rng):
    spec = _base_spec(seed, "shape_negative")
    t = _pick_input(spec, rng)
    shape = list(t.get("shape", [1]))
    shape[rng.randrange(len(shape))] = rng.choice([-1, -(2 ** 31), -(2 ** 62)])
    t["shape"] = shape
    return spec


def m_shape_huge(seed, rng):
    spec = _base_spec(seed, "shape_huge")
    t = _pick_input(spec, rng)
    if rng.random() < 0.5:
        shape = list(t.get("shape", [1]))
        shape[rng.randrange(len(shape))] = rng.choice(
            [2 ** 31, 2 ** 40, 2 ** 62])
        t["shape"] = shape
    else:
        t["shape"] = [65536, 65536]  # product bomb, small spelling
    return spec


def m_shape_rank_bomb(seed, rng):
    spec = _base_spec(seed, "shape_rank_bomb")
    t = _pick_input(spec, rng)
    t["shape"] = [1] * rng.choice([33, 100, 1000])
    return spec


def m_shape_bad_dims(seed, rng):
    spec = _base_spec(seed, "shape_bad_dims")
    t = _pick_input(spec, rng)
    shape = list(t.get("shape", [1]))
    shape[rng.randrange(len(shape))] = rng.choice([1.5, True, "4", None])
    t["shape"] = shape
    return spec


def m_data_mismatch(seed, rng):
    spec = _base_spec(seed, "data_mismatch")
    t = _pick_input(spec, rng)
    data = list(t.get("data", [])) or [0]
    if rng.random() < 0.5:
        data = data[: max(1, len(data) // 2)]
    else:
        data = data + data
    t["data"] = data
    t.pop("parameters", None)  # force the dense-JSON path
    return spec


def m_dtype_unknown(seed, rng):
    spec = _base_spec(seed, "dtype_unknown")
    t = _pick_input(spec, rng)
    t["datatype"] = rng.choice(["FP128", "int32", "", "X" * 64, "BYTES2"])
    return spec


def m_binary_truncated(seed, rng):
    spec = _base_spec(seed, "binary_truncated")
    t = _pick_input(spec, rng)
    t.pop("data", None)
    t.pop("parameters", None)
    claim = 64
    short = rng.randrange(0, claim)  # strictly fewer bytes than claimed
    t["parameters"] = {"binary_data_size": claim}
    spec["binary"] = {t["name"]: {"claim": claim,
                                  "blob_hex": ("ab" * short)}}
    return spec


def m_binary_size_lie(seed, rng):
    spec = _base_spec(seed, "binary_size_lie")
    t = _pick_input(spec, rng)
    t.pop("data", None)
    t.pop("parameters", None)
    claim = rng.choice([-1, -(2 ** 40), 2 ** 40, "sixty-four", None])
    t["parameters"] = {"binary_data_size": claim}
    spec["binary"] = {t["name"]: {"claim": 0, "blob_hex": "ab" * 64}}
    return spec


def m_header_len_abuse(seed, rng):
    spec = _base_spec(seed, "header_len_abuse")
    spec["header_len"] = rng.choice([-1, 10 ** 9, "NaN", 2 ** 62, ""])
    return spec


def m_junk_json(seed, rng):
    spec = _base_spec(seed, "junk_json")
    payload = json.dumps(spec["js"]).encode()
    choice = rng.randrange(4)
    if choice == 0:
        body = payload[: rng.randrange(1, len(payload))]  # truncated JSON
    elif choice == 1:
        body = b"\xff\xfe{" + payload[:32]
    elif choice == 2:
        body = b""
    else:
        body = b"[" + payload + b"]"  # a list where a dict is expected
    spec["raw_body"] = body.hex()
    return spec


def m_content_length_bomb(seed, rng):
    spec = _base_spec(seed, "content_length_bomb")
    spec["content_length"] = FUZZ_MAX_REQUEST_BYTES + rng.choice(
        [1, 4096, 2 ** 31, 2 ** 62])
    spec["raw_body"] = b"".hex()  # the cap must reject BEFORE any read
    return spec


def m_oversized_message(seed, rng):
    spec = _base_spec(seed, "oversized_message")
    t = _pick_input(spec, rng)
    t.pop("data", None)
    t.pop("parameters", None)
    nbytes = FUZZ_MAX_REQUEST_BYTES + 65536
    t["parameters"] = {"binary_data_size": nbytes}
    # Deterministic filler, sized just over the plane's body cap.
    spec["binary"] = {t["name"]: {"claim": nbytes, "blob_hex": None,
                                  "blob_fill": nbytes}}
    return spec


def m_shm_param_abuse(seed, rng):
    spec = _base_spec(seed, "shm_param_abuse")
    t = _pick_input(spec, rng)
    t.pop("data", None)
    t["parameters"] = {
        "shared_memory_region": rng.choice(["fuzz_region", "nope", ""]),
        "shared_memory_offset": rng.choice([-1, -(2 ** 40), 0, 2 ** 62]),
        "shared_memory_byte_size": rng.choice([-1, 2 ** 62, 64, "big"]),
    }
    return spec


def m_shm_register_abuse(seed, rng):
    spec = _base_spec(seed, "shm_register_abuse")
    spec["endpoint"] = "shm_register"
    spec["shm"] = {
        "name": rng.choice(["fuzz_reg", "", "a" * 512]),
        "key": "/tpufuzz_no_such_key",
        "offset": rng.choice([-1, -(2 ** 40), 0, 2 ** 62]),
        "byte_size": rng.choice([-1, 2 ** 62, 4096]),
    }
    return spec


def m_classification_abuse(seed, rng):
    spec = _base_spec(seed, "classification_abuse")
    outs = spec["js"]["outputs"] or [{"name": "OUTPUT0"}]
    out = outs[rng.randrange(len(outs))]
    out["parameters"] = {
        "classification": rng.choice([-1, 2 ** 40, "many", 1.5, None])
    }
    spec["js"]["outputs"] = outs
    return spec


def m_mixed_contents(seed, rng):
    """gRPC-only shape: contents AND raw_input_contents both set."""
    spec = _base_spec(seed, "mixed_contents")
    t = _pick_input(spec, rng)
    blob = "cd" * 64
    spec["binary"] = {t["name"]: {"claim": 64, "blob_hex": blob}}
    # keep t["data"] so the encoder also fills typed contents
    return spec


def m_id_unicode(seed, rng):
    spec = _base_spec(seed, "id_unicode")
    spec["js"]["id"] = rng.choice(["\U0001d518" * 256, "\x00\x01", "i" * 4096])
    return spec


#: name -> (planes, mutator). Sorted iteration keeps the stream stable.
CATALOG: Dict[str, Tuple[Tuple[str, ...], Callable]] = {
    "baseline_valid": (("http", "grpc"), m_baseline_valid),
    "missing_inputs": (("http", "grpc"), m_missing_inputs),
    "drop_required": (("http", "grpc"), m_drop_required),
    "type_confusion": (("http", "grpc"), m_type_confusion),
    "shape_negative": (("http", "grpc"), m_shape_negative),
    "shape_huge": (("http", "grpc"), m_shape_huge),
    "shape_rank_bomb": (("http", "grpc"), m_shape_rank_bomb),
    "shape_bad_dims": (("http", "grpc"), m_shape_bad_dims),
    "data_mismatch": (("http", "grpc"), m_data_mismatch),
    "dtype_unknown": (("http", "grpc"), m_dtype_unknown),
    "binary_truncated": (("http", "grpc"), m_binary_truncated),
    "binary_size_lie": (("http",), m_binary_size_lie),
    "header_len_abuse": (("http",), m_header_len_abuse),
    "junk_json": (("http",), m_junk_json),
    "content_length_bomb": (("http",), m_content_length_bomb),
    "oversized_message": (("http", "grpc"), m_oversized_message),
    "shm_param_abuse": (("http", "grpc"), m_shm_param_abuse),
    "shm_register_abuse": (("http", "grpc"), m_shm_register_abuse),
    "classification_abuse": (("http", "grpc"), m_classification_abuse),
    "mixed_contents": (("grpc",), m_mixed_contents),
    "id_unicode": (("http", "grpc"), m_id_unicode),
}


def generate_specs(seeds: List[dict], rng, count_per_plane: int,
                   planes: Tuple[str, ...],
                   expressible: Callable = None) -> List[dict]:
    """A deterministic spec stream with at least ``count_per_plane``
    cases expressible on each requested plane.

    ``expressible(spec, plane)`` narrows the catalog's plane tags to
    what the plane encoder can actually build (e.g. a dict where the
    proto wants an int64 is HTTP-only); it must be a pure function of
    the spec so the stream stays deterministic.
    """
    names = sorted(CATALOG)
    specs: List[dict] = []
    counts = {p: 0 for p in planes}
    i = 0
    while any(counts[p] < count_per_plane for p in planes):
        seed = seeds[i % len(seeds)]
        name = names[rng.randrange(len(names))]
        mut_planes, fn = CATALOG[name]
        spec = fn(seed, rng)
        spec["id"] = f"case-{i:05d}"
        spec["planes"] = [
            p for p in planes
            if p in mut_planes
            and counts[p] < count_per_plane
            and (expressible is None or expressible(spec, p))
        ]
        specs.append(spec)
        for p in spec["planes"]:
            counts[p] += 1
        i += 1
    return specs

"""tpufuzz runner: drive mutated KServe v2 requests at a live in-process
server on both protocol planes and assert the no-500 / no-hang / no-leak
contract.

The runner is the dynamic witness for TPU013: every failure is emitted
as a ``TPU013`` SARIF result attributed to the plane's front-end file,
so ``scripts/tpusan_report.py --rules TPU013`` can diff the fuzzer's
evidence against the static taint picture (witnessed / unexercised /
unpredicted) exactly the way tpusan runtime findings diff against the
other paired rules.

Determinism: the report contains no timestamps, addresses, or ports —
only seed, counts, sorted histograms, failures, and a digest over every
``case-id:plane:outcome`` triple. Two runs with the same seed and
corpus must produce byte-identical report and SARIF files; CI enforces
exactly that.
"""

import hashlib
import http.client
import json
import socket
from typing import Dict, List, Optional, Tuple

from tritonclient_tpu.analysis._engine import Finding
from tritonclient_tpu.fuzz import _mutate

#: SARIF rule metadata for tpufuzz results (same id as the static taint
#: rule — that identity is what lets the report streams merge).
RULES_META = [
    {
        "id": "TPU013",
        "name": "untrusted-sink",
        "shortDescription": {
            "text": "malformed request produced a server error, hang, or "
            "leak instead of a typed validation rejection"
        },
    },
]

_PLANE_FILES = {
    "http": "tritonclient_tpu/server/_http.py",
    "grpc": "tritonclient_tpu/server/_grpc.py",
}

#: gRPC status codes a validation rejection may legitimately map to.
_GRPC_ALLOWED = {
    "INVALID_ARGUMENT", "NOT_FOUND", "RESOURCE_EXHAUSTED",
    "UNIMPLEMENTED", "FAILED_PRECONDITION", "OUT_OF_RANGE",
}

_INT64_MIN, _INT64_MAX = -(2 ** 63), 2 ** 63 - 1
_HTTP_TIMEOUT = 30.0
_GRPC_TIMEOUT = 30.0


class Inexpressible(Exception):
    """The spec cannot be encoded on this plane (deterministic skip)."""


# -- gRPC encoding ---------------------------------------------------------


def _require(cond, why: str):
    if not cond:
        raise Inexpressible(why)


def _is_int(v) -> bool:
    return isinstance(v, int) and not isinstance(v, bool)


def _set_param(params, key, value):
    if isinstance(value, bool):
        params[key].bool_param = value
    elif _is_int(value):
        _require(_INT64_MIN <= value <= _INT64_MAX, "int64 range")
        params[key].int64_param = value
    elif isinstance(value, str):
        params[key].string_param = value
    elif isinstance(value, float):
        params[key].double_param = value
    else:
        raise Inexpressible(f"param type {type(value).__name__}")


def _blob_bytes(entry: dict) -> bytes:
    if entry.get("blob_hex") is not None:
        return bytes.fromhex(entry["blob_hex"])
    return b"\xab" * int(entry["blob_fill"])


def build_grpc_request(spec: dict, pb):
    """Spec -> protobuf message(s); raises :class:`Inexpressible` when
    the typed proto surface cannot carry the mutation."""
    if spec["endpoint"] == "shm_register":
        shm = spec["shm"]
        # offset/byte_size are uint64 on the wire: negative or huge
        # values simply cannot be encoded on this plane.
        _require(_is_int(shm["offset"]), "offset type")
        _require(_is_int(shm["byte_size"]), "byte_size type")
        _require(0 <= shm["offset"] < 2 ** 64, "offset range")
        _require(0 <= shm["byte_size"] < 2 ** 64, "byte_size range")
        return pb.SystemSharedMemoryRegisterRequest(
            name=shm["name"], key=shm["key"], offset=shm["offset"],
            byte_size=shm["byte_size"],
        )
    js = spec["js"]
    req = pb.ModelInferRequest(model_name=spec["model"])
    rid = js.get("id")
    if rid is not None:
        _require(isinstance(rid, str), "id type")
        req.id = rid
    binary = spec.get("binary") or {}
    for t in js.get("inputs", []):
        _require(isinstance(t, dict), "input shape")
        tensor = req.inputs.add()
        name = t.get("name")
        _require(isinstance(name, str), "input name")
        tensor.name = name
        dt = t.get("datatype", "")
        _require(isinstance(dt, str), "datatype type")
        tensor.datatype = dt
        shape = t.get("shape", [])
        _require(isinstance(shape, list), "shape type")
        for d in shape:
            _require(_is_int(d), "shape dim type")
            _require(_INT64_MIN <= d <= _INT64_MAX, "shape dim range")
            tensor.shape.append(d)
        for key, value in sorted((t.get("parameters") or {}).items()):
            if key == "binary_data_size":
                continue  # HTTP framing; gRPC carries raw_input_contents
            _set_param(tensor.parameters, key, value)
        data = t.get("data")
        if data is not None and name not in binary:
            _require(isinstance(data, list), "data type")
            if all(isinstance(v, str) for v in data):
                tensor.contents.bytes_contents.extend(
                    v.encode() for v in data)
            elif all(_is_int(v) for v in data):
                _require(
                    all(-(2 ** 31) <= v < 2 ** 31 for v in data),
                    "int32 range")
                tensor.contents.int_contents.extend(data)
            else:
                raise Inexpressible("mixed data elements")
    for name in sorted(binary):
        req.raw_input_contents.append(_blob_bytes(binary[name]))
    for o in js.get("outputs", []):
        _require(isinstance(o, dict), "output shape")
        out = req.outputs.add()
        oname = o.get("name")
        _require(isinstance(oname, str), "output name")
        out.name = oname
        for key, value in sorted((o.get("parameters") or {}).items()):
            _set_param(out.parameters, key, value)
    return req


def expressible(spec: dict, plane: str) -> bool:
    """Pure plane-expressibility test used during spec generation."""
    if plane != "grpc":
        return True
    from tritonclient_tpu.protocol import pb

    try:
        build_grpc_request(spec, pb)
    except Inexpressible:
        return False
    return True


# -- HTTP encoding ---------------------------------------------------------


def _http_payload(spec: dict) -> Tuple[str, Dict[str, str], bytes]:
    """(path, headers, body) for one spec."""
    if spec["endpoint"] == "shm_register":
        shm = spec["shm"]
        path = f"/v2/systemsharedmemory/region/{shm['name']}/register"
        body = json.dumps({
            "key": shm["key"], "offset": shm["offset"],
            "byte_size": shm["byte_size"],
        }).encode()
        return path, {}, body
    path = f"/v2/models/{spec['model']}/infer"
    headers: Dict[str, str] = {}
    if spec.get("raw_body") is not None:
        return path, headers, bytes.fromhex(spec["raw_body"])
    header_bytes = json.dumps(spec["js"]).encode()
    body = header_bytes
    binary = spec.get("binary") or {}
    if binary:
        for name in sorted(binary):
            body += _blob_bytes(binary[name])
        headers["Inference-Header-Content-Length"] = str(len(header_bytes))
    if spec.get("header_len") is not None:
        headers["Inference-Header-Content-Length"] = str(spec["header_len"])
    return path, headers, body


def http_case(spec: dict, host: str, port: int) -> Tuple[str, Optional[str]]:
    """Run one spec over HTTP -> (outcome label, failure description)."""
    path, headers, body = _http_payload(spec)
    conn = http.client.HTTPConnection(host, port, timeout=_HTTP_TIMEOUT)
    try:
        conn.putrequest("POST", path)
        conn.putheader("Content-Type", "application/json")
        length = spec.get("content_length")
        conn.putheader(
            "Content-Length", str(length if length is not None else len(body))
        )
        for k, v in headers.items():
            conn.putheader(k, v)
        conn.endheaders()
        if body:
            try:
                conn.send(body)
            except (BrokenPipeError, ConnectionResetError):
                # The server may reject oversized bodies before reading
                # them fully; the 413 is already buffered on the socket.
                pass
        resp = conn.getresponse()
        payload = resp.read()
        status = resp.status
    except socket.timeout:
        return "hang", "no response within the client timeout"
    except (ConnectionError, http.client.HTTPException) as e:
        return "conn-error", f"connection failed: {type(e).__name__}"
    finally:
        conn.close()
    if status >= 500:
        return f"http-{status}", (
            f"HTTP {status} (server error) for mutation "
            f"'{spec['mutation']}' — malformed input must be a typed 4xx")
    if 400 <= status < 500:
        try:
            doc = json.loads(payload.decode("utf-8", "replace"))
            if not isinstance(doc.get("error"), str):
                raise ValueError
        except (ValueError, AttributeError):
            return f"http-{status}", (
                f"HTTP {status} without a JSON error body for mutation "
                f"'{spec['mutation']}' — rejections must be typed")
        return f"http-{status}", None
    return f"http-{status}", None


# -- gRPC execution --------------------------------------------------------


def grpc_case(spec: dict, channel) -> Tuple[str, Optional[str]]:
    import grpc

    from tritonclient_tpu.protocol import GRPCInferenceServiceStub, pb

    try:
        req = build_grpc_request(spec, pb)
    except Inexpressible as e:
        return "skip", f"inexpressible: {e}"
    stub = GRPCInferenceServiceStub(channel)
    call = (stub.SystemSharedMemoryRegister
            if spec["endpoint"] == "shm_register" else stub.ModelInfer)
    try:
        call(req, timeout=_GRPC_TIMEOUT)
        return "grpc-OK", None
    except grpc.RpcError as e:
        code = e.code().name
        if code in _GRPC_ALLOWED:
            return f"grpc-{code}", None
        if code == "DEADLINE_EXCEEDED":
            return f"grpc-{code}", (
                f"no response within the client deadline for mutation "
                f"'{spec['mutation']}' — hang")
        return f"grpc-{code}", (
            f"gRPC {code} for mutation '{spec['mutation']}' — malformed "
            f"input must be INVALID_ARGUMENT/RESOURCE_EXHAUSTED")


# -- the run ---------------------------------------------------------------


def run_fuzz(seed: int, requests_per_plane: int,
             planes: Tuple[str, ...] = ("http", "grpc"),
             corpus_dir: Optional[str] = None) -> dict:
    """Boot an in-process server, fuzz every requested plane, return the
    deterministic report dict."""
    import random

    from tritonclient_tpu import sanitize
    from tritonclient_tpu.server import InferenceServer

    seeds = _mutate.load_corpus(corpus_dir or _mutate._CORPUS_DIR)
    rng = random.Random(seed)
    specs = _mutate.generate_specs(
        seeds, rng, requests_per_plane, planes, expressible=expressible)

    failures: List[dict] = []
    outcome_lines: List[str] = []
    histogram: Dict[str, int] = {}
    status_counts: Dict[str, int] = {}
    executed = {p: 0 for p in planes}

    sanitize.enable("report")
    sanitize.reset()
    try:
        server = InferenceServer(
            http="http" in planes,
            grpc="grpc" in planes,
            max_request_bytes=_mutate.FUZZ_MAX_REQUEST_BYTES,
        )
        server.start()
        try:
            grpc_channel = None
            if "grpc" in planes:
                import grpc as _grpc_mod

                grpc_channel = _grpc_mod.insecure_channel(server.grpc_address)
            host, port = None, None
            if "http" in planes:
                addr = server.http_address
                host, port = addr.rsplit(":", 1)
                port = int(port)
            for spec in specs:
                for plane in spec["planes"]:
                    if plane == "http":
                        outcome, problem = http_case(spec, host, port)
                    else:
                        outcome, problem = grpc_case(spec, grpc_channel)
                    if outcome == "skip":
                        continue
                    executed[plane] += 1
                    histogram[spec["mutation"]] = (
                        histogram.get(spec["mutation"], 0) + 1)
                    status_counts[outcome] = status_counts.get(outcome, 0) + 1
                    outcome_lines.append(f"{spec['id']}:{plane}:{outcome}")
                    ok_states = ("http-200", "grpc-OK")
                    seed_doc = next(
                        s for s in seeds if s["name"] == spec["seed"])
                    if (problem is None
                            and spec["mutation"] == "baseline_valid"
                            and seed_doc.get("expect_ok")
                            and outcome not in ok_states):
                        problem = (
                            f"well-formed baseline request rejected with "
                            f"{outcome} — over-rejection")
                    if problem is not None:
                        failures.append({
                            "case": spec["id"], "plane": plane,
                            "seed": spec["seed"],
                            "mutation": spec["mutation"],
                            "outcome": outcome, "detail": problem,
                        })
            # Still-serving probe: the server must answer a well-formed
            # request after absorbing the whole corpus.
            for plane in planes:
                probe = _mutate.m_baseline_valid(seeds[0], rng)
                probe["id"] = f"probe-{plane}"
                probe["planes"] = [plane]
                if plane == "http":
                    outcome, problem = http_case(probe, host, port)
                    alive = outcome == "http-200"
                else:
                    outcome, problem = grpc_case(probe, grpc_channel)
                    alive = outcome == "grpc-OK"
                outcome_lines.append(f"probe:{plane}:{outcome}")
                if not alive:
                    failures.append({
                        "case": "probe", "plane": plane,
                        "seed": seeds[0]["name"],
                        "mutation": "baseline_valid", "outcome": outcome,
                        "detail": "server no longer serving well-formed "
                                  "requests after the fuzz run",
                    })
            if grpc_channel is not None:
                grpc_channel.close()
        finally:
            server.stop()
        sanitize.check_leaks()
        san_findings = sanitize.findings()
    finally:
        sanitize.disable()
    for f in san_findings:
        failures.append({
            "case": "tpusan", "plane": "-", "seed": "-",
            "mutation": f.rule, "outcome": "sanitizer",
            "detail": f"{f.rule} {f.path}:{f.line}: {f.message}",
        })

    failures.sort(key=lambda f: (f["case"], f["plane"], f["detail"]))
    digest = hashlib.sha256(
        "\n".join(outcome_lines).encode()).hexdigest()
    return {
        "tool": "tpufuzz",
        "seed": seed,
        "requests_per_plane": requests_per_plane,
        "planes": sorted(planes),
        "corpus": [s["name"] for s in seeds],
        "executed": {p: executed[p] for p in sorted(executed)},
        "mutations": {k: histogram[k] for k in sorted(histogram)},
        "outcomes": {k: status_counts[k] for k in sorted(status_counts)},
        "cases_digest": digest,
        "failures": failures,
    }


def report_findings(report: dict) -> List[Finding]:
    """Failures as TPU013 findings attributed to the plane front-end."""
    out = []
    for f in report["failures"]:
        path = _PLANE_FILES.get(f["plane"], "tritonclient_tpu/server")
        if f["plane"] == "-":  # sanitizer finding: keep its own path
            path = f["detail"].split(" ", 2)[1].rsplit(":", 2)[0]
        out.append(Finding(
            "TPU013", path, 1, 0,
            f"tpufuzz[{f['seed']}:{f['mutation']}:{f['case']}]: "
            f"{f['detail']}"))
    return out


def render_sarif(report: dict) -> str:
    from tritonclient_tpu.analysis._sarif import render_sarif as _render

    return _render(report_findings(report), RULES_META,
                   tool_name="tpufuzz", level_for={"TPU013": "error"})

"""The router core: membership + policy + admission behind one object.

Both router front-ends (``_http``, ``_grpc``) drive inference traffic
through the same three steps — admit the tenant, lease a replica, release
the lease on completion — so quota accounting, outstanding counts, and
the ``/metrics`` families cannot diverge between transports.
"""

from typing import Dict, Optional, Union

from tritonclient_tpu import sanitize
from tritonclient_tpu.fleet._admission import AdmissionController, TenantQuota
from tritonclient_tpu.fleet._policy import Policy, affinity_select, make_policy
from tritonclient_tpu.fleet._replica import Replica, ReplicaSet
from tritonclient_tpu.protocol._literals import (
    QUOTA_REASONS,
    STATUS_OVER_QUOTA,
)

ROUTER_NAME = "triton-tpu-fleet"


class FleetError(Exception):
    """Router-side error with an HTTP-ish status hint (the fleet analog
    of ``CoreError``). ``reason`` carries the quota-rejection reason for
    429s so front-ends can label without string-parsing."""

    def __init__(self, msg: str, status: int = 500,
                 reason: Optional[str] = None):
        super().__init__(msg)
        self.status = status
        self.reason = reason


class _Lease:
    """One admitted, routed request: pairs an admission slot with a
    replica's outstanding count. ``release`` is idempotent so error
    paths can release defensively."""

    __slots__ = ("_router", "replica", "tenant", "_done")

    def __init__(self, router: "FleetRouter", replica: Replica,
                 tenant: str):
        self._router = router
        self.replica = replica
        self.tenant = tenant
        self._done = False

    def release(self, failed: bool = False):
        if self._done:
            return
        self._done = True
        self._router._set.release(self.replica, failed=failed)
        self._router.admission.release(self.tenant)


class FleetRouter:
    """Route unary requests and sticky streams across N replicas."""

    def __init__(self, replicas: Optional[ReplicaSet] = None,
                 policy: Union[str, Policy] = "least-outstanding",
                 quotas: Optional[Dict[str, TenantQuota]] = None,
                 admission: Optional[AdmissionController] = None,
                 pressure_queue_depth: int = 32):
        self._set = replicas if replicas is not None else ReplicaSet()
        self.policy = (
            policy if isinstance(policy, Policy) else make_policy(policy)
        )
        self.admission = admission or AdmissionController(quotas)
        # Fleet-pressure threshold: with EVERY routable replica's scraped
        # queue depth at/above this, low-priority tenants shed at
        # admission (reason=pressure).
        self.pressure_queue_depth = int(pressure_queue_depth)
        # Policy selection is not thread-safe by contract (round-robin
        # counters, p2c RNG); one small named lock serializes it.
        self._policy_lock = sanitize.named_lock(
            "fleet.FleetRouter._policy_lock"
        )

    # -- membership passthrough ----------------------------------------------

    @property
    def replica_set(self) -> ReplicaSet:
        return self._set

    def add_replica(self, name: str, http_address: str,
                    grpc_address: str = "") -> Replica:
        return self._set.add(name, http_address, grpc_address)

    def drain_replica(self, name: str, wait_s: float = 30.0) -> dict:
        return self._set.drain(name, wait_s=wait_s)

    def undrain_replica(self, name: str) -> dict:
        return self._set.undrain(name)

    def start(self):
        self._set.start()
        return self

    def stop(self):
        self._set.stop()

    # -- routing --------------------------------------------------------------

    def ready(self) -> bool:
        return bool(self._set.routable())

    def under_pressure(self) -> bool:
        routable = self._set.routable()
        return bool(routable) and all(
            r.queue_depth >= self.pressure_queue_depth for r in routable
        )

    def begin(self, tenant: str = "", affinity_key: str = "",
              exclude=()) -> _Lease:
        """Admit + lease for one request/stream; raises FleetError 429
        (over quota) or 503 (no routable replicas). The caller MUST
        ``release()`` the lease when the forwarded work completes.
        ``exclude`` names replicas a retry must avoid (the one that just
        failed)."""
        reason = self.admission.admit(
            tenant, under_pressure=self.under_pressure()
        )
        if reason is not None:
            raise FleetError(
                f"tenant '{tenant or 'default'}' over quota ({reason})",
                STATUS_OVER_QUOTA, reason=reason,
            )
        candidates = [
            r for r in self._set.routable() if r.name not in exclude
        ]
        if not candidates:
            self.admission.release(tenant)
            raise FleetError("no ready replicas in the fleet", 503)
        replica = affinity_select(candidates, affinity_key)
        if replica is None:
            with self._policy_lock:
                replica = self.policy.select(candidates)
        self._set.acquire(replica)
        return _Lease(self, replica, tenant)

    def pick_any(self) -> Replica:
        """A ready replica for non-inference traffic (metadata, stats,
        flight-recorder dumps): least-outstanding without admission."""
        candidates = self._set.routable()
        if not candidates:
            raise FleetError("no ready replicas in the fleet", 503)
        return min(candidates, key=lambda r: (r.outstanding, r.name))

    # -- introspection --------------------------------------------------------

    def status(self) -> dict:
        return {
            "kind": "fleet_status",
            "name": ROUTER_NAME,
            "policy": self.policy.name,
            "ready": self.ready(),
            "under_pressure": self.under_pressure(),
            "replicas": [r.as_dict() for r in self._set.replicas()],
            "admission": self.admission.status(),
        }

    def prometheus_metrics(self) -> str:
        """The router's own exposition: fleet membership, per-replica
        outstanding, and per-tenant quota rejections. Same exposition
        discipline as the replicas' /metrics (validated by
        scripts/check_metrics_exposition.py): stable label sets, every
        canonical reason row rendered per seen tenant."""
        def esc(v: str) -> str:
            return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                    .replace("\n", "\\n"))

        replicas = self._set.replicas()
        lines = []
        metric = "nv_fleet_replica_up"
        lines.append(
            f"# HELP {metric} Whether the fleet router considers a "
            "replica routable (1 = ready)"
        )
        lines.append(f"# TYPE {metric} gauge")
        for r in replicas:
            lines.append(
                f'{metric}{{replica="{esc(r.name)}"}} '
                f"{1 if r.routable else 0}"
            )
        metric = "nv_fleet_replica_outstanding"
        lines.append(
            f"# HELP {metric} Requests currently leased to a replica by "
            "the router (streams count one for their lifetime)"
        )
        lines.append(f"# TYPE {metric} gauge")
        for r in replicas:
            lines.append(
                f'{metric}{{replica="{esc(r.name)}"}} {r.outstanding}'
            )
        metric = "nv_fleet_replica_queue_depth"
        lines.append(
            f"# HELP {metric} Last scraped dynamic-batcher queue depth "
            "per replica (summed over models)"
        )
        lines.append(f"# TYPE {metric} gauge")
        for r in replicas:
            lines.append(
                f'{metric}{{replica="{esc(r.name)}"}} {r.queue_depth}'
            )
        metric = "nv_fleet_requests_total"
        lines.append(
            f"# HELP {metric} Requests routed to a replica by the router"
        )
        lines.append(f"# TYPE {metric} counter")
        for r in replicas:
            lines.append(
                f'{metric}{{replica="{esc(r.name)}"}} {r.requests_total}'
            )
        metric = "nv_fleet_tenant_quota_rejections_total"
        lines.append(
            f"# HELP {metric} Requests rejected at per-tenant admission, "
            "by reason"
        )
        lines.append(f"# TYPE {metric} counter")
        for tenant, reasons in self.admission.rejection_counts().items():
            for reason in QUOTA_REASONS:
                lines.append(
                    f'{metric}{{tenant="{esc(tenant)}"'
                    f',reason="{reason}"}} {reasons[reason]}'
                )
        return "\n".join(lines) + "\n"

"""The router core: membership + policy + admission behind one object.

Both router front-ends (``_http``, ``_grpc``) drive inference traffic
through the same three steps — admit the tenant, lease a replica, release
the lease on completion — so quota accounting, outstanding counts, and
the ``/metrics`` families cannot diverge between transports.
"""

import base64
import json
from typing import Dict, List, Optional, Tuple, Union

from tritonclient_tpu import sanitize
from tritonclient_tpu.fleet._admission import AdmissionController, TenantQuota
from tritonclient_tpu.fleet._fleetscope import FleetScope
from tritonclient_tpu.fleet._policy import Policy, affinity_select, make_policy
from tritonclient_tpu.fleet._replica import Replica, ReplicaSet, http_call
from tritonclient_tpu.resilience import CircuitBreaker, RetryPolicy
from tritonclient_tpu.protocol._literals import (
    BREAKER_STATE_VALUES,  # noqa: F401 — re-exported for front-ends
    EP_FLEET_COHORTS,
    EP_FLEET_SLO,
    FLEET_REPLICA_ROUTE_RE,
    HEDGE_OUTCOMES,
    QUOTA_REASONS,
    RETRY_REASONS,
    SLO_WINDOW_SLOW,
    STATUS_INVALID,
    STATUS_OVER_QUOTA,
)

ROUTER_NAME = "triton-tpu-fleet"


class FleetError(Exception):
    """Router-side error with an HTTP-ish status hint (the fleet analog
    of ``CoreError``). ``reason`` carries the quota-rejection reason for
    429s so front-ends can label without string-parsing."""

    def __init__(self, msg: str, status: int = 500,
                 reason: Optional[str] = None):
        super().__init__(msg)
        self.status = status
        self.reason = reason


class _Lease:
    """One admitted, routed request: pairs an admission slot with a
    replica's outstanding count. ``release`` is idempotent so error
    paths can release defensively."""

    __slots__ = ("_router", "replica", "tenant", "_done")

    def __init__(self, router: "FleetRouter", replica: Replica,
                 tenant: str):
        self._router = router
        self.replica = replica
        self.tenant = tenant
        self._done = False

    def release(self, failed: bool = False):
        if self._done:
            return
        self._done = True
        self._router._set.release(self.replica, failed=failed)
        self._router.admission.release(self.tenant)


class FleetRouter:
    """Route unary requests and sticky streams across N replicas."""

    def __init__(self, replicas: Optional[ReplicaSet] = None,
                 policy: Union[str, Policy] = "least-outstanding",
                 quotas: Optional[Dict[str, TenantQuota]] = None,
                 admission: Optional[AdmissionController] = None,
                 pressure_queue_depth: int = 32,
                 retry_policy: Optional[RetryPolicy] = None,
                 breaker_failure_threshold: int = 3,
                 breaker_reset_s: float = 2.0,
                 hedge_us: Optional[int] = None,
                 hedge_all: bool = False,
                 fleetscope: Optional[FleetScope] = None,
                 journal_path: Optional[str] = None):
        self._set = replicas if replicas is not None else ReplicaSet()
        self.policy = (
            policy if isinstance(policy, Policy) else make_policy(policy)
        )
        self.admission = admission or AdmissionController(quotas)
        # Fleet-pressure threshold: with EVERY routable replica's scraped
        # queue depth at/above this, low-priority tenants shed at
        # admission (reason=pressure).
        self.pressure_queue_depth = int(pressure_queue_depth)
        # Failover policy shared by both front-ends: connect/send-phase
        # proxy failures replay on a different replica; post-send
        # failures replay only with an idempotency key (the PR-8
        # unconditional "one safe retry" could double-execute).
        self.retry_policy = retry_policy if retry_policy is not None else (
            RetryPolicy(max_attempts=3, base_delay_s=0.02, max_delay_s=0.5)
        )
        # Per-replica circuit breakers: a replica that keeps failing
        # proxied exchanges is excluded from candidate selection for
        # ``breaker_reset_s`` even while the (slower) health prober still
        # calls it READY; the next request after cooldown is the probe.
        self.breaker_failure_threshold = int(breaker_failure_threshold)
        self.breaker_reset_s = float(breaker_reset_s)
        self._breakers: Dict[str, CircuitBreaker] = {}
        # Hedged unary inference: after ``hedge_us`` with no primary
        # response, a second attempt goes to a different replica and the
        # loser is cancelled. Hedging doubles execution on the slow
        # path, so it is gated on the idempotency key unless
        # ``hedge_all`` opts every request in.
        self.hedge_us = int(hedge_us) if hedge_us else None
        self.hedge_all = bool(hedge_all)
        self._hedge_counts = {outcome: 0 for outcome in HEDGE_OUTCOMES}
        # Journaled admin state: every successfully fanned-out admin
        # operation (shm registration, repository load/unload, trace/log
        # settings) in arrival order, replayed to a replica that rejoins
        # after a crash so it is servable, not merely READY.
        self._journal: List[Tuple[str, str, bytes, dict]] = []
        # Rejoin listeners: front-ends register cleanup here (e.g. the
        # HTTP proxy invalidates pooled keep-alive connections to the
        # dead incarnation) — run BEFORE the admin-state replay.
        self._rejoin_listeners: List = []
        self._resilience_lock = sanitize.named_lock(
            "fleet.FleetRouter._resilience_lock"
        )
        # Policy selection is not thread-safe by contract (round-robin
        # counters, p2c RNG); one small named lock serializes it.
        self._policy_lock = sanitize.named_lock(
            "fleet.FleetRouter._policy_lock"
        )
        # The fleet-wide SLO plane: scrape time series + merged sketches
        # (fed by the prober via the observer hook below), burn windows
        # and cohort detection (fed by the front-ends' record_request
        # calls), and the proxy-side flight ring.
        self.fleetscope = (
            fleetscope if fleetscope is not None else FleetScope()
        )
        # Optional journal persistence: every record_admin entry appends
        # one JSON line here, and a restarting router reloads the file —
        # SLO objectives and cohort assignments survive the restart.
        self._journal_path = journal_path
        if journal_path:
            self._load_journal(journal_path)
        self._set.set_on_rejoin(self._replay_admin_state)
        self._set.set_observer(self.fleetscope)

    # -- membership passthrough ----------------------------------------------

    @property
    def replica_set(self) -> ReplicaSet:
        return self._set

    def add_replica(self, name: str, http_address: str,
                    grpc_address: str = "") -> Replica:
        return self._set.add(name, http_address, grpc_address)

    def drain_replica(self, name: str, wait_s: float = 30.0) -> dict:
        return self._set.drain(name, wait_s=wait_s)

    def undrain_replica(self, name: str) -> dict:
        return self._set.undrain(name)

    def start(self):
        self._set.start()
        return self

    def stop(self):
        self._set.stop()

    # -- routing --------------------------------------------------------------

    def ready(self) -> bool:
        return bool(self._set.routable())

    def under_pressure(self) -> bool:
        routable = [s for s in self._set.snapshot() if s["routable"]]
        return bool(routable) and all(
            s["queue_depth"] >= self.pressure_queue_depth
            for s in routable
        )

    def begin(self, tenant: str = "", affinity_key: str = "",
              exclude=()) -> _Lease:
        """Admit + lease for one request/stream; raises FleetError 429
        (over quota) or 503 (no routable replicas). The caller MUST
        ``release()`` the lease when the forwarded work completes.
        ``exclude`` names replicas a retry must avoid (the one that just
        failed)."""
        reason = self.admission.admit(
            tenant, under_pressure=self.under_pressure()
        )
        if reason is not None:
            raise FleetError(
                f"tenant '{tenant or 'default'}' over quota ({reason})",
                STATUS_OVER_QUOTA, reason=reason,
            )
        candidates = [
            r for r in self._set.routable()
            if r.name not in exclude
            and not self.breaker_for(r.name).blocked()
        ]
        if not candidates:
            self.admission.release(tenant)
            raise FleetError("no ready replicas in the fleet", 503)
        replica = affinity_select(candidates, affinity_key)
        if replica is None:
            with self._policy_lock:
                replica = self.policy.select(candidates)
        self._set.acquire(replica)
        return _Lease(self, replica, tenant)

    # -- resilience -----------------------------------------------------------

    def breaker_for(self, replica_name: str) -> CircuitBreaker:
        with self._resilience_lock:
            breaker = self._breakers.get(replica_name)
            if breaker is None:
                breaker = self._breakers[replica_name] = CircuitBreaker(
                    endpoint=replica_name,
                    failure_threshold=self.breaker_failure_threshold,
                    reset_timeout_s=self.breaker_reset_s,
                )
            return breaker

    def breakers(self) -> Dict[str, CircuitBreaker]:
        with self._resilience_lock:
            return dict(self._breakers)

    def note_replica_result(self, replica: Replica, ok: bool):
        """Feed one proxied exchange's outcome into the replica's
        breaker (both front-ends call this on every attempt)."""
        breaker = self.breaker_for(replica.name)
        if ok:
            breaker.on_success()
        else:
            breaker.on_failure()

    def note_hedge(self, outcome: str):
        with self._resilience_lock:
            self._hedge_counts[outcome] = (
                self._hedge_counts.get(outcome, 0) + 1
            )

    def hedge_counts(self) -> Dict[str, int]:
        with self._resilience_lock:
            return dict(self._hedge_counts)

    def hedge_enabled(self, idempotent: bool) -> bool:
        return self.hedge_us is not None and (idempotent or self.hedge_all)

    # -- journaled admin state ------------------------------------------------

    def record_admin(self, method: str, path: str, body: bytes,
                     headers: Optional[dict] = None):
        """Journal one successfully fanned-out admin operation for
        replay to rejoining replicas. An unregister/unload does not
        erase its register/load entry — the journal is an ordered log,
        so replay converges to the same end state either way. Router-
        local ``v2/fleet/*`` entries (SLO objectives, cohort
        assignments) ride the same log but are applied locally on
        reload, never replayed to replicas."""
        entry = (method, path, bytes(body or b""), dict(headers or {}))
        with self._resilience_lock:
            self._journal.append(entry)
            if self._journal_path:
                line = json.dumps({
                    "method": entry[0],
                    "path": entry[1],
                    "body": base64.b64encode(entry[2]).decode("ascii"),
                    "headers": entry[3],
                })
                try:
                    with open(self._journal_path, "a",
                              encoding="utf-8") as fh:
                        fh.write(line + "\n")
                except OSError:
                    # Persistence is best-effort: a full disk must not
                    # fail the admin operation that already fanned out.
                    pass

    def _load_journal(self, path: str):
        """Reload persisted admin entries at construction: the
        in-memory journal is rebuilt for replica replay, and
        router-local ``v2/fleet/*`` entries are applied to fleetscope
        so SLO/cohort state survives a router restart."""
        try:
            with open(path, "r", encoding="utf-8") as fh:
                lines = fh.readlines()
        except OSError:
            return
        for raw in lines:
            raw = raw.strip()
            if not raw:
                continue
            try:
                doc = json.loads(raw)
                entry = (
                    str(doc["method"]),
                    str(doc["path"]),
                    base64.b64decode(doc.get("body", "") or ""),
                    dict(doc.get("headers") or {}),
                )
            except (ValueError, KeyError, TypeError):
                continue  # a torn tail line must not block startup
            with self._resilience_lock:
                self._journal.append(entry)
            self._apply_fleet_entry(entry)

    def _apply_fleet_entry(self, entry: Tuple[str, str, bytes, dict]):
        """Apply one journaled router-local fleet-admin entry to
        fleetscope state (journal reload path)."""
        _method, path, body, _headers = entry
        if not path.startswith("v2/fleet/"):
            return
        try:
            doc = json.loads(body.decode("utf-8") or "{}")
        except (ValueError, UnicodeDecodeError):
            return
        if not isinstance(doc, dict):
            return
        if path == EP_FLEET_SLO:
            try:
                if doc.get("remove"):
                    self.fleetscope.remove_objective(
                        doc.get("model", ""), doc.get("tenant", "")
                    )
                else:
                    self.fleetscope.set_objective(doc)
            except (ValueError, TypeError):
                pass
            return
        if path == EP_FLEET_COHORTS:
            try:
                self.fleetscope.assign_cohort(
                    doc.get("replica", ""), doc.get("cohort", "")
                )
            except ValueError:
                pass
            return
        m = FLEET_REPLICA_ROUTE_RE.match(path)
        if m is not None and m.group("action") == "cohort":
            try:
                self.fleetscope.assign_cohort(
                    m.group("replica"), doc.get("cohort", "")
                )
            except ValueError:
                pass

    def admin_journal(self) -> List[Tuple[str, str, bytes, dict]]:
        with self._resilience_lock:
            return list(self._journal)

    def add_rejoin_listener(self, listener):
        """``listener(replica)`` runs when a crashed replica rejoins,
        before its admin state is replayed (connection-pool hygiene)."""
        with self._resilience_lock:
            self._rejoin_listeners.append(listener)

    def _replay_admin_state(self, replica: Replica) -> bool:
        """Replay the journal to a rejoining replica (the ReplicaSet's
        ``on_rejoin`` hook, called with no locks held, BEFORE the
        replica becomes routable). Returns False — leaving the replica
        unroutable until the next probe retries — if any entry fails to
        apply."""
        with self._resilience_lock:
            listeners = list(self._rejoin_listeners)
        for listener in listeners:
            try:
                listener(replica)
            except Exception:  # noqa: BLE001 — hygiene must not block rejoin
                pass
        for method, path, body, headers in self.admin_journal():
            if path.startswith("v2/fleet/"):
                # Router-local entries (SLO objectives, cohort
                # assignments): a replica would answer 404 and block its
                # own rejoin forever.
                continue
            try:
                status, _ = http_call(
                    replica.http_address, method, path, body=body,
                    headers=headers, timeout_s=self._set.probe_timeout_s,
                )
            except OSError:
                return False
            if status >= STATUS_INVALID:
                return False
        return True

    def merged_flight_dump(self) -> dict:
        """The fleet-wide flight-recorder dump: fan out to every READY
        replica's dump endpoint and merge with the router's own
        proxy-side records (see FleetScope.merged_flight_dump)."""
        targets = [
            (r.name, r.http_address) for r in self._set.routable()
        ]
        return self.fleetscope.merged_flight_dump(targets)

    def pick_any(self) -> Replica:
        """A ready replica for non-inference traffic (metadata, stats,
        flight-recorder dumps): least-outstanding without admission."""
        candidates = self._set.routable()
        if not candidates:
            raise FleetError("no ready replicas in the fleet", 503)
        return min(candidates, key=lambda r: (r.outstanding, r.name))

    # -- introspection --------------------------------------------------------

    def status(self) -> dict:
        return {
            "kind": "fleet_status",
            "name": ROUTER_NAME,
            "policy": self.policy.name,
            "ready": self.ready(),
            "under_pressure": self.under_pressure(),
            "replicas": self._set.snapshot(),
            "admission": self.admission.status(),
        }

    def prometheus_metrics(self) -> str:
        """The router's own exposition: fleet membership, per-replica
        outstanding, and per-tenant quota rejections. Same exposition
        discipline as the replicas' /metrics (validated by
        scripts/check_metrics_exposition.py): stable label sets, every
        canonical reason row rendered per seen tenant."""
        def esc(v: str) -> str:
            return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                    .replace("\n", "\\n"))

        # One locked snapshot for the whole exposition: the prober
        # mutates these counters under the set lock (TPU009), and a
        # scrape that reads half-updated state would pair a new
        # queue_depth with an old restarts count.
        replicas = self._set.snapshot()
        lines = []
        metric = "nv_fleet_replica_up"
        lines.append(
            f"# HELP {metric} Whether the fleet router considers a "
            "replica routable (1 = ready)"
        )
        lines.append(f"# TYPE {metric} gauge")
        for r in replicas:
            lines.append(
                f'{metric}{{replica="{esc(r["name"])}"}} '
                f"{1 if r['routable'] else 0}"
            )
        metric = "nv_fleet_replica_outstanding"
        lines.append(
            f"# HELP {metric} Requests currently leased to a replica by "
            "the router (streams count one for their lifetime)"
        )
        lines.append(f"# TYPE {metric} gauge")
        for r in replicas:
            lines.append(
                f'{metric}{{replica="{esc(r["name"])}"}} '
                f"{r['outstanding']}"
            )
        metric = "nv_fleet_replica_queue_depth"
        lines.append(
            f"# HELP {metric} Last scraped dynamic-batcher queue depth "
            "per replica (summed over models)"
        )
        lines.append(f"# TYPE {metric} gauge")
        for r in replicas:
            lines.append(
                f'{metric}{{replica="{esc(r["name"])}"}} '
                f"{r['queue_depth']}"
            )
        metric = "nv_fleet_requests_total"
        lines.append(
            f"# HELP {metric} Requests routed to a replica by the router"
        )
        lines.append(f"# TYPE {metric} counter")
        for r in replicas:
            lines.append(
                f'{metric}{{replica="{esc(r["name"])}"}} '
                f"{r['requests_total']}"
            )
        metric = "nv_fleet_replica_restarts_total"
        lines.append(
            f"# HELP {metric} Times a replica rejoined after a crash "
            "and had the router's journaled admin state replayed"
        )
        lines.append(f"# TYPE {metric} counter")
        for r in replicas:
            lines.append(
                f'{metric}{{replica="{esc(r["name"])}"}} '
                f"{r['restarts']}"
            )
        metric = "nv_fleet_scrape_age_s"
        lines.append(
            f"# HELP {metric} Seconds since the router last successfully "
            "scraped a replica's /metrics (staleness signal)"
        )
        lines.append(f"# TYPE {metric} gauge")
        for r in replicas:
            lines.append(
                f'{metric}{{replica="{esc(r["name"])}"}} '
                f"{r['scrape_age_s']:.6f}"
            )
        metric = "nv_fleet_scrape_failures_total"
        lines.append(
            f"# HELP {metric} Prober ticks that did not yield a metrics "
            "scrape for a replica"
        )
        lines.append(f"# TYPE {metric} counter")
        for r in replicas:
            lines.append(
                f'{metric}{{replica="{esc(r["name"])}"}} '
                f"{r['scrape_failures']}"
            )
        metric = "nv_client_breaker_state"
        lines.append(
            f"# HELP {metric} Circuit-breaker state per replica "
            "endpoint (0=closed, 1=half_open, 2=open)"
        )
        lines.append(f"# TYPE {metric} gauge")
        for r in replicas:
            lines.append(
                f'{metric}{{endpoint="{esc(r["name"])}"}} '
                f"{self.breaker_for(r['name']).state_value()}"
            )
        metric = "nv_client_retries_total"
        lines.append(
            f"# HELP {metric} Replays authorized by the router's "
            "RetryPolicy, by canonical reason"
        )
        lines.append(f"# TYPE {metric} counter")
        retry_counts = self.retry_policy.snapshot()
        for reason in RETRY_REASONS:
            lines.append(
                f'{metric}{{reason="{reason}"}} '
                f"{retry_counts.get(reason, 0)}"
            )
        metric = "nv_fleet_hedges_total"
        lines.append(
            f"# HELP {metric} Hedged unary requests by outcome "
            "(primary/hedge = who won, failed = both attempts failed)"
        )
        lines.append(f"# TYPE {metric} counter")
        hedges = self.hedge_counts()
        for outcome in HEDGE_OUTCOMES:
            lines.append(
                f'{metric}{{outcome="{outcome}"}} '
                f"{hedges.get(outcome, 0)}"
            )
        metric = "nv_fleet_tenant_quota_rejections_total"
        lines.append(
            f"# HELP {metric} Requests rejected at per-tenant admission, "
            "by reason"
        )
        lines.append(f"# TYPE {metric} counter")
        for tenant, reasons in self.admission.rejection_counts().items():
            for reason in QUOTA_REASONS:
                lines.append(
                    f'{metric}{{tenant="{esc(tenant)}"'
                    f',reason="{reason}"}} {reasons[reason]}'
                )
        burn_rows = self.fleetscope.burn_rows()
        metric = "nv_fleet_slo_burn_rate"
        lines.append(
            f"# HELP {metric} Error-budget burn rate per SLO objective "
            "and window (1.0 = consuming budget exactly at the allowed "
            "rate)"
        )
        lines.append(f"# TYPE {metric} gauge")
        for row in burn_rows:
            lines.append(
                f'{metric}{{model="{esc(row["model"])}"'
                f',tenant="{esc(row["tenant"])}"'
                f',window="{row["window"]}"}} '
                f"{row['burn_rate']:.6f}"
            )
        metric = "nv_fleet_slo_budget_remaining"
        lines.append(
            f"# HELP {metric} Fraction of the error budget left over "
            "the slow window, per SLO objective (in [0, 1])"
        )
        lines.append(f"# TYPE {metric} gauge")
        for row in burn_rows:
            if row["window"] != SLO_WINDOW_SLOW:
                continue
            lines.append(
                f'{metric}{{model="{esc(row["model"])}"'
                f',tenant="{esc(row["tenant"])}"}} '
                f"{row['budget_remaining']:.6f}"
            )
        metric = "nv_fleet_cohort_requests_total"
        lines.append(
            f"# HELP {metric} Requests routed per replica cohort "
            "(baseline vs canary attribution)"
        )
        lines.append(f"# TYPE {metric} counter")
        for cohort, count in sorted(
            self.fleetscope.cohort_request_counts().items()
        ):
            lines.append(
                f'{metric}{{cohort="{esc(cohort)}"}} {count}'
            )
        return "\n".join(lines) + "\n"

"""fleetscope: fleet-wide time-series retention + merged observability.

Until now every observability plane stopped at one replica's boundary:
the router scraped ``/metrics`` but kept only the LATEST sample, and
flight records died inside each replica. fleetscope is the fleet-level
substrate the autoscaler (ROADMAP 4) and canary auto-rollback
(ROADMAP 5) consume:

* **time-series retention** — per-replica ring buffers (bounded by
  ``TPU_FLEETSCOPE_WINDOWS``) of parsed counter *deltas* (rates,
  monotonicity-checked so a replica restart resets cleanly instead of
  producing a huge negative rate) and gauge samples, riding the
  prober's existing scrape tick;
* **exact sketch merges** — each scrape also fetches the replica's raw
  DDSketch state (``GET v2/debug/sketches``); fleet-wide
  per-model/per-stage p50/p99/p999 come from bucket-wise
  :meth:`~tritonclient_tpu._sketch.LatencySketch.merge` (exact — never
  an approximation over resolved quantiles);
* **request plane** — the router's proxy path reports every routed
  request (:meth:`FleetScope.record_request`), feeding the SLO burn
  windows, the cohort detector's per-cohort sketches, and a bounded
  proxy-side flight ring (the router half of the merged timeline);
* **merged flight dump** — :meth:`merged_flight_dump` fans out to every
  READY replica's PR-6 dump endpoint, stamps each record with the
  replica name, and merges the router's proxy records keyed by
  traceparent, so ONE dump shows the full router→replica timeline.

Locking: one named lock guards all retained state; scrape/flight I/O
always happens OUTSIDE it (the prober calls
:meth:`observe_scrape` with already-fetched text, and the flight
fan-out collects replica dumps before taking the lock).
"""

import re
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from tritonclient_tpu import _memscope, sanitize
from tritonclient_tpu._sketch import LatencySketch
from tritonclient_tpu.fleet._replica import http_call
from tritonclient_tpu.fleet._slo import (
    CohortDetector,
    SloRegistry,
    max_windows,
    window_s,
)
from tritonclient_tpu.protocol._literals import (
    EP_FLIGHT_RECORDER,
)

#: A replica whose last successful scrape (or routed request) is older
#: than this is "stale": its samples are withheld from cohort verdicts
#: (``insufficient-data``) instead of silently trusted.
DEFAULT_STALE_AFTER_S = 30.0

#: Proxy-side flight ring bound (router half of the merged timeline).
_DEFAULT_FLIGHT_RING = 512

_SERIES_RE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)(\{[^}]*\})?\s+([0-9.eE+-]+|NaN)\s*$"
)
_TYPE_RE = re.compile(r"^# TYPE\s+(\S+)\s+(\S+)\s*$")


def parse_exposition(text: str) -> Tuple[Dict[str, float], Dict[str, float]]:
    """Split one Prometheus exposition into ``(counters, gauges)`` maps
    of full series id (``name{labels}``) -> value. Summary/untyped
    families are ignored — rates only make sense on counters and
    point-in-time values on gauges."""
    types: Dict[str, str] = {}
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    for line in text.splitlines():
        m = _TYPE_RE.match(line)
        if m:
            types[m.group(1)] = m.group(2)
            continue
        if line.startswith("#"):
            continue
        m = _SERIES_RE.match(line)
        if m is None:
            continue
        name, labels, raw = m.group(1), m.group(2) or "", m.group(3)
        try:
            value = float(raw)
        except ValueError:
            continue
        kind = types.get(name)
        if kind == "counter":
            counters[name + labels] = value
        elif kind == "gauge":
            gauges[name + labels] = value
    return counters, gauges


class _ReplicaSeries:
    """One replica's retained scrape history (owned by FleetScope;
    mutated only under its lock)."""

    __slots__ = ("last_counters", "last_t", "last_scrape_t",
                 "scrape_failures", "resets", "ring", "sketches",
                 "last_restarts")

    def __init__(self, limit: int):
        self.last_counters: Dict[str, float] = {}
        self.last_t: Optional[float] = None
        self.last_scrape_t: Optional[float] = None
        self.scrape_failures = 0
        # Counter resets observed (value decreased — the replica
        # restarted between scrapes); cross-checked against the
        # router's nv_fleet_replica_restarts_total in dumps.
        self.resets = 0
        self.last_restarts = 0
        # ring of {"t", "rates": {series: per-second rate},
        #          "gauges": {series: value}}
        self.ring: deque = deque(maxlen=limit)
        # model -> stage -> latest raw sketch doc from the replica
        self.sketches: Dict[str, Dict[str, dict]] = {}


class FleetScope:
    """Fleet-wide SLO plane state: scrape time series, merged sketches,
    SLO burn windows, cohort detection, and the proxy flight ring."""

    def __init__(self, clock=time.monotonic,
                 bucket_s: Optional[float] = None,
                 windows: Optional[int] = None,
                 stale_after_s: float = DEFAULT_STALE_AFTER_S,
                 slo: Optional[SloRegistry] = None,
                 cohorts: Optional[CohortDetector] = None,
                 flight_ring: int = _DEFAULT_FLIGHT_RING):
        self._clock = clock
        self.bucket_s = float(bucket_s) if bucket_s else window_s()
        self.windows = int(windows) if windows else max_windows()
        self.stale_after_s = float(stale_after_s)
        self.slo = slo if slo is not None else SloRegistry()
        self.cohorts = cohorts if cohorts is not None else CohortDetector()
        self._series: Dict[str, _ReplicaSeries] = {}
        self._flight: deque = deque(maxlen=max(int(flight_ring), 16))
        self._flight_seq = 0
        self._requests_by_cohort: Dict[str, int] = {}
        self._lock = sanitize.named_lock("fleet.FleetScope._lock")

    # -- clock ----------------------------------------------------------------

    def bucket_index(self, now: Optional[float] = None) -> int:
        now = self._clock() if now is None else now
        return int(now / self.bucket_s)

    # -- scrape plane (prober-driven) -----------------------------------------

    def observe_scrape(self, replica: str, ok: bool,
                       metrics_text: str = "",
                       sketches_doc: Optional[dict] = None,
                       restarts: int = 0,
                       now: Optional[float] = None):
        """Absorb one prober tick for ``replica``. ``ok=False`` counts a
        scrape failure (staleness accrues until the next success).
        Parsing happens outside the lock — only the ring mutation and
        delta bookkeeping are locked."""
        now = self._clock() if now is None else now
        if not ok:
            with self._lock:
                series = self._series.get(replica)
                if series is None:
                    series = self._series[replica] = _ReplicaSeries(
                        self.windows
                    )
                series.scrape_failures += 1
            return
        counters, gauges = parse_exposition(metrics_text or "")
        with self._lock:
            series = self._series.get(replica)
            if series is None:
                series = self._series[replica] = _ReplicaSeries(
                    self.windows
                )
            rates: Dict[str, float] = {}
            dt = (now - series.last_t) if series.last_t is not None else 0.0
            restarted = restarts > series.last_restarts
            for key, value in counters.items():
                prev = series.last_counters.get(key)
                if prev is None or dt <= 0:
                    continue
                delta = value - prev
                if delta < 0:
                    # Monotonicity break: the replica restarted and its
                    # counters reset to zero — the delta since restart
                    # is the new value (Prometheus reset semantics).
                    series.resets += 1
                    delta = value
                rates[key] = delta / dt
            _ = restarted  # cross-check surface: dumps expose both
            series.last_counters = counters
            series.last_restarts = max(restarts, series.last_restarts)
            series.last_t = now
            series.last_scrape_t = now
            series.ring.append({
                "t": now,
                "bucket": self.bucket_index(now),
                "rates": rates,
                "gauges": gauges,
            })
            if sketches_doc and isinstance(sketches_doc, dict):
                models = sketches_doc.get("models")
                if isinstance(models, dict):
                    series.sketches = models

    # -- request plane (router-driven) ----------------------------------------

    def record_request(self, model: str, tenant: str, duration_us: int,
                       ok: bool, replica: str, trace_id: str = "",
                       now: Optional[float] = None,
                       wall_time_s: Optional[float] = None):
        """One routed request's outcome, observed at the router: feeds
        the SLO burn windows, the cohort sketches, and the proxy-side
        flight ring (the router half of the merged timeline)."""
        now = self._clock() if now is None else now
        index = self.bucket_index(now)
        with self._lock:
            self.slo.record(model, tenant, duration_us, ok, index,
                            self.windows)
            self.cohorts.record(replica, duration_us, ok, index,
                                self.windows)
            cohort = self.cohorts.cohort_of(replica)
            self._requests_by_cohort[cohort] = (
                self._requests_by_cohort.get(cohort, 0) + 1
            )
            self._flight_seq += 1
            self._flight.append({
                "seq": self._flight_seq,
                "model_name": model,
                "model_version": "",
                "request_id": "",
                "trace_id": trace_id or "",
                "parent_span_id": "",
                "duration_us": int(duration_us),
                "status": "ok" if ok else "error",
                "error": "" if ok else "proxied request failed",
                "stages_us": {"proxy": int(duration_us)},
                "timestamps": {},
                "attributes": {"tenant": tenant, "fleet.replica": replica},
                "wall_time_s": (
                    time.time() if wall_time_s is None else wall_time_s
                ),
                "replica": "router",
            })

    # -- staleness ------------------------------------------------------------

    def stale_replicas(self, replicas: List[str],
                       now: Optional[float] = None) -> List[str]:
        """Replicas whose last successful scrape is missing or older
        than ``stale_after_s`` — their cohorts answer
        ``insufficient-data`` rather than judging on old samples."""
        now = self._clock() if now is None else now
        stale = []
        with self._lock:
            for name in replicas:
                series = self._series.get(name)
                if (series is None or series.last_scrape_t is None
                        or now - series.last_scrape_t
                        > self.stale_after_s):
                    stale.append(name)
        return stale

    def scrape_health(self) -> Dict[str, dict]:
        """Per-replica scrape bookkeeping for dumps/status."""
        now = self._clock()
        with self._lock:
            return {
                name: {
                    "scrape_age_s": (
                        now - series.last_scrape_t
                        if series.last_scrape_t is not None else None
                    ),
                    "scrape_failures": series.scrape_failures,
                    "counter_resets": series.resets,
                    "samples_retained": len(series.ring),
                }
                for name, series in sorted(self._series.items())
            }

    # -- merged sketches ------------------------------------------------------

    def merged_sketch_rows(
        self, quantiles: Tuple[float, ...] = (0.5, 0.99, 0.999)
    ) -> List[dict]:
        """Fleet-wide per-model/per-stage quantiles from EXACT
        bucket-wise merges of the replicas' raw DDSketch state."""
        with self._lock:
            pending: Dict[Tuple[str, str], List[dict]] = {}
            for series in self._series.values():
                for model, stages in series.sketches.items():
                    for stage, doc in stages.items():
                        pending.setdefault((model, stage), []).append(doc)
        rows = []
        for (model, stage), docs in sorted(pending.items()):
            merged = LatencySketch.merged(
                [LatencySketch.from_dict(d) for d in docs]
            )
            rows.append({
                "model": model,
                "stage": stage,
                "count": merged.count,
                "quantiles": {
                    str(q): merged.quantile(q) for q in quantiles
                },
            })
        return rows

    # -- merged device-memory headroom ----------------------------------------

    _HEADROOM_SERIES_RE = re.compile(
        r"^" + _memscope.MEM_HEADROOM_METRIC + r"\{model=\"([^\"]*)\"\}$"
    )

    def headroom_rows(self) -> dict:
        """Fleet-level merge of the ``nv_device_memory_headroom_bytes``
        gauge: each replica's LATEST retained sample (the gauge rides the
        scrape ring like every other gauge, so history stays queryable
        from ``timeseries()``), plus the fleet-wide minimum per model —
        the number an admission-aware router actually cares about (the
        fleet can place a request only where the tightest replica that
        must host it still has room)."""
        rows: List[dict] = []
        fleet_min: Dict[str, float] = {}
        with self._lock:
            for name, series in sorted(self._series.items()):
                if not series.ring:
                    continue
                gauges = series.ring[-1].get("gauges", {})
                for key, value in sorted(gauges.items()):
                    m = self._HEADROOM_SERIES_RE.match(key)
                    if m is None:
                        continue
                    model = m.group(1)
                    rows.append({
                        "replica": name,
                        "model": model,
                        "headroom_bytes": value,
                    })
                    if (model not in fleet_min
                            or value < fleet_min[model]):
                        fleet_min[model] = value
        return {"replicas": rows, "fleet_min": fleet_min}

    # -- SLO / cohorts --------------------------------------------------------

    def set_objective(self, doc: dict) -> dict:
        """Declare (or replace) one SLO objective from its admin/config
        document. Returns the canonical form."""
        from tritonclient_tpu.fleet._slo import SloObjective

        objective = SloObjective.from_dict(doc)
        with self._lock:
            self.slo.set_objective(objective)
        return objective.to_dict()

    def remove_objective(self, model: str, tenant: str = "") -> bool:
        with self._lock:
            return self.slo.remove_objective(model, tenant)

    def objective_docs(self) -> List[dict]:
        with self._lock:
            return [o.to_dict() for o in self.slo.objectives()]

    def assign_cohort(self, replica: str, cohort: str) -> dict:
        with self._lock:
            self.cohorts.assign(replica, cohort)
            return {"replica": replica,
                    "cohort": self.cohorts.cohort_of(replica)}

    def cohort_assignments(self) -> Dict[str, str]:
        with self._lock:
            return self.cohorts.assignments()

    def burn_rows(self, now: Optional[float] = None) -> List[dict]:
        index = self.bucket_index(now)
        with self._lock:
            return self.slo.burn_rows(index)

    def verdicts(self, replicas: List[str],
                 now: Optional[float] = None) -> List[dict]:
        now = self._clock() if now is None else now
        stale = self.stale_replicas(replicas, now=now)
        index = self.bucket_index(now)
        with self._lock:
            return self.cohorts.verdicts(index, replicas, stale=stale)

    def cohort_request_counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._requests_by_cohort)

    # -- flight merge ---------------------------------------------------------

    def proxy_flight_records(self) -> List[dict]:
        with self._lock:
            return [dict(r) for r in self._flight]

    def merged_flight_dump(self, targets: List[Tuple[str, str]],
                           timeout_s: float = 2.0) -> dict:
        """Fan out to every (name, http_address) target's flight
        recorder dump, stamp records with the replica name, and merge
        with the router's proxy records keyed by traceparent. I/O runs
        with NO fleetscope lock held."""
        import json as _json

        per_replica: Dict[str, dict] = {}
        errors: Dict[str, str] = {}
        for name, address in targets:
            try:
                status, body = http_call(
                    address, "GET", EP_FLIGHT_RECORDER,
                    timeout_s=timeout_s,
                )
                if status != 200:
                    errors[name] = f"HTTP {status}"
                    continue
                per_replica[name] = _json.loads(body)
            except (OSError, ValueError) as e:
                errors[name] = f"{type(e).__name__}: {e}"
        records: List[dict] = []
        counters = {"offered": 0, "retained_slow": 0, "errors": 0,
                    "deadline_misses": 0}
        for name, doc in sorted(per_replica.items()):
            for key_from, key_to in (("offered", "offered"),
                                     ("retained_slow", "retained_slow"),
                                     ("errors", "errors"),
                                     ("deadline_misses",
                                      "deadline_misses")):
                counters[key_to] += int(
                    (doc.get("counters") or {}).get(key_from, 0) or 0
                )
            for rec in doc.get("records", ()):
                stamped = dict(rec)
                stamped["replica"] = name
                records.append(stamped)
        records.extend(self.proxy_flight_records())
        # Merge keyed by traceparent: records sharing a trace_id sort
        # together (router proxy span first by wall time), the rest
        # interleave chronologically.
        by_trace: Dict[str, int] = {}
        for rec in records:
            trace = rec.get("trace_id") or ""
            if trace and trace not in by_trace:
                by_trace[trace] = len(by_trace)

        def sort_key(rec):
            trace = rec.get("trace_id") or ""
            wall = float(rec.get("wall_time_s") or 0.0)
            if trace in by_trace:
                return (0, by_trace[trace], wall)
            return (1, 0, wall)

        records.sort(key=sort_key)
        return {
            "kind": "fleet_flight_recorder",
            "replicas": sorted(per_replica),
            "unreachable": errors,
            "counters": counters,
            "records": records,
        }

    # -- dump -----------------------------------------------------------------

    def timeseries(self) -> Dict[str, List[dict]]:
        with self._lock:
            return {
                name: [dict(sample) for sample in series.ring]
                for name, series in sorted(self._series.items())
            }

    def dump(self, replicas: Optional[List[str]] = None) -> dict:
        """Self-describing document ``scripts/fleet_report.py`` loads."""
        replicas = list(replicas or [])
        now = self._clock()
        doc = {
            "kind": "fleetscope",
            "config": {
                "bucket_s": self.bucket_s,
                "windows": self.windows,
                "stale_after_s": self.stale_after_s,
            },
            "scrape_health": self.scrape_health(),
            "timeseries": self.timeseries(),
            "merged_sketches": self.merged_sketch_rows(),
            "memory": {"headroom": self.headroom_rows()},
            "slo": {
                "objectives": self.objective_docs(),
                "burn": self.burn_rows(now=now),
            },
            "cohorts": {
                "assignments": self.cohort_assignments(),
                "requests": self.cohort_request_counts(),
                "verdicts": self.verdicts(replicas, now=now),
            },
        }
        return doc


# The observer protocol the ReplicaSet prober drives: anything with
# ``observe_scrape`` works; FleetScope is the shipped implementation.
Observer = FleetScope

"""Load-balancing policies behind one interface.

A policy chooses one replica out of the routable candidates for a unary
request or a new stream. All policies are cheap (O(candidates)) and
stateless apart from deterministic counters — the *signal* (per-replica
outstanding requests, scraped queue depth) lives on the ``Replica``
records the router passes in, so policies compose with any membership
source.

Stream affinity is deliberately NOT a policy subclass: stickiness is a
keyed transform (``affinity_select``) layered over whichever policy
handles keyless traffic, so "tenant X's streams land on one replica"
and "everything else balances least-outstanding" coexist.
"""

import hashlib
import random
from typing import List, Optional, Sequence


class Policy:
    """One replica out of ``candidates`` (never empty; router guarantees)."""

    name = "policy"

    def select(self, candidates: Sequence):
        raise NotImplementedError


class LeastOutstanding(Policy):
    """The replica with the fewest router-tracked outstanding requests,
    breaking ties on the scraped queue depth, then on lifetime request
    count (so an idle fleet rotates instead of piling sequential traffic
    onto the name-first replica). The default: outstanding count is the
    router's freshest local signal — scrapes lag by a probe interval,
    but the lease counter is exact."""

    name = "least-outstanding"

    def select(self, candidates: Sequence):
        return min(
            candidates,
            key=lambda r: (
                r.outstanding, r.queue_depth, r.requests_total, r.name,
            ),
        )


class PowerOfTwoChoices(Policy):
    """Sample two distinct replicas, keep the less loaded one.

    The classic load/communication trade: with stale load signals,
    full-scan least-loaded herds onto whichever replica last scraped
    empty; two random choices cut the herd while staying within a
    constant factor of optimal imbalance. Deterministically seeded so
    tests replay.
    """

    name = "p2c"

    def __init__(self, seed: int = 0x5EED):
        self._rng = random.Random(seed)

    def select(self, candidates: Sequence):
        if len(candidates) == 1:
            return candidates[0]
        a, b = self._rng.sample(list(candidates), 2)
        return min((a, b), key=lambda r: (r.outstanding, r.queue_depth))


class RoundRobin(Policy):
    """Strict rotation over the candidate list (sorted by name so the
    rotation is stable under membership churn)."""

    name = "round-robin"

    def __init__(self):
        self._next = 0

    def select(self, candidates: Sequence):
        ordered = sorted(candidates, key=lambda r: r.name)
        choice = ordered[self._next % len(ordered)]
        self._next += 1
        return choice


POLICIES = {
    LeastOutstanding.name: LeastOutstanding,
    PowerOfTwoChoices.name: PowerOfTwoChoices,
    RoundRobin.name: RoundRobin,
}


def make_policy(name: str) -> Policy:
    try:
        return POLICIES[name]()
    except KeyError:
        raise ValueError(
            f"unknown balancing policy '{name}' (have: "
            f"{', '.join(sorted(POLICIES))})"
        ) from None


def affinity_select(candidates: Sequence, key: str) -> Optional[object]:
    """Rendezvous (highest-random-weight) hash of ``key`` over the
    candidates: every router instance maps the same key to the same
    replica, and losing a replica remaps ONLY the keys that lived on it
    (no mod-N reshuffle). Returns None for an empty key so the caller
    falls through to its keyless policy."""
    if not key or not candidates:
        return None
    best: Optional[object] = None
    best_weight = b""
    for replica in candidates:
        weight = hashlib.blake2b(
            f"{key}\x00{replica.name}".encode(), digest_size=8
        ).digest()
        if best is None or weight > best_weight:
            best, best_weight = replica, weight
    return best


def policy_names() -> List[str]:
    return sorted(POLICIES)

"""HTTP front-end of the fleet router.

Speaks the same KServe v2 REST surface as the replicas so existing
clients point at the router unchanged. The hot path is a byte-level
reverse proxy: the request body is never JSON-parsed in the router —
admission needs only the ``tenant-id`` header, balancing needs only the
route — so the router's per-request Python cost stays a small fraction
of a replica's parse+compute cost (the aggregate-throughput condition).

Routing table:

* ``/metrics``, health, ``v2/fleet/*`` — answered by the ROUTER
  (fleet-level metrics/health/admin);
* ``v2/models/{m}[/versions/{v}]/infer`` POST — admission + balance +
  proxy to the leased replica (tenant-id / traceparent / deadline
  parameters forward untouched);
* shared-memory admin, repository load/unload, trace/log settings —
  fanned out to EVERY ready replica (shared-nothing replicas each need
  the registration);
* everything else — proxied to one ready replica.

Connections to replicas are pooled keep-alive ``http.client``
connections; a transport failure mid-proxy retries once on a different
replica when the request never reached processing.
"""

import json
import socket
import threading
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from tritonclient_tpu import sanitize
from tritonclient_tpu.fleet._replica import Replica
from tritonclient_tpu.fleet._router import FleetError, FleetRouter
from tritonclient_tpu.protocol._literals import (
    EP_FLEET_STATUS,
    EP_HEALTH_LIVE,
    EP_HEALTH_READY,
    EP_LOGGING,
    EP_METRICS,
    EP_TRACE_SETTING,
    FLEET_REPLICA_ROUTE_RE,
    HEADER_TENANT_ID,
    MODEL_ROUTE_RE,
    REPOSITORY_ROUTE_RE,
    SHM_ROUTE_RE,
)

#: Request headers the proxy forwards verbatim (everything else is
#: hop-by-hop or recomputed). Lowercase.
_FORWARD_REQUEST_HEADERS = (
    "content-type",
    "content-encoding",
    "accept-encoding",
    "inference-header-content-length",
    HEADER_TENANT_ID,
    "traceparent",
    "triton-request-id",
)

#: Response headers relayed back to the caller.
_FORWARD_RESPONSE_HEADERS = (
    "content-type",
    "content-encoding",
    "inference-header-content-length",
)


class _ConnPool:
    """Keep-alive connections to replicas, pooled per address. The pool
    lock guards the free lists only — never the sockets: a connection is
    checked out, used outside the lock, and returned (or dropped) after.
    """

    def __init__(self, timeout_s: float = 30.0, per_address: int = 32):
        self._timeout_s = timeout_s
        self._per_address = per_address
        self._free: Dict[str, List[HTTPConnection]] = {}
        self._lock = sanitize.named_lock("fleet._ConnPool._lock")

    def get(self, address: str) -> HTTPConnection:
        with self._lock:
            free = self._free.get(address)
            if free:
                return free.pop()
        host, _, port = address.partition(":")
        return HTTPConnection(host, int(port or 80),
                              timeout=self._timeout_s)

    def put(self, address: str, conn: HTTPConnection):
        with self._lock:
            free = self._free.setdefault(address, [])
            if len(free) < self._per_address:
                free.append(conn)
                return
        conn.close()

    def close(self):
        with self._lock:
            conns = [c for free in self._free.values() for c in free]
            self._free.clear()
        for conn in conns:
            conn.close()


class _RouterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "triton-tpu-fleet"

    def log_message(self, fmt, *args):
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    @property
    def router(self) -> FleetRouter:
        return self.server.router

    @property
    def pool(self) -> _ConnPool:
        return self.server.pool

    # -- plumbing -------------------------------------------------------------

    def _read_body(self) -> bytes:
        length = int(self.headers.get("Content-Length", 0))
        return self.rfile.read(length) if length else b""

    def _send(self, status: int, body: bytes,
              content_type: str = "application/json",
              extra: Optional[dict] = None):
        try:
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra or {}).items():
                self.send_header(k, str(v))
            self.end_headers()
            if body:
                self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # caller disconnected; nothing left to tell them

    def _send_json(self, obj, status: int = 200):
        body = json.dumps(obj).encode() if obj is not None else b""
        self._send(status, body)

    def _send_fleet_error(self, e: FleetError):
        self._send_json({"error": str(e)}, e.status)

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def _dispatch(self, method: str):
        try:
            self._route(method)
        except FleetError as e:
            self._send_fleet_error(e)
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:  # noqa: BLE001 — a bug fails the request
            self._send_json({"error": f"router error: {e}"}, 500)

    # -- proxy ----------------------------------------------------------------

    def _forward_headers(self, body: bytes) -> dict:
        headers = {}
        for name in _FORWARD_REQUEST_HEADERS:
            value = self.headers.get(name)
            if value is not None:
                headers[name] = value
        headers["Content-Length"] = str(len(body))
        return headers

    def _exchange(self, address: str, method: str, body: bytes,
                  headers: dict) -> Tuple[int, dict, bytes]:
        """One proxied exchange over a pooled connection. Transport
        failures close the connection and re-raise (the caller decides
        whether a retry is safe)."""
        conn = self.pool.get(address)
        try:
            conn.request(method, self.path, body=body, headers=headers)
            resp = conn.getresponse()
            payload = resp.read()
            relay = {
                k: resp.headers[k]
                for k in _FORWARD_RESPONSE_HEADERS
                if resp.headers.get(k) is not None
            }
            status = resp.status
        except (OSError, socket.timeout):
            conn.close()
            raise
        self.pool.put(address, conn)
        return status, relay, payload

    def _relay(self, status: int, relay_headers: dict, payload: bytes):
        ctype = relay_headers.pop("content-type", "application/json")
        self._send(status, payload, content_type=ctype,
                   extra=relay_headers)

    def _proxy_one(self, replica: Replica, method: str, body: bytes):
        status, relay, payload = self._exchange(
            replica.http_address, method, body, self._forward_headers(body)
        )
        self._relay(status, relay, payload)
        return status

    # -- routes ---------------------------------------------------------------

    def _route(self, method: str):
        path = self.path.split("?", 1)[0].strip("/")
        router = self.router

        # Router-local surfaces first (no body expected on the GETs, but
        # drain/undrain POSTs carry options — read lazily per branch).
        if path == EP_METRICS and method == "GET":
            return self._send(
                200, router.prometheus_metrics().encode(),
                content_type="text/plain; version=0.0.4; charset=utf-8",
            )
        if path == EP_HEALTH_LIVE:
            self._read_body()
            return self._send(200, b"")
        if path == EP_HEALTH_READY:
            self._read_body()
            ready = router.ready()
            routable = len(router.replica_set.routable())
            return self._send_json(
                {"ready": ready, "routable_replicas": routable},
                200 if ready else 400,
            )
        if path == EP_FLEET_STATUS:
            self._read_body()
            return self._send_json(router.status())
        m = FLEET_REPLICA_ROUTE_RE.match(path)
        if m and method == "POST":
            body = self._read_body()
            options = json.loads(body) if body else {}
            name = m.group("replica")
            try:
                if m.group("action") == "drain":
                    detail = router.drain_replica(
                        name, wait_s=float(options.get("wait_s", 30.0))
                    )
                else:
                    detail = router.undrain_replica(name)
            except KeyError as e:
                return self._send_json({"error": str(e)}, 404)
            except TimeoutError as e:
                # Admin-operation timeout (drain did not settle), NOT the
                # request-shed status — a plain 500 keeps the shed
                # vocabulary unambiguous.
                return self._send_json({"error": str(e)}, 500)
            return self._send_json(detail)

        body = self._read_body()

        # Inference: admission + balance + proxy (the hot path).
        m = MODEL_ROUTE_RE.match(path)
        if m and m.group("action") == "infer" and method == "POST":
            return self._infer(body)

        # Shared-nothing admin state: every ready replica needs it.
        if SHM_ROUTE_RE.match(path) or REPOSITORY_ROUTE_RE.match(path) or (
            method == "POST" and (
                path == EP_LOGGING
                or path == EP_TRACE_SETTING
                or (m and m.group("action") == "trace/setting")
            )
        ):
            if (
                SHM_ROUTE_RE.match(path)
                and SHM_ROUTE_RE.match(path).group("action") == "status"
            ):
                return self._proxy_one(router.pick_any(), method, body)
            return self._fan_out(method, body)

        # Everything else (metadata, config, stats, flight recorder,
        # readiness of a model, repository index): any ready replica.
        self._proxy_one(router.pick_any(), method, body)

    def _fan_out(self, method: str, body: bytes):
        """Forward to EVERY ready replica; first failure wins the reply
        (the caller must see that the fleet is not uniformly configured),
        else the last response is relayed."""
        replicas = self.router.replica_set.routable()
        if not replicas:
            raise FleetError("no ready replicas in the fleet", 503)
        last = None
        for replica in replicas:
            status, relay, payload = self._exchange(
                replica.http_address, method, body,
                self._forward_headers(body),
            )
            if status >= 400:
                return self._relay(status, relay, payload)
            last = (status, relay, payload)
        return self._relay(*last)

    def _infer(self, body: bytes):
        tenant = self.headers.get(HEADER_TENANT_ID, "")
        router = self.router
        lease = router.begin(tenant)  # FleetError 429/503 -> _dispatch
        try:
            status = self._proxy_one(lease.replica, "POST", body)
        except (OSError, socket.timeout):
            # The replica died under us before answering. Release the
            # failed lease and retry ONCE on a different replica — a
            # fresh admission charge, deliberately conservative (a
            # retry is a second unit of offered load).
            lease.release(failed=True)
            retry = router.begin(tenant, exclude=(lease.replica.name,))
            try:
                status = self._proxy_one(retry.replica, "POST", body)
            except (OSError, socket.timeout) as e:
                retry.release(failed=True)
                raise FleetError(
                    f"replica {retry.replica.name} unreachable: {e}", 502
                )
            retry.release(failed=status >= 500)
            return
        lease.release(failed=status >= 500)


class _RouterHTTPServer(ThreadingHTTPServer):
    # Same accept-burst headroom as the replica front-end.
    request_queue_size = 128


class RouterHTTPFrontend:
    """Threaded HTTP server hosting a FleetRouter."""

    def __init__(self, router: FleetRouter, host: str = "127.0.0.1",
                 port: int = 0, verbose: bool = False):
        self._server = _RouterHTTPServer((host, port), _RouterHandler)
        self._server.router = router
        self._server.pool = _ConnPool()
        self._server.verbose = verbose
        self._server.daemon_threads = True
        self._server.socket.setsockopt(
            socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
        )
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        host, port = self._server.server_address[:2]
        return f"{host}:{port}"

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="fleet-http-frontend",
        )
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        self._server.pool.close()
        if self._thread:
            self._thread.join(timeout=5)

"""HTTP front-end of the fleet router.

Speaks the same KServe v2 REST surface as the replicas so existing
clients point at the router unchanged. The hot path is a byte-level
reverse proxy: the request body is never JSON-parsed in the router —
admission needs only the ``tenant-id`` header, balancing needs only the
route — so the router's per-request Python cost stays a small fraction
of a replica's parse+compute cost (the aggregate-throughput condition).

Routing table:

* ``/metrics``, health, ``v2/fleet/*`` — answered by the ROUTER
  (fleet-level metrics/health/admin);
* ``v2/models/{m}[/versions/{v}]/infer`` POST — admission + balance +
  proxy to the leased replica (tenant-id / traceparent / deadline
  parameters forward untouched);
* shared-memory admin, repository load/unload, trace/log settings —
  fanned out to EVERY ready replica (shared-nothing replicas each need
  the registration);
* everything else — proxied to one ready replica.

Connections to replicas are pooled keep-alive ``http.client``
connections; a transport failure mid-proxy retries once on a different
replica when the request never reached processing.
"""

import json
import queue
import socket
import threading
import time
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from tritonclient_tpu import chaos, sanitize
from tritonclient_tpu.fleet._replica import Replica
from tritonclient_tpu.fleet._router import FleetError, FleetRouter
from tritonclient_tpu.resilience import (
    PHASE_CONNECT,
    PHASE_RESPONSE,
    PHASE_SEND,
)
from tritonclient_tpu.protocol._literals import (
    EP_FLEET_COHORTS,
    EP_FLEET_FLEETSCOPE,
    EP_FLEET_FLIGHT_RECORDER,
    EP_FLEET_SLO,
    EP_FLEET_STATUS,
    EP_HEALTH_LIVE,
    EP_HEALTH_READY,
    EP_LOGGING,
    EP_METRICS,
    EP_TRACE_SETTING,
    FLEET_REPLICA_ROUTE_RE,
    HEADER_HEDGE_ATTEMPT,
    HEADER_IDEMPOTENCY_KEY,
    HEADER_RETRY_ATTEMPT,
    HEADER_TENANT_ID,
    HEDGE_OUTCOME_FAILED,
    HEDGE_OUTCOME_HEDGE,
    HEDGE_OUTCOME_PRIMARY,
    MODEL_ROUTE_RE,
    MAX_REQUEST_BYTES_DEFAULT,
    REPOSITORY_ROUTE_RE,
    SHM_ROUTE_RE,
    STATUS_INVALID,
    STATUS_TOO_LARGE,
)
from tritonclient_tpu.protocol._validate import (
    ValidationError,
    validate_content_length,
)

#: Request headers the proxy forwards verbatim (everything else is
#: hop-by-hop or recomputed). Lowercase.
_FORWARD_REQUEST_HEADERS = (
    "content-type",
    "content-encoding",
    "accept-encoding",
    "inference-header-content-length",
    HEADER_TENANT_ID,
    HEADER_IDEMPOTENCY_KEY,
    "traceparent",
    "triton-request-id",
)


class _ExchangeError(Exception):
    """One failed proxied exchange, tagged with the request phase it
    failed in — the input to RetryPolicy.classify (connect/send are
    provably pre-execution; response means the replica may have
    executed the request)."""

    def __init__(self, phase: str, cause: BaseException):
        super().__init__(f"{phase}: {cause}")
        self.phase = phase
        self.cause = cause

#: Response headers relayed back to the caller.
_FORWARD_RESPONSE_HEADERS = (
    "content-type",
    "content-encoding",
    "inference-header-content-length",
)


class _ConnPool:
    """Keep-alive connections to replicas, pooled per address. The pool
    lock guards the free lists only — never the sockets: a connection is
    checked out, used outside the lock, and returned (or dropped) after.
    """

    def __init__(self, timeout_s: float = 30.0, per_address: int = 32):
        self._timeout_s = timeout_s
        self._per_address = per_address
        self._free: Dict[str, List[HTTPConnection]] = {}
        self._lock = sanitize.named_lock("fleet._ConnPool._lock")

    def get(self, address: str) -> HTTPConnection:
        with self._lock:
            free = self._free.get(address)
            if free:
                return free.pop()
        host, _, port = address.partition(":")
        return HTTPConnection(host, int(port or 80),
                              timeout=self._timeout_s)

    def put(self, address: str, conn: HTTPConnection):
        with self._lock:
            free = self._free.setdefault(address, [])
            if len(free) < self._per_address:
                free.append(conn)
                return
        conn.close()

    def invalidate(self, address: str):
        """Drop every pooled connection to one address. Called when a
        replica rejoins after a crash: a keep-alive connection opened to
        the DEAD incarnation must never carry traffic to what is now a
        different process (or, in-process, a zombie handler thread)."""
        with self._lock:
            conns = self._free.pop(address, [])
        for conn in conns:
            conn.close()

    def close(self):
        with self._lock:
            conns = [c for free in self._free.values() for c in free]
            self._free.clear()
        for conn in conns:
            conn.close()


class _RouterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "triton-tpu-fleet"

    def log_message(self, fmt, *args):
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    @property
    def router(self) -> FleetRouter:
        return self.server.router

    @property
    def pool(self) -> _ConnPool:
        return self.server.pool

    # -- plumbing -------------------------------------------------------------

    def _read_body(self) -> bytes:
        # The fleet proxy reads the whole body before forwarding, so the
        # declared length must be capped BEFORE it sizes a read — same
        # 413 contract as the replica front-end.
        cap = getattr(self.server, "max_request_bytes",
                      MAX_REQUEST_BYTES_DEFAULT)
        length = validate_content_length(
            self.headers.get("Content-Length", 0), cap
        )
        return self.rfile.read(length) if length else b""

    def _send(self, status: int, body: bytes,
              content_type: str = "application/json",
              extra: Optional[dict] = None):
        try:
            self.send_response(status)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (extra or {}).items():
                self.send_header(k, str(v))
            self.end_headers()
            if body:
                self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # caller disconnected; nothing left to tell them

    def _send_json(self, obj, status: int = 200):
        body = json.dumps(obj).encode() if obj is not None else b""
        self._send(status, body)

    def _send_fleet_error(self, e: FleetError):
        self._send_json({"error": str(e)}, e.status)

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def _dispatch(self, method: str):
        try:
            self._route(method)
        except FleetError as e:
            self._send_fleet_error(e)
        except ValidationError as e:
            if e.status == STATUS_TOO_LARGE:
                # The over-cap body was never read; drop the connection so
                # it cannot be parsed as the next keep-alive request.
                self.close_connection = True
            self._send_json({"error": str(e)}, e.status)
        except _ExchangeError as e:
            # A proxied non-inference exchange failed (inference paths
            # handle their own failover before this).
            self._send_json({"error": f"replica unreachable: {e}"}, 502)
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:  # noqa: BLE001 — a bug fails the request
            self._send_json({"error": f"router error: {e}"}, 500)

    # -- proxy ----------------------------------------------------------------

    def _forward_headers(self, body: bytes) -> dict:
        headers = {}
        for name in _FORWARD_REQUEST_HEADERS:
            value = self.headers.get(name)
            if value is not None:
                headers[name] = value
        headers["Content-Length"] = str(len(body))
        return headers

    def _exchange(self, address: str, method: str, body: bytes,
                  headers: dict,
                  conn_box: Optional[dict] = None
                  ) -> Tuple[int, dict, bytes]:
        """One proxied exchange over a pooled connection. Transport
        failures close the connection and raise :class:`_ExchangeError`
        tagged with the phase (connect / send / response) so the caller
        can decide whether a replay is provably safe. ``conn_box``, when
        given, exposes the live connection under ``conn_box["conn"]`` so
        a hedging caller can cancel this exchange by shutting the socket
        down (the replica's disconnect watcher then sheds the work)."""
        phase = PHASE_CONNECT
        conn = None
        try:
            chaos.fire(chaos.SITE_FLEET_CONNECT)
            conn = self.pool.get(address)
            if conn.sock is None:
                conn.connect()
            if conn_box is not None:
                conn_box["conn"] = conn
            phase = PHASE_SEND
            chaos.fire(chaos.SITE_FLEET_SEND)
            conn.request(method, self.path, body=body, headers=headers)
            # Request fully written: a failure past this point is no
            # longer provably pre-execution.
            phase = PHASE_RESPONSE
            chaos.fire(chaos.SITE_FLEET_RESPONSE)
            resp = conn.getresponse()
            payload = resp.read()
            relay = {
                k: resp.headers[k]
                for k in _FORWARD_RESPONSE_HEADERS
                if resp.headers.get(k) is not None
            }
            status = resp.status
        except (OSError, socket.timeout) as e:
            if conn is not None:
                conn.close()
            raise _ExchangeError(phase, e) from e
        if conn_box is not None:
            conn_box["conn"] = None
        self.pool.put(address, conn)
        return status, relay, payload

    def _relay(self, status: int, relay_headers: dict, payload: bytes):
        ctype = relay_headers.pop("content-type", "application/json")
        self._send(status, payload, content_type=ctype,
                   extra=relay_headers)

    def _proxy_one(self, replica: Replica, method: str, body: bytes):
        status, relay, payload = self._exchange(
            replica.http_address, method, body, self._forward_headers(body)
        )
        self._relay(status, relay, payload)
        return status

    # -- routes ---------------------------------------------------------------

    def _route(self, method: str):
        path = self.path.split("?", 1)[0].strip("/")
        router = self.router

        # Router-local surfaces first (no body expected on the GETs, but
        # drain/undrain POSTs carry options — read lazily per branch).
        if path == EP_METRICS and method == "GET":
            return self._send(
                200, router.prometheus_metrics().encode(),
                content_type="text/plain; version=0.0.4; charset=utf-8",
            )
        if path == EP_HEALTH_LIVE:
            self._read_body()
            return self._send(200, b"")
        if path == EP_HEALTH_READY:
            self._read_body()
            ready = router.ready()
            routable = len(router.replica_set.routable())
            return self._send_json(
                {"ready": ready, "routable_replicas": routable},
                200 if ready else STATUS_INVALID,
            )
        if path == EP_FLEET_STATUS:
            self._read_body()
            return self._send_json(router.status())
        if path == EP_FLEET_FLIGHT_RECORDER and method == "GET":
            self._read_body()
            return self._send_json(router.merged_flight_dump())
        if path == EP_FLEET_FLEETSCOPE and method == "GET":
            self._read_body()
            names = [r["name"] for r in router.replica_set.snapshot()]
            return self._send_json(router.fleetscope.dump(names))
        if path == EP_FLEET_SLO:
            body = self._read_body()
            if method == "POST":
                doc = json.loads(body) if body else {}
                try:
                    if doc.get("remove"):
                        result = {
                            "removed": router.fleetscope.remove_objective(
                                doc.get("model", ""),
                                doc.get("tenant", "") or "",
                            ),
                            "model": doc.get("model", ""),
                            "tenant": doc.get("tenant", "") or "",
                        }
                    else:
                        result = router.fleetscope.set_objective(doc)
                except (ValueError, TypeError) as e:
                    return self._send_json({"error": str(e)}, STATUS_INVALID)
                # Journaled (router-local: never replayed to replicas)
                # so objectives survive a router restart.
                router.record_admin(method, path, body, {})
                return self._send_json(result)
            return self._send_json({
                "kind": "fleet_slo",
                "objectives": router.fleetscope.objective_docs(),
                "burn": router.fleetscope.burn_rows(),
            })
        if path == EP_FLEET_COHORTS:
            body = self._read_body()
            if method == "POST":
                doc = json.loads(body) if body else {}
                try:
                    result = router.fleetscope.assign_cohort(
                        doc.get("replica", ""), doc.get("cohort", "")
                    )
                except ValueError as e:
                    return self._send_json({"error": str(e)}, STATUS_INVALID)
                router.record_admin(method, path, body, {})
                return self._send_json(result)
            names = [r["name"] for r in router.replica_set.snapshot()]
            return self._send_json({
                "kind": "fleet_cohorts",
                "assignments": router.fleetscope.cohort_assignments(),
                "requests": router.fleetscope.cohort_request_counts(),
                "verdicts": router.fleetscope.verdicts(names),
            })
        m = FLEET_REPLICA_ROUTE_RE.match(path)
        if m and method == "POST":
            body = self._read_body()
            options = json.loads(body) if body else {}
            name = m.group("replica")
            if m.group("action") == "cohort":
                try:
                    detail = router.fleetscope.assign_cohort(
                        name, options.get("cohort", "")
                    )
                except ValueError as e:
                    return self._send_json({"error": str(e)}, STATUS_INVALID)
                router.record_admin(method, path, body, {})
                return self._send_json(detail)
            try:
                if m.group("action") == "drain":
                    detail = router.drain_replica(
                        name, wait_s=float(options.get("wait_s", 30.0))
                    )
                else:
                    detail = router.undrain_replica(name)
            except KeyError as e:
                return self._send_json({"error": str(e)}, 404)
            except TimeoutError as e:
                # Admin-operation timeout (drain did not settle), NOT the
                # request-shed status — a plain 500 keeps the shed
                # vocabulary unambiguous.
                return self._send_json({"error": str(e)}, 500)
            return self._send_json(detail)

        body = self._read_body()

        # Inference: admission + balance + proxy (the hot path).
        m = MODEL_ROUTE_RE.match(path)
        if m and m.group("action") == "infer" and method == "POST":
            return self._infer(body, m.group("model"))

        # Shared-nothing admin state: every ready replica needs it.
        if SHM_ROUTE_RE.match(path) or REPOSITORY_ROUTE_RE.match(path) or (
            method == "POST" and (
                path == EP_LOGGING
                or path == EP_TRACE_SETTING
                or (m and m.group("action") == "trace/setting")
            )
        ):
            if (
                SHM_ROUTE_RE.match(path)
                and SHM_ROUTE_RE.match(path).group("action") == "status"
            ):
                return self._proxy_one(router.pick_any(), method, body)
            return self._fan_out(method, body)

        # Everything else (metadata, config, stats, flight recorder,
        # readiness of a model, repository index): any ready replica.
        self._proxy_one(router.pick_any(), method, body)

    def _fan_out(self, method: str, body: bytes):
        """Forward to EVERY ready replica; first failure wins the reply
        (the caller must see that the fleet is not uniformly configured),
        else the last response is relayed. Uniformly applied operations
        are journaled so a replica rejoining after a crash gets them
        replayed before it is routable again."""
        replicas = self.router.replica_set.routable()
        if not replicas:
            raise FleetError("no ready replicas in the fleet", 503)
        headers = self._forward_headers(body)
        last = None
        for replica in replicas:
            status, relay, payload = self._exchange(
                replica.http_address, method, body, headers,
            )
            if status >= STATUS_INVALID:
                return self._relay(status, relay, payload)
            last = (status, relay, payload)
        self.router.record_admin(
            method, self.path.split("?", 1)[0], body, headers
        )
        return self._relay(*last)

    def _trace_id(self) -> str:
        """The trace-id field of an incoming traceparent header (the
        merged flight dump's correlation key), or ""."""
        parts = self.headers.get("traceparent", "").split("-")
        return parts[1] if len(parts) >= 3 else ""

    def _infer(self, body: bytes, model: str = ""):
        """Inference proxy: admission + balance + policy-driven
        failover.

        The PR-8 behavior here was an UNCONDITIONAL "one safe retry on
        transport failure" — which can re-send a non-idempotent infer
        whose first attempt may have executed (the failure could be a
        mid-response FIN *after* the replica ran the model). Replays now
        go through the router's RetryPolicy: connect/send-phase failures
        (provably pre-execution) fail over to a different replica;
        post-send failures fail over ONLY when the request carries an
        idempotency key. Idempotent requests are additionally eligible
        for hedging (``hedge_us``).
        """
        tenant = self.headers.get(HEADER_TENANT_ID, "")
        idempotent = self.headers.get(HEADER_IDEMPOTENCY_KEY) is not None
        router = self.router
        trace_id = self._trace_id()
        if router.hedge_enabled(idempotent):
            return self._infer_hedged(body, tenant, model, trace_id)
        policy = router.retry_policy
        attempt = 0
        exclude: List[str] = []
        with chaos.operation("fleet.infer"):
            while True:
                lease = router.begin(tenant, exclude=tuple(exclude))
                headers = self._forward_headers(body)
                if attempt:
                    headers[HEADER_RETRY_ATTEMPT] = str(attempt)
                started = time.monotonic()
                try:
                    # Per-replica chaos site: faulting ONE replica's
                    # proxied traffic is how the cohort drill injects a
                    # regression into the canary cohort only.
                    chaos.fire(
                        chaos.SITE_FLEET_REPLICA_PREFIX
                        + lease.replica.name
                    )
                    status, relay, payload = self._exchange(
                        lease.replica.http_address, "POST", body, headers
                    )
                except (_ExchangeError, OSError) as failure:
                    if not isinstance(failure, _ExchangeError):
                        # An injected per-replica fault fires before the
                        # connect — provably pre-execution.
                        failure = _ExchangeError(PHASE_CONNECT, failure)
                    router.fleetscope.record_request(
                        model, tenant,
                        int((time.monotonic() - started) * 1e6),
                        False, lease.replica.name, trace_id=trace_id,
                    )
                    lease.release(failed=True)
                    router.note_replica_result(lease.replica, ok=False)
                    reason = policy.classify(
                        failure.phase, idempotent=idempotent
                    )
                    if policy.should_retry(attempt, reason):
                        exclude.append(lease.replica.name)
                        policy.sleep(attempt)
                        attempt += 1
                        continue
                    raise FleetError(
                        f"replica {lease.replica.name} unreachable "
                        f"({failure.phase} phase): {failure.cause}", 502
                    )
                router.fleetscope.record_request(
                    model, tenant,
                    int((time.monotonic() - started) * 1e6),
                    status < 500, lease.replica.name, trace_id=trace_id,
                )
                router.note_replica_result(lease.replica, ok=status < 500)
                if status < 500:
                    policy.note_success()
                lease.release(failed=status >= 500)
                return self._relay(status, relay, payload)

    def _infer_hedged(self, body: bytes, tenant: str, model: str = "",
                      trace_id: str = ""):
        """Hedged unary inference: launch the primary, and when it has
        not answered within ``hedge_us`` (or failed outright), launch a
        second attempt on a different replica. First success wins; the
        loser's connection is shut down so the replica's disconnect
        watcher sheds its queued work (PR-7 cancellation).

        Chaos accounting note: attempts run on worker threads, so
        injections here are not attributed to a thread-local
        ``chaos.operation`` — a hedged request's fault tolerance is read
        from ``nv_fleet_hedges_total`` outcomes instead."""
        router = self.router
        results: "queue.Queue" = queue.Queue()

        def run(tag: str, lease, headers: dict, box: dict):
            started = time.monotonic()
            try:
                chaos.fire(
                    chaos.SITE_FLEET_REPLICA_PREFIX + lease.replica.name
                )
                out = self._exchange(
                    lease.replica.http_address, "POST", body, headers,
                    conn_box=box,
                )
                router.fleetscope.record_request(
                    model, tenant,
                    int((time.monotonic() - started) * 1e6),
                    out[0] < 500, lease.replica.name, trace_id=trace_id,
                )
                results.put((tag, lease, box, out, None))
            except (_ExchangeError, OSError) as failure:
                if not isinstance(failure, _ExchangeError):
                    failure = _ExchangeError(PHASE_CONNECT, failure)
                router.fleetscope.record_request(
                    model, tenant,
                    int((time.monotonic() - started) * 1e6),
                    False, lease.replica.name, trace_id=trace_id,
                )
                results.put((tag, lease, box, None, failure))

        def launch(tag: str, exclude=()):
            lease = router.begin(tenant, exclude=exclude)
            headers = self._forward_headers(body)
            if tag != "primary":
                headers[HEADER_HEDGE_ATTEMPT] = "1"
            box: dict = {"conn": None}
            thread = threading.Thread(
                target=run, args=(tag, lease, headers, box),
                daemon=True, name=f"fleet-hedge-{tag}",
            )
            thread.start()
            return lease, box

        def cancel(box: dict):
            conn = box.get("conn")
            if conn is None:
                return
            sock = getattr(conn, "sock", None)
            if sock is not None:
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass

        boxes: Dict[str, dict] = {}
        winner = None
        failures = []

        def handle(item):
            nonlocal winner
            tag, lease, box, out, failure = item
            cancelled = box.get("cancelled", False)
            if failure is not None or out[0] >= 500:
                # A cancel-induced failure is the router's own doing —
                # neither a lease failure nor breaker evidence.
                lease.release(failed=not cancelled)
                if not cancelled:
                    router.note_replica_result(lease.replica, ok=False)
                failures.append((tag, out, failure))
                return
            router.note_replica_result(lease.replica, ok=True)
            lease.release()
            if winner is None:
                winner = (tag, out)
            # else: both answered before the cancel landed; the slower
            # response is simply dropped.

        primary_lease, primary_box = launch("primary")
        boxes["primary"] = primary_box
        remaining = 1
        try:
            first = results.get(timeout=router.hedge_us / 1e6)
        except queue.Empty:
            first = None
        hedged = False
        if first is not None:
            remaining -= 1
        if first is None or first[4] is not None or first[3][0] >= 500:
            # Primary slow (hedge) or already failed (failover): second
            # attempt on a different replica — a fresh admission charge.
            try:
                _, hedge_box = launch(
                    "hedge", exclude=(primary_lease.replica.name,)
                )
                boxes["hedge"] = hedge_box
                hedged = True
                remaining += 1
            except FleetError:
                pass  # nowhere to hedge; ride the primary alone
        if first is not None:
            handle(first)
        while remaining:
            if winner is not None:
                # Cancel the still-running loser: the socket shutdown
                # makes its replica's disconnect watcher shed the work.
                for tag, box in boxes.items():
                    if tag != winner[0] and not box.get("cancelled"):
                        box["cancelled"] = True
                        cancel(box)
            handle(results.get())
            remaining -= 1
        if hedged:
            if winner is None:
                router.note_hedge(HEDGE_OUTCOME_FAILED)
            else:
                router.note_hedge(
                    HEDGE_OUTCOME_PRIMARY if winner[0] == "primary"
                    else HEDGE_OUTCOME_HEDGE
                )
        if winner is not None:
            return self._relay(*winner[1])
        tag, out, failure = failures[-1]
        if out is not None:
            return self._relay(*out)
        raise FleetError(
            f"all hedged attempts failed: {failure.cause}", 502
        )


class _RouterHTTPServer(ThreadingHTTPServer):
    # Same accept-burst headroom as the replica front-end.
    request_queue_size = 128


class RouterHTTPFrontend:
    """Threaded HTTP server hosting a FleetRouter."""

    def __init__(self, router: FleetRouter, host: str = "127.0.0.1",
                 port: int = 0, verbose: bool = False,
                 max_request_bytes: int = MAX_REQUEST_BYTES_DEFAULT):
        self._server = _RouterHTTPServer((host, port), _RouterHandler)
        self._server.router = router
        self._server.max_request_bytes = max_request_bytes
        self._server.pool = _ConnPool()
        # A rejoined (crash-restarted) replica is a NEW process on the
        # old address: pooled keep-alive connections to the dead
        # incarnation must be dropped before traffic resumes.
        router.add_rejoin_listener(
            lambda replica: self._server.pool.invalidate(
                replica.http_address
            )
        )
        self._server.verbose = verbose
        self._server.daemon_threads = True
        self._server.socket.setsockopt(
            socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
        )
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> str:
        host, port = self._server.server_address[:2]
        return f"{host}:{port}"

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="fleet-http-frontend",
        )
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()
        self._server.pool.close()
        if self._thread:
            self._thread.join(timeout=5)

"""gRPC front-end of the fleet router: raw-bytes passthrough.

The router never deserializes ``ModelInferRequest`` protos — admission
needs only the ``tenant-id`` invocation metadata and balancing needs
only the method — so forwarded messages cross the router as opaque
bytes (identity serializers on both the inbound handler and the
outbound multicallable). That keeps the router's per-request cost to a
metadata walk plus one channel write, and guarantees deadline
parameters and trace context inside the proto forward bit-exact.

Sticky streams: a ``ModelStreamInfer`` stream leases one replica at
open (rendezvous-hashed when the client sends a
``stream-affinity-key``/tenant, policy-balanced otherwise) and pipes
messages both ways until either side closes; the stream holds one
outstanding-lease for its lifetime.

Fleet-level surfaces (``ServerLive``/``ServerReady``) answer locally
with typed protos; shared-nothing admin RPCs (shm registration,
repository control, trace/log settings) fan out to every ready replica.
"""

import collections
import queue as queue_module
import threading
from concurrent import futures
from typing import Dict, Optional, Tuple

import grpc

from tritonclient_tpu import chaos, sanitize
from tritonclient_tpu.fleet._router import FleetError, FleetRouter
from tritonclient_tpu.grpc._client import classify_rpc_error
from tritonclient_tpu.protocol import pb
from tritonclient_tpu.protocol._literals import (
    HEADER_IDEMPOTENCY_KEY,
    HEADER_TENANT_ID,
    STATUS_OVER_QUOTA,
)
from tritonclient_tpu.protocol._service import FULL_SERVICE_NAME, RPC_METHODS

_MAX_MESSAGE_LENGTH = 2**31 - 1

#: Invocation-metadata key selecting the replica a stream sticks to
#: (rendezvous-hashed); absent, the stream falls back to the tenant id,
#: then to the balancing policy.
HEADER_STREAM_AFFINITY = "stream-affinity-key"

#: Metadata keys forwarded router -> replica (same allowlist as the HTTP
#: proxy): tenant accounting, W3C trace context, request-id tagging.
_FORWARD_METADATA_KEYS = (
    HEADER_TENANT_ID,
    "traceparent",
    "triton-request-id",
)

#: RPCs whose effect is per-replica state every ready replica needs.
_FAN_OUT_METHODS = frozenset({
    "SystemSharedMemoryRegister",
    "SystemSharedMemoryUnregister",
    "TpuSharedMemoryRegister",
    "TpuSharedMemoryUnregister",
    "RepositoryModelLoad",
    "RepositoryModelUnload",
    "TraceSetting",
    "LogSettings",
})


def _ident(payload: bytes) -> bytes:
    return payload


def _code_for(e: FleetError) -> grpc.StatusCode:
    if e.status == STATUS_OVER_QUOTA:
        return grpc.StatusCode.RESOURCE_EXHAUSTED
    if e.status in (502, 503):
        return grpc.StatusCode.UNAVAILABLE
    return grpc.StatusCode.UNKNOWN


def _call_metadata(context) -> Dict[str, str]:
    try:
        pairs = context.invocation_metadata()
    except Exception:
        return {}
    return {k: v for k, v in pairs or ()}


def _forward_metadata(meta: Dict[str, str]) -> Tuple:
    return tuple(
        (k, meta[k]) for k in _FORWARD_METADATA_KEYS if k in meta
    )


#: time_remaining() values above this are "no deadline" (gRPC reports
#: INT64_MAX seconds; forwarding it overflows the outbound deadline
#: arithmetic into an already-expired deadline).
_NO_DEADLINE_S = 3600.0 * 24 * 365


def _deadline(context) -> Optional[float]:
    remaining = context.time_remaining()
    if remaining is None or remaining <= 0 or remaining > _NO_DEADLINE_S:
        return None
    return remaining


class _ReplicaChannels:
    """One lazily opened channel per replica address, with per-method
    raw-bytes multicallables cached beside it."""

    def __init__(self):
        self._lock = sanitize.named_lock("fleet._ReplicaChannels._lock")
        self._channels: Dict[str, tuple] = {}

    def _entry(self, address: str):
        with self._lock:
            entry = self._channels.get(address)
        if entry is not None:
            return entry
        channel = grpc.insecure_channel(
            address,
            options=[
                ("grpc.max_send_message_length", _MAX_MESSAGE_LENGTH),
                ("grpc.max_receive_message_length", _MAX_MESSAGE_LENGTH),
            ],
        )
        with self._lock:
            # A racing opener wins; close the loser outside the lock.
            entry = self._channels.get(address)
            if entry is None:
                entry = (channel, {})
                self._channels[address] = entry
                channel = None
        if channel is not None:
            channel.close()
        return entry

    def unary(self, address: str, method: str):
        channel, calls = self._entry(address)
        call = calls.get(method)
        if call is None:
            call = calls[method] = channel.unary_unary(
                f"/{FULL_SERVICE_NAME}/{method}",
                request_serializer=_ident,
                response_deserializer=_ident,
            )
        return call

    def stream(self, address: str, method: str):
        channel, calls = self._entry(address)
        key = ("stream", method)
        call = calls.get(key)
        if call is None:
            call = calls[key] = channel.stream_stream(
                f"/{FULL_SERVICE_NAME}/{method}",
                request_serializer=_ident,
                response_deserializer=_ident,
            )
        return call

    def close(self):
        with self._lock:
            channels = [c for c, _ in self._channels.values()]
            self._channels.clear()
        for channel in channels:
            channel.close()


def make_router_handler(router: FleetRouter,
                        channels: _ReplicaChannels) -> grpc.GenericRpcHandler:
    """The router's GRPCInferenceService: typed local health, raw-bytes
    forwarding for everything else."""

    def server_live(request, context):
        return pb.ServerLiveResponse(live=True)

    def server_ready(request, context):
        return pb.ServerReadyResponse(ready=router.ready())

    def drain(request, context):
        context.abort(
            grpc.StatusCode.UNIMPLEMENTED,
            "drain a NAMED replica through the router's HTTP admin "
            "surface (POST v2/fleet/replicas/{name}/drain); the gRPC "
            "Drain RPC is a replica-level control",
        )

    def fleet_flight_recorder(request: bytes, context):
        # Answered LOCALLY: the trailing RPC_METHODS loop would
        # otherwise forward this to one replica, which cannot merge
        # the fleet. The fan-out to replica dump endpoints happens
        # inside merged_flight_dump (HTTP, outside any router lock).
        import json as _json

        return _json.dumps(router.merged_flight_dump()).encode()

    def model_infer(request: bytes, context):
        """Unary inference: admission + balance + policy-driven
        failover (same RetryPolicy instance as the HTTP proxy, so the
        retry budget and counters are router-global). UNAVAILABLE with
        a connect-phase detail is provably pre-execution; any other
        failure fails over only when the caller sent an idempotency
        key."""
        meta = _call_metadata(context)
        tenant = meta.get(HEADER_TENANT_ID, "")
        idempotent = HEADER_IDEMPOTENCY_KEY in meta
        fwd = _forward_metadata(meta)
        policy = router.retry_policy
        attempt = 0
        exclude = []
        with chaos.operation("fleet.grpc.infer"):
            while True:
                try:
                    lease = router.begin(tenant, exclude=tuple(exclude))
                except FleetError as e:
                    context.abort(_code_for(e), str(e))
                try:
                    chaos.fire(chaos.SITE_GRPC_CALL)
                    reply = channels.unary(
                        lease.replica.grpc_address, "ModelInfer"
                    )(request, metadata=fwd, timeout=_deadline(context))
                except grpc.RpcError as e:
                    lease.release(failed=True)
                    router.note_replica_result(lease.replica, ok=False)
                    if policy.should_retry(
                        attempt,
                        classify_rpc_error(policy, e,
                                           idempotent=idempotent),
                    ):
                        exclude.append(lease.replica.name)
                        policy.sleep(attempt)
                        attempt += 1
                        continue
                    context.abort(e.code(), e.details())
                router.note_replica_result(lease.replica, ok=True)
                policy.note_success()
                lease.release()
                return reply

    def model_stream_infer(request_iterator, context):
        """Sticky stream with crash resume.

        The stream leases one replica at open (rendezvous affinity). If
        that replica dies mid-stream, the stream RE-ESTABLISHES on a
        surviving replica: the rendezvous hash remaps the affinity key
        over the survivors, and piping continues. Requests that were
        sent but unanswered at the break are replayed on the new
        replica when the stream's metadata carries an idempotency key
        (the server answers a stream's requests in order, so the
        unanswered set is an exact FIFO suffix); without the key they
        are dropped and only future requests flow — resumption either
        way, double-execution never without the caller's opt-in.
        """
        meta = _call_metadata(context)
        tenant = meta.get(HEADER_TENANT_ID, "")
        affinity = meta.get(HEADER_STREAM_AFFINITY, "") or tenant
        idempotent = HEADER_IDEMPOTENCY_KEY in meta
        fwd = _forward_metadata(meta)
        policy = router.retry_policy

        # One pump thread owns the inbound iterator for the stream's
        # whole life (across downstream incarnations).
        inbound: "queue_module.Queue" = queue_module.Queue()
        closed = object()

        def pump():
            try:
                for message in request_iterator:
                    inbound.put(message)
            except Exception:  # noqa: BLE001 — client went away
                pass
            finally:
                inbound.put(closed)

        threading.Thread(
            target=pump, daemon=True, name="fleet-stream-pump"
        ).start()

        # FIFO of messages sent downstream but not yet answered — the
        # replay set for an idempotent resume (the server answers a
        # stream's requests in order, so this is an exact suffix).
        unanswered = collections.deque()
        replay = []
        attempt = 0
        exclude = []
        while True:
            try:
                lease = router.begin(tenant, affinity_key=affinity,
                                     exclude=tuple(exclude))
            except FleetError as e:
                context.abort(_code_for(e), str(e))
            stop = threading.Event()

            def feed(replay_now=tuple(replay), stop=stop):
                # Replays and fresh messages are tracked uniformly:
                # append to ``unanswered`` BEFORE yield, so a message
                # that reaches a dying call counts as unanswered, never
                # lost.
                for message in replay_now:
                    unanswered.append(message)
                    yield message
                while not stop.is_set():
                    try:
                        message = inbound.get(timeout=0.05)
                    except queue_module.Empty:
                        continue
                    if message is closed:
                        # Future incarnations must see EOF too.
                        inbound.put(closed)
                        return
                    unanswered.append(message)
                    yield message

            call = channels.stream(
                lease.replica.grpc_address, "ModelStreamInfer"
            )(feed(), metadata=fwd, timeout=_deadline(context))
            # Client cancellation tears down the downstream stream too,
            # so the replica's stream-cancel event fires and queued work
            # sheds.
            context.add_callback(call.cancel)
            try:
                for message in call:
                    if unanswered:
                        unanswered.popleft()
                    yield message
                lease.release()
                return
            except grpc.RpcError as e:
                stop.set()
                lease.release(failed=True)
                router.note_replica_result(lease.replica, ok=False)
                # Resumption itself is always safe (it sends nothing by
                # itself), so eligibility is judged as-if idempotent;
                # whether the unanswered suffix is REPLAYED is gated on
                # the caller's actual opt-in below.
                reason = classify_rpc_error(policy, e, idempotent=True)
                if reason is not None and policy.should_retry(
                    attempt, reason
                ):
                    exclude.append(lease.replica.name)
                    replay = list(unanswered) if idempotent else []
                    unanswered.clear()
                    policy.sleep(attempt)
                    attempt += 1
                    continue
                context.abort(e.code(), e.details())
            finally:
                stop.set()
                lease.release()

    def forward(name: str):
        fan_out = name in _FAN_OUT_METHODS

        def handler(request: bytes, context, _name=name,
                    _fan_out=fan_out):
            meta = _call_metadata(context)
            fwd = _forward_metadata(meta)
            timeout = _deadline(context)
            try:
                if not _fan_out:
                    replica = router.pick_any()
                    return channels.unary(
                        replica.grpc_address, _name
                    )(request, metadata=fwd, timeout=timeout)
                replicas = router.replica_set.routable()
                if not replicas:
                    raise FleetError("no ready replicas in the fleet", 503)
                reply = b""
                for replica in replicas:
                    reply = channels.unary(
                        replica.grpc_address, _name
                    )(request, metadata=fwd, timeout=timeout)
                return reply
            except FleetError as e:
                context.abort(_code_for(e), str(e))
            except grpc.RpcError as e:
                context.abort(e.code(), e.details())

        return handler

    handlers = {
        "ServerLive": grpc.unary_unary_rpc_method_handler(
            server_live,
            request_deserializer=pb.ServerLiveRequest.FromString,
            response_serializer=pb.ServerLiveResponse.SerializeToString,
        ),
        "ServerReady": grpc.unary_unary_rpc_method_handler(
            server_ready,
            request_deserializer=pb.ServerReadyRequest.FromString,
            response_serializer=pb.ServerReadyResponse.SerializeToString,
        ),
        "Drain": grpc.unary_unary_rpc_method_handler(
            drain,
            request_deserializer=_ident,
            response_serializer=_ident,
        ),
        "FleetFlightRecorder": grpc.unary_unary_rpc_method_handler(
            fleet_flight_recorder,
            request_deserializer=_ident,
            response_serializer=_ident,
        ),
        "ModelInfer": grpc.unary_unary_rpc_method_handler(
            model_infer,
            request_deserializer=_ident,
            response_serializer=_ident,
        ),
        "ModelStreamInfer": grpc.stream_stream_rpc_method_handler(
            model_stream_infer,
            request_deserializer=_ident,
            response_serializer=_ident,
        ),
    }
    for name, (kind, _req, _resp) in RPC_METHODS.items():
        if name in handlers or kind != "unary":
            continue
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            forward(name),
            request_deserializer=_ident,
            response_serializer=_ident,
        )
    return grpc.method_handlers_generic_handler(
        FULL_SERVICE_NAME, handlers
    )


class RouterGRPCFrontend:
    """gRPC front-end hosting a FleetRouter (thread-pool transport; each
    long-lived proxied stream pins one pool thread)."""

    def __init__(self, router: FleetRouter, host: str = "127.0.0.1",
                 port: int = 0, max_workers: int = 80):
        self._host = host
        self._channels = _ReplicaChannels()
        self._server = grpc.server(
            futures.ThreadPoolExecutor(
                max_workers=max_workers, thread_name_prefix="fleet-grpc"
            ),
            options=[
                ("grpc.max_send_message_length", _MAX_MESSAGE_LENGTH),
                ("grpc.max_receive_message_length", _MAX_MESSAGE_LENGTH),
            ],
        )
        self._server.add_generic_rpc_handlers(
            [make_router_handler(router, self._channels)]
        )
        self._port = self._server.add_insecure_port(f"{host}:{port}")

    @property
    def address(self) -> str:
        return f"{self._host}:{self._port}"

    def start(self):
        self._server.start()
        return self

    def stop(self, grace: Optional[float] = 0.5):
        self._server.stop(grace)
        self._channels.close()

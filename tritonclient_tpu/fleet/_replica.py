"""Replica membership for the fleet router.

Replicas are separate ``server/_core`` processes (one device / mesh
partition each) known by address. A prober thread drives their state
from the signals the observability plane already exposes:

* ``GET v2/health/ready`` — the readiness verdict plus the readiness
  detail document (``draining``, ``in_flight``) PR 8 added for exactly
  this consumer;
* ``GET /metrics`` — ``nv_inference_queue_depth`` (summed over models)
  and ``nv_inference_oldest_request_age_us`` (max), the
  backlog-vs-stall discriminator pair.

State machine::

    JOINING --probe ok--> READY --failures>=eject_after--> EJECTED
       ^                    |                                 |
       |                 drain()                       backoff elapses,
       |                    v                           probe ok -> READY
       +--undrain()--- DRAINING --in_flight==0--> DRAINED

Probe I/O always runs OUTSIDE the set lock (the lock guards membership
and counters only, never the network), so a hung replica cannot wedge
routing for the healthy ones.
"""

import json
import re
import threading
import time
from http.client import HTTPConnection
from typing import Dict, List, Optional

from tritonclient_tpu import sanitize
from tritonclient_tpu.protocol._literals import (
    EP_DEBUG_SKETCHES,
    EP_FLEET_DRAIN,
    EP_HEALTH_READY,
    EP_METRICS,
)


class ReplicaState:
    JOINING = "joining"
    READY = "ready"
    DRAINING = "draining"
    DRAINED = "drained"
    EJECTED = "ejected"


_QUEUE_DEPTH_RE = re.compile(
    r"^nv_inference_queue_depth(?:\{[^}]*\})? ([0-9.eE+-]+)", re.M
)
_OLDEST_AGE_RE = re.compile(
    r"^nv_inference_oldest_request_age_us(?:\{[^}]*\})? ([0-9.eE+-]+)", re.M
)


class Replica:
    """One replica's identity + live signals (owned by a ReplicaSet;
    counters mutate only under the set lock)."""

    def __init__(self, name: str, http_address: str,
                 grpc_address: str = ""):
        self.name = name
        self.http_address = http_address
        self.grpc_address = grpc_address
        self.state = ReplicaState.JOINING
        # Router-local signal: requests leased to this replica right now.
        self.outstanding = 0
        # Scraped signals (lag by one probe interval).
        self.queue_depth = 0
        self.oldest_age_us = 0
        self.in_flight = 0  # replica-reported, from the readiness detail
        self.consecutive_failures = 0
        self.ejections = 0
        self.backoff_until_s = 0.0
        self.requests_total = 0
        self.failures_total = 0
        self.last_error = ""
        # Crash-recovery bookkeeping: a probe failure marks the replica
        # as needing admin-state replay (a restarted process has empty
        # shm/repository/trace state even though it reports READY); the
        # ReplicaSet's on_rejoin hook must succeed before the replica
        # becomes routable again. ``restarts`` counts completed rejoins
        # (the nv_fleet_replica_restarts_total family).
        self.needs_replay = False
        self.restarts = 0
        # Scrape-staleness bookkeeping (satellite of the fleetscope
        # plane): when the last metrics scrape SUCCEEDED, and how many
        # probe ticks failed to produce one. A replica whose scrapes
        # are stale must not silently feed old samples into fleet
        # aggregation — the exposition makes the age visible
        # (nv_fleet_scrape_age_s) and fleetscope gates verdicts on it.
        self.last_scrape_s: Optional[float] = None
        self.scrape_failures = 0
        self.registered_s: Optional[float] = None

    def _snapshot_locked(self, now: float = 0.0) -> dict:
        """Point-in-time copy of the live signals. Caller MUST hold the
        owning ReplicaSet's lock — reach this through
        ``ReplicaSet.snapshot()``, never directly from a status/metrics
        path (the prober thread mutates these counters concurrently)."""
        reference = (
            self.last_scrape_s if self.last_scrape_s is not None
            else self.registered_s
        )
        scrape_age = max(now - reference, 0.0) if reference else 0.0
        return {
            "scrape_age_s": scrape_age,
            "scrape_failures": self.scrape_failures,
            "name": self.name,
            "http_address": self.http_address,
            "grpc_address": self.grpc_address,
            "state": self.state,
            "routable": self.state == ReplicaState.READY,
            "outstanding": self.outstanding,
            "queue_depth": self.queue_depth,
            "oldest_age_us": self.oldest_age_us,
            "in_flight": self.in_flight,
            "consecutive_failures": self.consecutive_failures,
            "requests_total": self.requests_total,
            "failures_total": self.failures_total,
            "restarts": self.restarts,
            "needs_replay": self.needs_replay,
            "last_error": self.last_error,
        }


def http_call(address: str, method: str, path: str,
              body: Optional[bytes] = None, timeout_s: float = 5.0,
              headers: Optional[dict] = None):
    """One short-lived HTTP exchange with a replica (probe / drain
    control). Returns (status, body bytes); raises OSError-family on
    transport failure. Deliberately connection-per-call: probes are low
    rate, and a pooled connection to a dying replica is exactly the
    stale resource a prober must not trust."""
    host, _, port = address.partition(":")
    conn = HTTPConnection(host, int(port or 80), timeout=timeout_s)
    try:
        conn.request(method, "/" + path.lstrip("/"), body=body,
                     headers=headers or {})
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


class ReplicaSet:
    """Membership + health-driven state for a set of replicas."""

    def __init__(self, probe_interval_s: float = 1.0,
                 eject_after: int = 3, backoff_base_s: float = 0.5,
                 backoff_max_s: float = 30.0,
                 probe_timeout_s: float = 2.0,
                 clock=time.monotonic):
        self.probe_interval_s = float(probe_interval_s)
        self.eject_after = int(eject_after)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self._clock = clock
        self._replicas: Dict[str, Replica] = {}
        # Crash-recovery hook: ``on_rejoin(replica) -> bool`` is called
        # (no locks held — it does network I/O) when a replica that
        # previously failed probes reports ready again; the replica only
        # becomes routable when the hook returns True. The FleetRouter
        # installs its admin-state replay here.
        self.on_rejoin = None
        # Fleetscope scrape observer (``set_observer``): fed every
        # probe tick's scraped metrics/sketches outside the set lock.
        self.observer = None
        self._lock = sanitize.named_lock("fleet.ReplicaSet._lock")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- membership -----------------------------------------------------------

    def add(self, name: str, http_address: str,
            grpc_address: str = "") -> Replica:
        replica = Replica(name, http_address, grpc_address)
        replica.registered_s = self._clock()
        with self._lock:
            if name in self._replicas:
                raise ValueError(f"replica '{name}' already registered")
            self._replicas[name] = replica
        return replica

    def remove(self, name: str):
        with self._lock:
            self._replicas.pop(name, None)

    def get(self, name: str) -> Optional[Replica]:
        with self._lock:
            return self._replicas.get(name)

    def replicas(self) -> List[Replica]:
        with self._lock:
            return sorted(self._replicas.values(), key=lambda r: r.name)

    def routable(self) -> List[Replica]:
        with self._lock:
            return sorted(
                (
                    r for r in self._replicas.values()
                    if r.state == ReplicaState.READY
                ),
                key=lambda r: r.name,
            )

    def snapshot(self) -> List[dict]:
        """Consistent copies of every replica's counters, taken under
        the set lock — the sanctioned read path for status endpoints and
        /metrics exposition (TPU009: the prober mutates the same fields
        under this lock)."""
        now = self._clock()
        with self._lock:
            return [
                r._snapshot_locked(now)
                for r in sorted(
                    self._replicas.values(), key=lambda r: r.name
                )
            ]

    def set_on_rejoin(self, hook):
        """Install the crash-recovery replay hook under the set lock
        (the prober reads it under the same lock)."""
        with self._lock:
            self.on_rejoin = hook

    def set_observer(self, observer):
        """Install the fleetscope scrape observer under the set lock.
        ``observer.observe_scrape(name, ok, metrics_text, sketches_doc,
        restarts, now)`` is invoked OUTSIDE the lock after every probe
        tick (same discipline as the rejoin hook: observers may do
        their own locking, never ours)."""
        with self._lock:
            self.observer = observer

    # -- lease counters -------------------------------------------------------

    def acquire(self, replica: Replica):
        with self._lock:
            # TPU009 lockset witness: router threads and the prober both
            # touch these counters; the witness proves the set lock is
            # held on every access (no-op unless TPUSAN is active).
            sanitize.note_field_access(replica, "outstanding")
            replica.outstanding += 1
            replica.requests_total += 1

    def release(self, replica: Replica, failed: bool = False):
        with self._lock:
            sanitize.note_field_access(replica, "outstanding")
            if replica.outstanding > 0:
                replica.outstanding -= 1
            if failed:
                replica.failures_total += 1

    # -- probing --------------------------------------------------------------

    def probe_once(self):
        """Probe every replica once (I/O outside the lock), then apply
        the observations. Callable directly for deterministic tests; the
        background prober loops it."""
        now = self._clock()
        with self._lock:
            targets = [
                r for r in self._replicas.values()
                if not (
                    r.state == ReplicaState.EJECTED
                    and now < r.backoff_until_s
                ) and r.state != ReplicaState.DRAINED
            ]
        for replica in targets:
            observation = self._probe(replica)
            self._apply(replica, observation)

    def _probe(self, replica: Replica) -> dict:
        try:
            status, body = http_call(
                replica.http_address, "GET", EP_HEALTH_READY,
                timeout_s=self.probe_timeout_s,
            )
            detail = {}
            if body:
                try:
                    detail = json.loads(body)
                except ValueError:
                    detail = {}
            observation = {
                "ok": True,
                "ready": status == 200,
                "draining": bool(detail.get("draining", False)),
                "in_flight": int(detail.get("in_flight", 0) or 0),
            }
        except (OSError, ValueError) as e:
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}
        # Metrics scrape rides the same probe tick; a scrape hiccup is
        # not a health failure (readiness already answered).
        try:
            _, metrics = http_call(
                replica.http_address, "GET", EP_METRICS,
                timeout_s=self.probe_timeout_s,
            )
            text = metrics.decode("utf-8", errors="replace")
            observation["metrics_text"] = text
            observation["queue_depth"] = int(sum(
                float(v) for v in _QUEUE_DEPTH_RE.findall(text)
            ))
            ages = [float(v) for v in _OLDEST_AGE_RE.findall(text)]
            observation["oldest_age_us"] = int(max(ages)) if ages else 0
        except (OSError, ValueError):
            pass
        # Raw sketch fetch (fleetscope only): merged fleet quantiles
        # need the replica's DDSketch state, not resolved quantiles.
        with self._lock:
            want_sketches = self.observer is not None
        if want_sketches and "metrics_text" in observation:
            try:
                status, body = http_call(
                    replica.http_address, "GET", EP_DEBUG_SKETCHES,
                    timeout_s=self.probe_timeout_s,
                )
                if status == 200 and body:
                    observation["sketches"] = json.loads(body)
            except (OSError, ValueError):
                pass
        return observation

    def _apply(self, replica: Replica, obs: dict):
        now = self._clock()
        rejoin_hook = None
        observer = None
        restarts_now = 0
        try:
            with self._lock:
                observer = self.observer
                scraped = "metrics_text" in obs
                if scraped:
                    replica.last_scrape_s = now
                else:
                    # No metrics text this tick (transport failure or a
                    # scrape hiccup on a healthy probe): staleness
                    # accrues and the failure is counted.
                    replica.scrape_failures += 1
                restarts_now = replica.restarts
                if not obs["ok"]:
                    replica.consecutive_failures += 1
                    replica.last_error = obs.get("error", "")
                    # A transport-failed probe means the process may have
                    # crashed (and restarted empty): whatever comes back on
                    # this address must have admin state replayed before it
                    # is routable again.
                    if replica.state != ReplicaState.DRAINED:
                        replica.needs_replay = True
                    if replica.state in (
                        ReplicaState.READY, ReplicaState.JOINING,
                    ) and replica.consecutive_failures >= self.eject_after:
                        replica.state = ReplicaState.EJECTED
                        replica.ejections += 1
                        replica.backoff_until_s = now + min(
                            self.backoff_base_s
                            * (2 ** (replica.ejections - 1)),
                            self.backoff_max_s,
                        )
                    elif replica.state == ReplicaState.EJECTED:
                        # Failed the post-backoff retry: back off further.
                        replica.ejections += 1
                        replica.backoff_until_s = now + min(
                            self.backoff_base_s
                            * (2 ** (replica.ejections - 1)),
                            self.backoff_max_s,
                        )
                    return
                replica.consecutive_failures = 0
                replica.last_error = ""
                replica.in_flight = obs.get("in_flight", replica.in_flight)
                if "queue_depth" in obs:
                    replica.queue_depth = obs["queue_depth"]
                if "oldest_age_us" in obs:
                    replica.oldest_age_us = obs["oldest_age_us"]
                if replica.state == ReplicaState.DRAINING:
                    if replica.in_flight == 0 and replica.outstanding == 0:
                        replica.state = ReplicaState.DRAINED
                    return
                if obs["draining"]:
                    # Drained out-of-band (operator hit the replica's
                    # drain endpoint directly): stop routing, track
                    # settlement.
                    replica.state = ReplicaState.DRAINING
                elif obs["ready"]:
                    if replica.needs_replay and self.on_rejoin is not None:
                        # Rejoin after a crash: replay admin state OUTSIDE
                        # the lock before the replica becomes routable.
                        rejoin_hook = self.on_rejoin
                    else:
                        if replica.needs_replay:
                            replica.needs_replay = False
                            replica.restarts += 1
                        replica.state = ReplicaState.READY
                        replica.ejections = 0
                else:
                    # Alive but declining traffic: not routable, not a
                    # fault.
                    replica.state = ReplicaState.JOINING
            if rejoin_hook is not None:
                try:
                    replayed = bool(rejoin_hook(replica))
                except Exception:  # a replay bug must not kill the prober
                    replayed = False
                with self._lock:
                    if replayed:
                        replica.needs_replay = False
                        replica.restarts += 1
                        replica.state = ReplicaState.READY
                        replica.ejections = 0
                    elif replica.state not in (
                        ReplicaState.DRAINING, ReplicaState.DRAINED,
                    ):
                        # Not servable yet: stay out of routing; the next
                        # probe retries the replay.
                        replica.state = ReplicaState.JOINING
        finally:
            # Fleetscope notification, OUTSIDE the set lock on every
            # path (the early returns above exit the with-block first):
            # observers take their own lock and must never nest inside
            # ours.
            if observer is not None:
                try:
                    observer.observe_scrape(
                        replica.name,
                        ok="metrics_text" in obs,
                        metrics_text=obs.get("metrics_text", ""),
                        sketches_doc=obs.get("sketches"),
                        restarts=restarts_now,
                        now=now,
                    )
                except Exception:  # an observer bug must not kill probing
                    pass

    # -- drain ----------------------------------------------------------------

    def drain(self, name: str, wait_s: float = 30.0,
              poll_s: float = 0.05) -> dict:
        """Gracefully drain one replica: stop routing to it, flip its
        readiness (so any OTHER balancer stops too), then wait for every
        in-flight request — router-leased and replica-reported — to
        finish. Returns the replica's final detail document."""
        with self._lock:
            replica = self._replicas.get(name)
            if replica is None:
                raise KeyError(f"unknown replica '{name}'")
            replica.state = ReplicaState.DRAINING
        status, body = http_call(
            replica.http_address, "POST", EP_FLEET_DRAIN,
            body=json.dumps({"drain": True}).encode(),
            timeout_s=self.probe_timeout_s,
        )
        detail = json.loads(body) if body else {}
        deadline = self._clock() + wait_s
        while self._clock() < deadline:
            with self._lock:
                outstanding = replica.outstanding
                replica.in_flight = int(detail.get("in_flight", 0) or 0)
                settled = outstanding == 0 and replica.in_flight == 0
                if settled:
                    replica.state = ReplicaState.DRAINED
            if settled:
                return detail
            # Deliberately-sync settle poll: drain runs on admin/prober
            # threads, never on an event loop.
            time.sleep(poll_s)  # tpulint: disable=TPU001
            _, body = http_call(
                replica.http_address, "GET", EP_HEALTH_READY,
                timeout_s=self.probe_timeout_s,
            )
            detail = json.loads(body) if body else {}
        raise TimeoutError(
            f"replica '{name}' did not settle within {wait_s}s "
            f"(outstanding={replica.outstanding}, "
            f"in_flight={detail.get('in_flight')})"
        )

    def undrain(self, name: str) -> dict:
        """Re-admit a drained replica: clear its drain flag, then let the
        normal probe path flip it READY once it reports ready (the
        immediate probe below makes that synchronous when healthy)."""
        with self._lock:
            replica = self._replicas.get(name)
            if replica is None:
                raise KeyError(f"unknown replica '{name}'")
            replica.state = ReplicaState.JOINING
        _, body = http_call(
            replica.http_address, "POST", EP_FLEET_DRAIN,
            body=json.dumps({"drain": False}).encode(),
            timeout_s=self.probe_timeout_s,
        )
        self._apply(replica, self._probe(replica))
        return json.loads(body) if body else {}

    # -- prober lifecycle -----------------------------------------------------

    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="fleet-health-prober"
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self):
        while not self._stop.is_set():
            try:
                self.probe_once()
            except Exception:  # a probe bug must not kill membership
                pass
            self._stop.wait(self.probe_interval_s)

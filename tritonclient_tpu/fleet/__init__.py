"""Fleet tier: a multi-tenant router over N ``server/_core`` replicas.

Every serving capability before this package lived inside a single
server process; serving heavy traffic from millions of users needs the
shared-nothing scale-out mode the shared-facility Triton deployments run
(arxiv 2312.06838): many tenants, one fleet, fairness enforced at
admission from ``/metrics`` + perf_analyzer signals. This package is
that tier — a thin router process speaking the same KServe v2 HTTP and
gRPC surfaces as the replicas:

* **membership** (``_replica``): replicas join by address; a health
  prober drives state from ``v2/health/ready`` (readiness detail:
  draining + in-flight) and ``/metrics`` scrapes (queue depth, oldest
  request age), with backoff-and-eject for unhealthy replicas and
  graceful drain for rolling restarts;
* **balancing** (``_policy``): least-outstanding (default),
  power-of-two-choices, and round-robin behind one interface, plus
  rendezvous-hash stream affinity for sticky streams;
* **admission** (``_admission``): per-tenant token-bucket quotas,
  concurrency caps, and priority classes keyed by the ``tenant-id``
  header — over-quota requests answered with a fast 429 /
  RESOURCE_EXHAUSTED before any replica I/O;
* **front-ends** (``_http`` / ``_grpc``): the router's own KServe v2
  surfaces. Inference traffic is balanced (HTTP: byte-level reverse
  proxy over pooled keep-alive connections; gRPC: raw-bytes passthrough
  — request protos are never deserialized in the router), admin traffic
  (shm registration, repository control, trace/log settings) fans out to
  every ready replica, and ``tenant-id`` / ``traceparent`` / deadline
  parameters forward untouched so traces and deadlines span
  router→replica.

``serve.py`` is the replica process entry (one device / mesh partition
per replica); ``__main__.py`` is the router CLI; ``scripts/fleet_bench.py``
is the perf gate recording ``FLEET_r01.json``.
"""

from tritonclient_tpu.fleet._admission import (  # noqa: F401
    AdmissionController,
    TenantQuota,
)
from tritonclient_tpu.fleet._fleetscope import (  # noqa: F401
    FleetScope,
    parse_exposition,
)
from tritonclient_tpu.fleet._grpc import RouterGRPCFrontend  # noqa: F401
from tritonclient_tpu.fleet._http import RouterHTTPFrontend  # noqa: F401
from tritonclient_tpu.fleet._policy import (  # noqa: F401
    POLICIES,
    affinity_select,
    make_policy,
)
from tritonclient_tpu.fleet._replica import (  # noqa: F401
    Replica,
    ReplicaSet,
    ReplicaState,
)
from tritonclient_tpu.fleet._router import (  # noqa: F401
    FleetError,
    FleetRouter,
)
from tritonclient_tpu.fleet._slo import (  # noqa: F401
    CohortDetector,
    SloObjective,
    SloRegistry,
)


class FleetServer:
    """A router hosted behind HTTP and/or gRPC on loopback — the fleet
    analog of ``server.InferenceServer`` (hermetic fixture + process
    entry). Ports default to 0 (ephemeral)."""

    def __init__(self, router: FleetRouter, http: bool = True,
                 grpc: bool = True, host: str = "127.0.0.1",
                 http_port: int = 0, grpc_port: int = 0):
        self.router = router
        self._http = (
            RouterHTTPFrontend(router, host, http_port) if http else None
        )
        self._grpc = (
            RouterGRPCFrontend(router, host, grpc_port) if grpc else None
        )

    @property
    def http_address(self):
        return self._http.address if self._http else None

    @property
    def grpc_address(self):
        return self._grpc.address if self._grpc else None

    def start(self):
        self.router.start()
        if self._http:
            self._http.start()
        if self._grpc:
            self._grpc.start()
        return self

    def stop(self):
        if self._http:
            self._http.stop()
        if self._grpc:
            self._grpc.stop()
        self.router.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

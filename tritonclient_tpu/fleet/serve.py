"""Replica process entry: one ``server/_core`` process per device.

The fleet tier is shared-nothing — each replica is its own process
owning one device / mesh partition. This module is what the bench, the
smoke tests, and operators launch per replica::

    python -m tritonclient_tpu.fleet.serve --address-file /tmp/r0.json \
        --model-set fleet --service-ms 25

Ports default to 0 (ephemeral); the bound addresses are published
atomically to ``--address-file`` as ``{"name", "http", "grpc", "pid"}``
so launchers never race the bind.

``FleetDeviceModel`` is the fleet bench's replica-capacity stand-in: an
identity model whose execution serializes on a single device slot (one
batch at a time, ``service_ms`` per execution) — the way a real
accelerator serializes launches — without burning host CPU. On a
CPU-only bench host that is what makes per-replica capacity additive
across replica PROCESSES, so the 2-replica aggregate-throughput gate
measures routing, not GIL contention inside one interpreter. The
``--model-set fleet`` set is deliberately jax-free: replica cold-start
is a process spawn plus imports, no backend init.
"""

import argparse
import json
import os
import signal
import sys
import threading
import time

import numpy as np

from tritonclient_tpu.models._base import Model, TensorSpec


class FleetDeviceModel(Model):
    """Identity INT32 [-1,16] whose executions serialize on one device
    slot for ``service_ms`` each — a replica-capacity model, not a
    compute model."""

    name = "fleet_device"
    platform = "fleet"
    # Real waits in infer(): must never run inline on an event loop.
    blocking = True

    def __init__(self, service_ms: float = 25.0):
        super().__init__()
        self.service_ms = float(service_ms)
        self.inputs = [TensorSpec("INPUT", "INT32", [-1, 16])]
        self.outputs = [TensorSpec("OUTPUT", "INT32", [-1, 16])]
        # One execution slot, like one accelerator: a semaphore (not a
        # lock) because the holder BLOCKS in it by design — this is the
        # modeled device time, not a critical section over shared state.
        self._slot = threading.BoundedSemaphore(1)

    def infer(self, inputs, parameters=None):
        with self._slot:
            # Modeled device execution time (deliberate; see class doc).
            time.sleep(self.service_ms / 1000.0)  # tpulint: disable=TPU001
        return {"OUTPUT": np.asarray(inputs["INPUT"], dtype=np.int32)}


def build_models(model_set: str, service_ms: float):
    if model_set == "fleet":
        return [FleetDeviceModel(service_ms)]
    from tritonclient_tpu.server import default_models

    models = default_models()
    if model_set == "all":
        models.append(FleetDeviceModel(service_ms))
    return models


def write_address_file(path: str, doc: dict):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="fleet.serve",
        description="Run one fleet replica (an InferenceCore behind "
        "HTTP + gRPC) as its own process",
    )
    parser.add_argument("--name", default=f"replica-{os.getpid()}")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--http-port", type=int, default=0)
    parser.add_argument("--grpc-port", type=int, default=0)
    parser.add_argument(
        "--model-set", choices=["fleet", "default", "all"], default="fleet",
        help="'fleet' = the jax-free capacity model only (fast start); "
        "'default' = the reference model matrix; 'all' = both",
    )
    parser.add_argument(
        "--service-ms", type=float,
        default=float(os.environ.get("FLEET_SERVICE_MS", "25")),
        help="modeled device time per fleet_device execution",
    )
    parser.add_argument(
        "--address-file", default="",
        help="publish bound addresses here as JSON (atomic)",
    )
    args = parser.parse_args(argv)

    from tritonclient_tpu.server import InferenceServer

    server = InferenceServer(
        models=build_models(args.model_set, args.service_ms),
        host=args.host, http_port=args.http_port, grpc_port=args.grpc_port,
    )
    server.start()
    doc = {
        "name": args.name,
        "http": server.http_address,
        "grpc": server.grpc_address,
        "pid": os.getpid(),
    }
    if args.address_file:
        write_address_file(args.address_file, doc)
    print(json.dumps(doc), flush=True)

    stop = threading.Event()

    def _terminate(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _terminate)
    signal.signal(signal.SIGINT, _terminate)
    stop.wait()
    server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())

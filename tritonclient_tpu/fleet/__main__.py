"""Fleet router CLI: ``python -m tritonclient_tpu.fleet``.

Typical two-replica bring-up (each replica launched via
``python -m tritonclient_tpu.fleet.serve --address-file rN.json``)::

    python -m tritonclient_tpu.fleet \
        --replica-address-file r0.json --replica-address-file r1.json \
        --policy least-outstanding --quota hostile=50:100:low \
        --address-file router.json

Replicas can also be named inline: ``--replica name=HTTP_ADDR[,GRPC_ADDR]``.
The router probes the fleet once before publishing its own address file,
so a launcher that waits for the file sees a routable fleet.
"""

import argparse
import json
import signal
import sys
import threading

from tritonclient_tpu.fleet import FleetRouter, FleetServer, ReplicaSet
from tritonclient_tpu.fleet._admission import TenantQuota
from tritonclient_tpu.fleet._policy import policy_names
from tritonclient_tpu.fleet.serve import write_address_file


def _parse_replica(spec: str):
    name, _, addrs = spec.partition("=")
    if not addrs:
        raise argparse.ArgumentTypeError(
            "--replica takes name=HTTP_ADDR[,GRPC_ADDR]"
        )
    http_addr, _, grpc_addr = addrs.partition(",")
    return name, http_addr, grpc_addr


def _parse_quota(spec: str):
    tenant, _, quota = spec.partition("=")
    if not quota:
        raise argparse.ArgumentTypeError(
            "--quota takes TENANT=rate[:burst[:priority[:max_outstanding]]]"
        )
    try:
        return tenant, TenantQuota.parse(quota)
    except (ValueError, IndexError) as e:
        raise argparse.ArgumentTypeError(f"bad quota spec {spec!r}: {e}")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="tritonclient_tpu.fleet",
        description="Multi-tenant KServe v2 router over N replicas",
    )
    parser.add_argument(
        "--replica", action="append", type=_parse_replica, default=[],
        metavar="NAME=HTTP[,GRPC]",
    )
    parser.add_argument(
        "--replica-address-file", action="append", default=[],
        metavar="PATH", help="a fleet.serve --address-file to join",
    )
    parser.add_argument("--policy", choices=policy_names(),
                        default="least-outstanding")
    parser.add_argument(
        "--quota", action="append", type=_parse_quota, default=[],
        metavar="TENANT=RATE[:BURST[:PRIORITY[:MAX_OUT]]]",
        help="per-tenant token-bucket quota; tenant 'default' covers "
        "requests without a tenant-id header",
    )
    parser.add_argument("--pressure-queue-depth", type=int, default=32)
    parser.add_argument(
        "--retry-attempts", type=int, default=3, metavar="N",
        help="failover RetryPolicy attempts per proxied infer (connect/"
        "send-phase failures always fail over; post-send only with an "
        "idempotency-key header)",
    )
    parser.add_argument(
        "--hedge-us", type=int, default=0, metavar="US",
        help="hedge idempotent unary infers onto a second replica after "
        "US microseconds without a response (0 = off); loser cancelled",
    )
    parser.add_argument(
        "--breaker-failures", type=int, default=3, metavar="N",
        help="consecutive proxy failures that open a replica's circuit "
        "breaker (excluded from routing until the cooldown probe)",
    )
    parser.add_argument("--breaker-reset", type=float, default=2.0,
                        metavar="SECONDS")
    parser.add_argument(
        "--slo-config", default="", metavar="PATH",
        help="JSON file declaring SLO objectives: a list of {model, "
        "tenant, latency_target_us, error_budget} documents (the same "
        "schema POST v2/fleet/slo takes)",
    )
    parser.add_argument(
        "--journal-file", default="", metavar="PATH",
        help="persist the admin journal (shm/repository admin, SLO "
        "objectives, cohort assignments) as JSON lines; reloaded on "
        "router restart",
    )
    parser.add_argument("--probe-interval", type=float, default=1.0,
                        metavar="SECONDS")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--http-port", type=int, default=0)
    parser.add_argument("--grpc-port", type=int, default=0)
    parser.add_argument("--address-file", default="")
    args = parser.parse_args(argv)

    replicas = list(args.replica)
    for path in args.replica_address_file:
        with open(path) as f:
            doc = json.load(f)
        replicas.append((doc["name"], doc["http"], doc.get("grpc") or ""))
    if not replicas:
        parser.error("at least one --replica / --replica-address-file")

    from tritonclient_tpu.resilience import RetryPolicy

    replica_set = ReplicaSet(probe_interval_s=args.probe_interval)
    router = FleetRouter(
        replicas=replica_set,
        policy=args.policy,
        quotas=dict(args.quota),
        pressure_queue_depth=args.pressure_queue_depth,
        retry_policy=RetryPolicy(max_attempts=max(args.retry_attempts, 1)),
        breaker_failure_threshold=args.breaker_failures,
        breaker_reset_s=args.breaker_reset,
        hedge_us=args.hedge_us or None,
        journal_path=args.journal_file or None,
    )
    if args.slo_config:
        with open(args.slo_config) as f:
            objectives = json.load(f)
        for doc in objectives:
            router.fleetscope.set_objective(doc)
    for name, http_addr, grpc_addr in replicas:
        router.add_replica(name, http_addr, grpc_addr)
    replica_set.probe_once()  # routable before the address file appears

    server = FleetServer(
        router, host=args.host,
        http_port=args.http_port, grpc_port=args.grpc_port,
    )
    server.start()
    doc = {
        "name": "router",
        "http": server.http_address,
        "grpc": server.grpc_address,
        "policy": args.policy,
        "replicas": [name for name, _h, _g in replicas],
    }
    if args.address_file:
        write_address_file(args.address_file, doc)
    print(json.dumps(doc), flush=True)

    stop = threading.Event()

    def _terminate(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _terminate)
    signal.signal(signal.SIGINT, _terminate)
    stop.wait()
    server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())

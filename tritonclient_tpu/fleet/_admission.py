"""Per-tenant admission at the fleet router.

Quotas are enforced BEFORE any replica I/O — the whole point of a fast
429 is that a hostile tenant's over-quota traffic costs the fleet one
token-bucket check, not a queue slot on a replica. Three rejection
reasons, spelled once in ``protocol/_literals.QUOTA_REASONS``:

* ``rate`` — the tenant's token bucket is empty (sustained rate above
  its refill rate, burst above its capacity);
* ``concurrency`` — the tenant already has ``max_outstanding`` requests
  in flight through the router;
* ``pressure`` — the fleet is under pressure (every ready replica's
  scraped queue depth at/above the threshold) and the tenant's priority
  class is ``low``: best-effort traffic sheds first so paying tenants
  keep their latency.

The controller is transport-neutral: both router front-ends call
``admit``/``release`` with the ``tenant-id`` header value. Unknown
tenants fall to the ``default`` quota (unlimited unless configured).
"""

import time
from typing import Dict, Optional, Tuple

from tritonclient_tpu import sanitize
from tritonclient_tpu.protocol._literals import (
    QUOTA_REASON_CONCURRENCY,
    QUOTA_REASON_PRESSURE,
    QUOTA_REASON_RATE,
    QUOTA_REASONS,
)

#: Priority classes, highest first. ``low`` is the only class shed under
#: fleet pressure; the ordering exists so configs read as a vocabulary.
PRIORITY_CLASSES = ("high", "normal", "low")

#: The quota key unknown tenants (and requests without a tenant-id
#: header) resolve to.
DEFAULT_TENANT = "default"


class TenantQuota:
    """One tenant's admission contract.

    ``rate`` tokens/second refill into a bucket of ``burst`` capacity
    (rate <= 0 means unlimited rate). ``max_outstanding`` caps in-flight
    requests through the router (0 = uncapped). ``priority`` is the
    pressure-shed class.
    """

    __slots__ = ("rate", "burst", "max_outstanding", "priority")

    def __init__(self, rate: float = 0.0, burst: float = 0.0,
                 max_outstanding: int = 0, priority: str = "normal"):
        if priority not in PRIORITY_CLASSES:
            raise ValueError(
                f"priority {priority!r} not in {PRIORITY_CLASSES}"
            )
        self.rate = float(rate)
        self.burst = float(burst) if burst else max(float(rate), 1.0)
        self.max_outstanding = int(max_outstanding)
        self.priority = priority

    @property
    def unlimited_rate(self) -> bool:
        return self.rate <= 0

    def as_dict(self) -> dict:
        return {
            "rate": self.rate,
            "burst": self.burst,
            "max_outstanding": self.max_outstanding,
            "priority": self.priority,
        }

    @classmethod
    def parse(cls, spec: str) -> "TenantQuota":
        """``rate[:burst[:priority[:max_outstanding]]]`` — the CLI shape
        (``--quota tenant=10:20:low``)."""
        parts = spec.split(":")
        rate = float(parts[0])
        burst = float(parts[1]) if len(parts) > 1 and parts[1] else 0.0
        priority = parts[2] if len(parts) > 2 and parts[2] else "normal"
        max_outstanding = int(parts[3]) if len(parts) > 3 and parts[3] else 0
        return cls(rate, burst, max_outstanding, priority)


def _check(tenant, quota, now, under_pressure, cost, outstanding,
           buckets):
    """The admission decision over CALLER-LOCKED state (``outstanding``
    and ``buckets`` belong to the controller's lock; this function never
    touches the controller so the lock discipline stays visible at the
    one call site)."""
    if quota is None:
        return None  # no quota configured anywhere: open admission
    if under_pressure and quota.priority == "low":
        return QUOTA_REASON_PRESSURE
    if quota.max_outstanding and (
        outstanding.get(tenant, 0) >= quota.max_outstanding
    ):
        return QUOTA_REASON_CONCURRENCY
    if quota.unlimited_rate:
        return None
    bucket = buckets.get(tenant)
    if bucket is None:
        bucket = buckets[tenant] = [quota.burst, now]
    tokens, last = bucket
    tokens = min(quota.burst, tokens + (now - last) * quota.rate)
    if tokens < cost:
        bucket[0], bucket[1] = tokens, now
        return QUOTA_REASON_RATE
    bucket[0], bucket[1] = tokens - cost, now
    return None


class AdmissionController:
    """Token buckets + concurrency caps + pressure shed, one lock.

    The hot path (``admit``) does one monotonic read and O(1) arithmetic
    under the named lock — never I/O, never a nested lock — so a flood
    of over-quota traffic is answered at memory speed. Rejection
    counters key ``(tenant, reason)`` and feed the router's
    ``nv_fleet_tenant_quota_rejections_total`` family.
    """

    def __init__(self, quotas: Optional[Dict[str, TenantQuota]] = None,
                 clock=time.monotonic):
        self._quotas = dict(quotas or {})
        self._clock = clock
        # tenant -> [tokens, last_refill_s]; created lazily per tenant.
        self._buckets: Dict[str, list] = {}
        self._outstanding: Dict[str, int] = {}
        self._rejections: Dict[Tuple[str, str], int] = {}
        self._admitted: Dict[str, int] = {}
        self._lock = sanitize.named_lock("fleet.AdmissionController._lock")

    # -- config ---------------------------------------------------------------

    def set_quota(self, tenant: str, quota: TenantQuota):
        with self._lock:
            self._quotas[tenant] = quota
            self._buckets.pop(tenant, None)  # restart from the new burst

    def quota_for(self, tenant: str) -> Optional[TenantQuota]:
        with self._lock:
            return self._quotas.get(tenant) or self._quotas.get(
                DEFAULT_TENANT
            )

    # -- admission ------------------------------------------------------------

    def admit(self, tenant: str, under_pressure: bool = False,
              cost: float = 1.0) -> Optional[str]:
        """Admit one request for ``tenant``; returns None (admitted, the
        caller MUST pair with ``release``) or the rejection reason."""
        tenant = tenant or DEFAULT_TENANT
        now = self._clock()
        with self._lock:
            quota = self._quotas.get(tenant) or self._quotas.get(
                DEFAULT_TENANT
            )
            reason = _check(
                tenant, quota, now, under_pressure, cost,
                self._outstanding, self._buckets,
            )
            if reason is None:
                self._outstanding[tenant] = (
                    self._outstanding.get(tenant, 0) + 1
                )
                self._admitted[tenant] = self._admitted.get(tenant, 0) + 1
            else:
                self._rejections[(tenant, reason)] = (
                    self._rejections.get((tenant, reason), 0) + 1
                )
                # Seen-tenant registration: the metrics family renders
                # every canonical reason row per tenant it has seen.
                self._admitted.setdefault(tenant, 0)
        return reason

    def release(self, tenant: str):
        """The completion half of a successful ``admit``."""
        tenant = tenant or DEFAULT_TENANT
        with self._lock:
            count = self._outstanding.get(tenant, 0)
            if count > 0:
                self._outstanding[tenant] = count - 1

    # -- introspection --------------------------------------------------------

    def rejection_counts(self) -> Dict[str, Dict[str, int]]:
        """{tenant: {reason: count}} with EVERY canonical reason present
        per seen tenant (zeros included) — the stable-label-set contract
        the metrics checker enforces for the quota family."""
        with self._lock:
            tenants = set(self._admitted) | {t for t, _ in self._rejections}
            return {
                tenant: {
                    reason: self._rejections.get((tenant, reason), 0)
                    for reason in QUOTA_REASONS
                }
                for tenant in sorted(tenants)
            }

    def status(self) -> dict:
        with self._lock:
            return {
                "quotas": {
                    t: q.as_dict() for t, q in sorted(self._quotas.items())
                },
                "outstanding": {
                    t: n for t, n in sorted(self._outstanding.items()) if n
                },
                "admitted": dict(sorted(self._admitted.items())),
                "rejections": {
                    f"{t}:{r}": n
                    for (t, r), n in sorted(self._rejections.items())
                },
            }

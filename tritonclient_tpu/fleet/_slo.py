"""SLO engine + cohort-delta detector for the fleet router.

Two consumers drove this design (ROADMAP items 4 and 5): the autoscaler
needs *trend* signals (burn rates over scraped time series) and canary
auto-rollback needs a *verdict* (did the canary cohort regress vs the
baseline). Both live here, fed by the router's own per-request
observations (``FleetScope.record_request``), so the signal covers the
full router→replica path the clients actually experience.

**SLO engine** (:class:`SloRegistry`): declarative per-model/per-tenant
objectives — a latency target plus an error budget — evaluated into
multi-window burn rates. An event is *bad* when it errored OR exceeded
the latency target; ``burn = bad_fraction / error_budget`` (burn 1.0 =
exactly consuming budget; the classic page thresholds are fast>14.4,
slow>6). The fast window is one bucket, the slow window
``SLOW_WINDOW_BUCKETS`` buckets; bucket width comes from
``TPU_FLEETSCOPE_WINDOW_S`` (default 60 s) so tests compress an
"hour" into fractions of a second without touching the math.

**Cohort-delta detector** (:class:`CohortDetector`): replicas are
partitioned into labeled cohorts (default ``baseline``); per bucket the
detector keeps each cohort's request count, bad count, and an exact
:class:`~tritonclient_tpu._sketch.LatencySketch` of durations. A cohort
regresses when ``confirm_windows`` CONSECUTIVE buckets each show its
p99 above ``p99_ratio`` × baseline p99 or its error rate above
baseline + ``error_rate_delta`` — with a minimum-sample gate per bucket
and a stale-scrape gate per replica, both of which answer
``insufficient-data`` rather than guessing.

Pure data structures: no I/O, no threads. Locking is the caller's
(:class:`~tritonclient_tpu.fleet._fleetscope.FleetScope` wraps every
entry point in one named lock).
"""

import math
import os
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from tritonclient_tpu._sketch import LatencySketch
from tritonclient_tpu.protocol._literals import (
    COHORT_BASELINE,
    COHORT_CLEAN,
    COHORT_INSUFFICIENT,
    COHORT_LABEL_RE,
    COHORT_REGRESSED,
    SLO_WINDOW_FAST,
    SLO_WINDOW_SLOW,
    SLO_WINDOWS,
)

#: Bucket width in seconds (the "1 minute" of multi-window burn-rate
#: alerting). Tests shrink it so an hour-equivalent slow window closes
#: in milliseconds.
DEFAULT_WINDOW_S = 60.0

#: Slow window span in buckets (the "1 hour" = 60 x fast).
SLOW_WINDOW_BUCKETS = 60

#: Ring bound: how many closed buckets each series retains.
DEFAULT_WINDOWS = 120


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def window_s() -> float:
    return _env_float("TPU_FLEETSCOPE_WINDOW_S", DEFAULT_WINDOW_S)


def max_windows() -> int:
    return max(_env_int("TPU_FLEETSCOPE_WINDOWS", DEFAULT_WINDOWS),
               SLOW_WINDOW_BUCKETS + 1)


class SloObjective:
    """One declarative objective: requests for (model, tenant) should
    answer OK within ``latency_target_us``, with at most
    ``error_budget`` of them allowed to miss. ``tenant`` empty = all
    tenants of the model."""

    __slots__ = ("model", "tenant", "latency_target_us", "error_budget")

    def __init__(self, model: str, tenant: str = "",
                 latency_target_us: int = 1_000_000,
                 error_budget: float = 0.01):
        if not model:
            raise ValueError("SLO objective requires a model")
        if not 0.0 < float(error_budget) <= 1.0:
            raise ValueError(
                f"error_budget must be in (0, 1], got {error_budget}"
            )
        if int(latency_target_us) <= 0:
            raise ValueError("latency_target_us must be positive")
        self.model = model
        self.tenant = tenant or ""
        self.latency_target_us = int(latency_target_us)
        self.error_budget = float(error_budget)

    def to_dict(self) -> dict:
        return {
            "model": self.model,
            "tenant": self.tenant,
            "latency_target_us": self.latency_target_us,
            "error_budget": self.error_budget,
        }

    @classmethod
    def from_dict(cls, doc: dict) -> "SloObjective":
        return cls(
            model=doc.get("model", ""),
            tenant=doc.get("tenant", "") or "",
            latency_target_us=int(doc.get("latency_target_us",
                                          1_000_000)),
            error_budget=float(doc.get("error_budget", 0.01)),
        )


class _BucketSeries:
    """Bounded map of bucket index -> [total, bad]."""

    __slots__ = ("buckets", "limit")

    def __init__(self, limit: int):
        self.buckets: "OrderedDict[int, List[int]]" = OrderedDict()
        self.limit = limit

    def add(self, index: int, bad: bool):
        cell = self.buckets.get(index)
        if cell is None:
            cell = self.buckets[index] = [0, 0]
            while len(self.buckets) > self.limit:
                self.buckets.popitem(last=False)
        cell[0] += 1
        if bad:
            cell[1] += 1

    def window_counts(self, end_index: int, span: int) -> Tuple[int, int]:
        """(total, bad) over bucket indices in (end_index - span,
        end_index]."""
        total = bad = 0
        for index, (t, b) in self.buckets.items():
            if end_index - span < index <= end_index:
                total += t
                bad += b
        return total, bad


class SloRegistry:
    """Objectives + windowed good/bad accounting + burn-rate math.

    ``record`` is called once per routed request with the request's
    wall duration and outcome; the registry buckets it against every
    matching objective ((model, tenant) exact match first, then the
    model-wide ``tenant=""`` objective).
    """

    def __init__(self):
        self._objectives: "OrderedDict[Tuple[str, str], SloObjective]" = (
            OrderedDict()
        )
        # (model, tenant of the OBJECTIVE) -> series
        self._series: Dict[Tuple[str, str], _BucketSeries] = {}

    # -- objectives -----------------------------------------------------------

    def set_objective(self, objective: SloObjective):
        self._objectives[(objective.model, objective.tenant)] = objective

    def remove_objective(self, model: str, tenant: str = "") -> bool:
        return self._objectives.pop((model, tenant or ""), None) is not None

    def objectives(self) -> List[SloObjective]:
        return list(self._objectives.values())

    def _matching(self, model: str,
                  tenant: str) -> List[SloObjective]:
        out = []
        exact = self._objectives.get((model, tenant))
        if exact is not None:
            out.append(exact)
        if tenant:
            model_wide = self._objectives.get((model, ""))
            if model_wide is not None:
                out.append(model_wide)
        return out

    # -- accounting -----------------------------------------------------------

    def record(self, model: str, tenant: str, duration_us: int,
               ok: bool, bucket_index: int, limit: int):
        for objective in self._matching(model, tenant):
            bad = (not ok) or duration_us > objective.latency_target_us
            key = (objective.model, objective.tenant)
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = _BucketSeries(limit)
            series.add(bucket_index, bad)

    # -- evaluation -----------------------------------------------------------

    def burn_rows(self, bucket_index: int) -> List[dict]:
        """One row per (objective, window): burn rate and remaining
        budget. Rendered into ``nv_fleet_slo_burn_rate`` /
        ``nv_fleet_slo_budget_remaining``."""
        rows = []
        spans = {SLO_WINDOW_FAST: 1, SLO_WINDOW_SLOW: SLOW_WINDOW_BUCKETS}
        for (model, tenant), objective in self._objectives.items():
            series = self._series.get((model, tenant))
            for window in SLO_WINDOWS:
                total = bad = 0
                if series is not None:
                    total, bad = series.window_counts(
                        bucket_index, spans[window]
                    )
                bad_fraction = (bad / total) if total else 0.0
                burn = bad_fraction / objective.error_budget
                if total:
                    remaining = 1.0 - bad / (
                        total * objective.error_budget
                    )
                    remaining = min(max(remaining, 0.0), 1.0)
                else:
                    remaining = 1.0
                rows.append({
                    "model": model,
                    "tenant": tenant,
                    "window": window,
                    "total": total,
                    "bad": bad,
                    "burn_rate": burn,
                    "budget_remaining": remaining,
                })
        return rows


class _CohortBucket:
    __slots__ = ("total", "bad", "sketch")

    def __init__(self):
        self.total = 0
        self.bad = 0
        self.sketch = LatencySketch()


class CohortDetector:
    """Baseline-vs-cohort regression detection over exact sketch merges.

    ``min_samples`` gates each compared bucket; ``confirm_windows``
    consecutive regressed buckets confirm a verdict (one bad window is
    noise, K in a row is a regression — the serving-comparison
    methodology of arxiv 2605.25645 applied to merged DDSketches).
    """

    def __init__(self, min_samples: int = 20, confirm_windows: int = 3,
                 p99_ratio: float = 1.5, error_rate_delta: float = 0.05):
        self.min_samples = int(min_samples)
        self.confirm_windows = max(int(confirm_windows), 1)
        self.p99_ratio = float(p99_ratio)
        self.error_rate_delta = float(error_rate_delta)
        self._assignments: Dict[str, str] = {}
        # cohort -> bucket index -> _CohortBucket (bounded)
        self._buckets: Dict[str, "OrderedDict[int, _CohortBucket]"] = {}

    # -- assignment -----------------------------------------------------------

    def assign(self, replica: str, cohort: str):
        cohort = (cohort or COHORT_BASELINE).strip().lower()
        if not replica:
            raise ValueError("cohort assignment requires a replica name")
        if not COHORT_LABEL_RE.match(cohort):
            raise ValueError(
                f"cohort label {cohort!r} is not canonical "
                "(lowercase slug: [a-z0-9][a-z0-9_-]*)"
            )
        self._assignments[replica] = cohort

    def cohort_of(self, replica: str) -> str:
        return self._assignments.get(replica, COHORT_BASELINE)

    def assignments(self) -> Dict[str, str]:
        return dict(self._assignments)

    def members(self, cohort: str, replicas: List[str]) -> List[str]:
        return [r for r in replicas if self.cohort_of(r) == cohort]

    # -- accounting -----------------------------------------------------------

    def record(self, replica: str, duration_us: int, ok: bool,
               bucket_index: int, limit: int):
        cohort = self.cohort_of(replica)
        series = self._buckets.get(cohort)
        if series is None:
            series = self._buckets[cohort] = OrderedDict()
        bucket = series.get(bucket_index)
        if bucket is None:
            bucket = series[bucket_index] = _CohortBucket()
            while len(series) > limit:
                series.popitem(last=False)
        bucket.total += 1
        if not ok:
            bucket.bad += 1
        bucket.sketch.insert(max(duration_us, 0))

    # -- evaluation -----------------------------------------------------------

    def _window_indices(self, bucket_index: int) -> List[int]:
        """The ``confirm_windows`` most recent bucket indices with any
        data in any cohort, newest last, capped at ``bucket_index``."""
        seen = set()
        for series in self._buckets.values():
            for index in series:
                if index <= bucket_index:
                    seen.add(index)
        return sorted(seen)[-self.confirm_windows:]

    def verdicts(self, bucket_index: int, replicas: List[str],
                 stale: Optional[List[str]] = None) -> List[dict]:
        """One verdict document per non-baseline cohort, compared
        against ``COHORT_BASELINE`` over the K most recent populated
        buckets. ``stale`` names replicas whose last scrape/observation
        is too old to trust — any stale member forces
        ``insufficient-data`` for its cohort."""
        stale_set = set(stale or ())
        cohorts = sorted(
            {self.cohort_of(r) for r in replicas}
            | set(self._buckets)
        )
        indices = self._window_indices(bucket_index)
        baseline = self._buckets.get(COHORT_BASELINE, OrderedDict())
        out = []
        for cohort in cohorts:
            if cohort == COHORT_BASELINE:
                continue
            members = self.members(cohort, replicas)
            doc = {
                "cohort": cohort,
                "baseline": COHORT_BASELINE,
                "replicas": members,
                "windows_compared": 0,
                "windows_regressed": 0,
                "p99_us": 0.0,
                "baseline_p99_us": 0.0,
                "error_rate": 0.0,
                "baseline_error_rate": 0.0,
                "samples": 0,
                "baseline_samples": 0,
            }
            stale_members = sorted(stale_set & set(members))
            if stale_members:
                doc["verdict"] = COHORT_INSUFFICIENT
                doc["reason"] = (
                    "stale scrape: " + ", ".join(stale_members)
                )
                out.append(doc)
                continue
            series = self._buckets.get(cohort, OrderedDict())
            if len(indices) < self.confirm_windows:
                doc["verdict"] = COHORT_INSUFFICIENT
                doc["reason"] = (
                    f"{len(indices)}/{self.confirm_windows} windows "
                    "observed"
                )
                out.append(doc)
                continue
            regressed_all = True
            insufficient = None
            merged = LatencySketch()
            merged_base = LatencySketch()
            total = bad = base_total = base_bad = 0
            for index in indices:
                mine = series.get(index)
                theirs = baseline.get(index)
                n_mine = mine.total if mine else 0
                n_theirs = theirs.total if theirs else 0
                if (n_mine < self.min_samples
                        or n_theirs < self.min_samples):
                    insufficient = (
                        f"window {index}: {n_mine}/{n_theirs} samples "
                        f"(need {self.min_samples} each)"
                    )
                    break
                merged.merge(mine.sketch)
                merged_base.merge(theirs.sketch)
                total += mine.total
                bad += mine.bad
                base_total += theirs.total
                base_bad += theirs.bad
                p99 = mine.sketch.quantile(0.99)
                base_p99 = theirs.sketch.quantile(0.99)
                err = mine.bad / mine.total
                base_err = theirs.bad / theirs.total
                latency_regressed = (
                    base_p99 > 0.0 and p99 > self.p99_ratio * base_p99
                )
                errors_regressed = (
                    err > base_err + self.error_rate_delta
                )
                doc["windows_compared"] += 1
                if latency_regressed or errors_regressed:
                    doc["windows_regressed"] += 1
                else:
                    regressed_all = False
            if insufficient is not None:
                doc["verdict"] = COHORT_INSUFFICIENT
                doc["reason"] = insufficient
                out.append(doc)
                continue
            doc["samples"] = total
            doc["baseline_samples"] = base_total
            doc["p99_us"] = merged.quantile(0.99)
            doc["baseline_p99_us"] = merged_base.quantile(0.99)
            doc["error_rate"] = (bad / total) if total else 0.0
            doc["baseline_error_rate"] = (
                (base_bad / base_total) if base_total else 0.0
            )
            doc["verdict"] = (
                COHORT_REGRESSED
                if regressed_all and doc["windows_compared"]
                == self.confirm_windows
                else COHORT_CLEAN
            )
            out.append(doc)
        return out


def merged_p99_matches_pooled(samples_by_replica: Dict[str, List[float]],
                              alpha: float = 0.01) -> Tuple[float, float]:
    """Drill helper: (merged-sketch p99, pooled-sample sketch p99) for
    the exactness acceptance check — merging per-replica sketches must
    equal sketching the pooled samples (bucket-wise merge is exact), and
    both sit within the sketch's relative-error bound of the true
    sample quantile."""
    per_replica = []
    for values in samples_by_replica.values():
        sketch = LatencySketch(alpha=alpha)
        sketch.extend(values)
        per_replica.append(sketch)
    merged = LatencySketch.merged(per_replica, alpha=alpha)
    pooled = LatencySketch(alpha=alpha)
    for values in samples_by_replica.values():
        pooled.extend(values)
    return merged.quantile(0.99), pooled.quantile(0.99)


def exact_quantile(values: List[float], q: float) -> float:
    """Nearest-rank sample quantile (the reference the sketch's 2%
    bound is stated against)."""
    if not values:
        return 0.0
    ordered = sorted(values)
    rank = max(int(math.ceil(q * len(ordered))), 1)
    return ordered[rank - 1]

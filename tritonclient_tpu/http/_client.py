"""Synchronous HTTP/REST client for the KServe v2 protocol.

Full method-surface parity with the reference client
(tritonclient/http/_client.py:102-1659). The reference rides geventhttpclient
with a greenlet pool; neither exists in a TPU image, so this build uses a
plain http.client connection pool with a bounded thread pool for async_infer —
preserving the behavioral contract that at most ``concurrency`` requests are
in flight and exceeding it blocks (http/_client.py:1489-1493).
"""

import gzip
import http.client
import json
import queue
import socket
import ssl as ssl_module
import threading
import zlib
import concurrent.futures as futures_module
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, Optional
from urllib.parse import urlparse

from tritonclient_tpu import chaos
from tritonclient_tpu.resilience import (
    PHASE_CONNECT,
    PHASE_RESPONSE,
    PHASE_SEND,
    CircuitBreaker,
    RetryPolicy,
    parse_retry_after,
)
from tritonclient_tpu.protocol._literals import (
    EP_FLIGHT_RECORDER,
    EP_HEALTH_LIVE,
    HEADER_IDEMPOTENCY_KEY,
    HEADER_RETRY_AFTER,
    HEADER_RETRY_ATTEMPT,
    EP_HEALTH_READY,
    EP_LOGGING,
    EP_REPOSITORY_INDEX,
    EP_SERVER_METADATA,
    KEY_UNLOAD_DEPENDENTS,
    model_config_path,
    model_infer_path,
    model_path,
    model_ready_path,
    model_stats_path,
    repository_load_path,
    repository_unload_path,
    shm_admin_path,
    trace_setting_path,
)
from tritonclient_tpu._client import InferenceServerClientBase
from tritonclient_tpu._request import Request
from tritonclient_tpu.http._infer_result import InferResult
from tritonclient_tpu.http._utils import (
    _get_inference_request,
    _get_inference_request_chunks,
    _get_query_string,
    _raise_if_error,
)
from tritonclient_tpu.utils import InferenceServerException, raise_error


class _CancelToken:
    """Cancellation handle shared between an InferAsyncRequest and its
    in-flight request thread.

    HTTP has no cancel verb; closing the connection IS the wire's
    cancellation signal — the server's disconnect watcher arms the
    request's ``cancel_event`` and the batcher sheds the queued work
    (``nv_inference_shed_total{reason="cancelled"}``). ``cancel()``
    therefore closes whatever connection the request currently holds; a
    cancel that lands before the connection is acquired poisons the token
    so the request aborts at attach time instead.
    """

    __slots__ = ("_lock", "_conn", "cancelled")

    def __init__(self):
        self._lock = threading.Lock()
        self._conn = None
        self.cancelled = False

    @staticmethod
    def _kill(conn):
        # shutdown() BEFORE close(): the request thread's in-flight
        # getresponse holds a makefile io-ref, so close() alone defers
        # the real close (no FIN ever reaches the server). shutdown()
        # sends the FIN immediately — the server's disconnect watcher
        # sees EOF and the blocked response read wakes with an error.
        sock = getattr(conn, "sock", None)
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        try:
            conn.close()
        except OSError:
            pass

    def attach(self, conn):
        with self._lock:
            self._conn = conn
            if self.cancelled:
                self._kill(conn)

    def detach(self):
        with self._lock:
            self._conn = None

    def cancel(self):
        with self._lock:
            self.cancelled = True
            conn = self._conn
        if conn is not None:
            self._kill(conn)


class InferAsyncRequest:
    """Handle for an in-flight async_infer (reference: http/_client.py:46-99)."""

    def __init__(self, future: Future, verbose: bool = False,
                 cancel_token: Optional[_CancelToken] = None):
        self._future = future
        self._verbose = verbose
        self._cancel_token = cancel_token

    def get_result(self, block: bool = True, timeout: Optional[float] = None) -> InferResult:
        """Wait for and return the InferResult (raises on server error)."""
        try:
            return self._future.result(timeout=timeout if block else 0)
        except futures_module.TimeoutError:
            # On 3.10 concurrent.futures.TimeoutError is NOT the builtin
            # TimeoutError; catching the futures one covers both (3.11+ alias).
            raise InferenceServerException(
                msg="failed to obtain inference response"
            ) from None

    def cancel(self) -> bool:
        """Cancel the request. Not-yet-started requests are dropped from
        the pool; an IN-FLIGHT request has its connection closed, which
        the server observes as a client disconnect and sheds the queued
        work — the cancellation actually travels to the server."""
        if self._future.cancel():
            return True
        if self._future.done():
            return False
        if self._cancel_token is not None:
            self._cancel_token.cancel()
            return True
        return False


class _ConnectionPool:
    """Bounded pool of persistent HTTP/1.1 connections to one host."""

    def __init__(self, scheme, host, port, size, connection_timeout, network_timeout, ssl_context):
        self._scheme = scheme
        self._host = host
        self._port = port
        self._size = size
        self._connection_timeout = connection_timeout
        self._network_timeout = network_timeout
        self._ssl_context = ssl_context
        self._idle = queue.LifoQueue()
        self._capacity = threading.Semaphore(size)
        self._closed = False

    def _new_connection(self):
        # connection_timeout governs the connect (incl. TLS) phase only;
        # after that the socket switches to network_timeout for I/O.
        if self._scheme == "https":
            conn = http.client.HTTPSConnection(
                self._host,
                self._port,
                timeout=self._connection_timeout,
                context=self._ssl_context,
            )
        else:
            conn = http.client.HTTPConnection(
                self._host, self._port, timeout=self._connection_timeout
            )
        conn.connect()
        conn.sock.settimeout(self._network_timeout)
        return conn

    def acquire(self):
        """Returns (connection, reused). Blocks while the pool is exhausted."""
        self._capacity.acquire()
        try:
            return self._idle.get_nowait(), True
        except queue.Empty:
            pass
        try:
            return self._new_connection(), False
        except BaseException:
            self._capacity.release()
            raise

    def release(self, conn):
        if self._closed:
            conn.close()
        else:
            self._idle.put(conn)
        self._capacity.release()

    def discard(self, conn):
        conn.close()
        self._capacity.release()

    def close(self):
        self._closed = True
        while True:
            try:
                self._idle.get_nowait().close()
            except queue.Empty:
                break


class InferenceServerClient(InferenceServerClientBase):
    """Talks to the server over HTTP/REST.

    One client maps to one connection pool; use the ``concurrency`` parameter
    to bound in-flight requests (reference: http/_client.py:119-152).
    """

    def __init__(
        self,
        url: str,
        verbose: bool = False,
        concurrency: int = 1,
        connection_timeout: float = 60.0,
        network_timeout: float = 60.0,
        max_greenlets=None,  # accepted for API parity; thread pool sizing == concurrency
        ssl: bool = False,
        ssl_options: Optional[dict] = None,
        ssl_context_factory=None,
        insecure: bool = False,
        retry_policy: Optional[RetryPolicy] = None,
        circuit_breaker: Optional[CircuitBreaker] = None,
    ):
        """``retry_policy``: opt-in :class:`~tritonclient_tpu.resilience.
        RetryPolicy` — connect/send-phase transport failures and
        retryable statuses (429/503, ``Retry-After`` honored) are
        replayed with jittered backoff under the policy's budget;
        post-send failures are replayed ONLY when the request carries an
        idempotency key (``infer(..., idempotency_key=...)``). ``None``
        (default) keeps the legacy behavior: a single replay only when a
        reused keep-alive connection failed. ``circuit_breaker``: opt-in
        per-endpoint breaker — while open, requests fail fast with
        ``BreakerOpenError`` instead of touching the server."""
        super().__init__()
        if url.startswith("http://") or url.startswith("https://"):
            raise_error("url should not include the scheme")
        scheme = "https" if ssl else "http"
        parsed = urlparse(f"{scheme}://{url}")
        self._host = parsed.hostname
        self._port = parsed.port or (443 if ssl else 80)
        self._base_path = parsed.path.rstrip("/")
        self._verbose = verbose

        ssl_context = None
        if ssl:
            if ssl_context_factory is not None:
                ssl_context = ssl_context_factory()
            else:
                ssl_context = ssl_module.create_default_context()
                options = ssl_options or {}
                if "ca_certs" in options:
                    ssl_context.load_verify_locations(options["ca_certs"])
                if "keyfile" in options and "certfile" in options:
                    ssl_context.load_cert_chain(
                        options["certfile"], options["keyfile"]
                    )
                if insecure:
                    ssl_context.check_hostname = False
                    ssl_context.verify_mode = ssl_module.CERT_NONE

        self._pool = _ConnectionPool(
            scheme,
            self._host,
            self._port,
            max(concurrency, 1),
            connection_timeout,
            network_timeout,
            ssl_context,
        )
        self._executor = ThreadPoolExecutor(max_workers=max(concurrency, 1))
        self._retry_policy = retry_policy
        self._breaker = circuit_breaker

    # -- lifecycle -----------------------------------------------------------

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass

    def close(self):
        """Close the client and all pooled connections."""
        self._executor.shutdown(wait=True)
        self._pool.close()

    # -- low-level request ---------------------------------------------------

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[bytes] = None,
        headers: Optional[Dict[str, str]] = None,
        query_params: Optional[dict] = None,
        cancel_token: Optional[_CancelToken] = None,
    ):
        headers = dict(headers) if headers else {}
        for key in headers:
            if key.lower() == "transfer-encoding":
                raise_error(
                    "Unsupported Transfer-Encoding header; the client always "
                    "sends Content-Length"
                )
        request_obj = Request(headers)
        self._call_plugin(request_obj)
        headers = request_obj.headers

        if isinstance(body, list):
            # Chunked upload: http.client iterates the list, so each tensor
            # streams to the socket in its own (<= 16 MiB) write with no
            # monolithic join. Content-Length must be explicit or
            # http.client would fall back to Transfer-Encoding: chunked.
            headers["Content-Length"] = str(sum(len(c) for c in body))

        uri = f"{self._base_path}/{path}{_get_query_string(query_params)}"
        if self._verbose:
            print(f"{method} {uri}, headers {headers}")

        policy = self._retry_policy
        idempotent = any(
            k.lower() == HEADER_IDEMPOTENCY_KEY for k in headers
        )
        retried = False
        attempt = 0
        with chaos.operation(f"http.{method} {path}"):
            while True:
                if self._breaker is not None:
                    self._breaker.check()
                if attempt and policy is not None:
                    headers[HEADER_RETRY_ATTEMPT] = str(attempt)
                phase = PHASE_CONNECT
                conn = None
                reused = False
                try:
                    chaos.fire(chaos.SITE_HTTP_CONNECT)
                    conn, reused = self._pool.acquire()
                    if cancel_token is not None:
                        cancel_token.attach(conn)
                    phase = PHASE_SEND
                    chaos.fire(chaos.SITE_HTTP_SEND)
                    conn.request(method, uri, body=body, headers=headers)
                    # Request fully written: from here a failure is
                    # post-send — the server MAY have executed it.
                    phase = PHASE_RESPONSE
                    chaos.fire(chaos.SITE_HTTP_RESPONSE)
                    response = conn.getresponse()
                    payload = response.read()
                except TimeoutError:
                    # A timed-out request must NOT be retried (infer is not
                    # idempotent and the retry would double the effective
                    # timeout).
                    if conn is not None:
                        self._pool.discard(conn)
                    if self._breaker is not None:
                        self._breaker.on_failure()
                    raise InferenceServerException(msg="timed out") from None
                except (http.client.HTTPException, OSError) as e:
                    if conn is not None:
                        self._pool.discard(conn)
                    if self._breaker is not None:
                        self._breaker.on_failure()
                    if cancel_token is not None and cancel_token.cancelled:
                        # The failure IS the cancellation (the token closed
                        # this connection); never retry cancelled work.
                        raise InferenceServerException(
                            msg="Locally cancelled by application!"
                        ) from None
                    # Legacy allowance, both modes: one replay when a REUSED
                    # keep-alive connection failed (closed while idle — the
                    # request almost certainly never reached the server).
                    if reused and not retried:
                        retried = True
                        attempt += 1
                        continue
                    if policy is not None:
                        # Policy-driven replay: pre-execution phases always
                        # eligible; post-send only with an idempotency key.
                        reason = policy.classify(
                            phase, idempotent=idempotent
                        )
                        if policy.should_retry(attempt, reason):
                            policy.sleep(attempt)
                            attempt += 1
                            continue
                    raise InferenceServerException(msg=str(e)) from None
                # Response in hand. Retryable statuses (429/503) replay
                # under the policy, honoring the server's Retry-After.
                if (
                    policy is not None
                    and response.status in policy.retryable_statuses
                    and policy.should_retry(
                        attempt,
                        policy.classify(phase, status=response.status),
                    )
                ):
                    if cancel_token is not None:
                        cancel_token.detach()
                    self._pool.release(conn)
                    policy.sleep(
                        attempt,
                        parse_retry_after(
                            response.headers.get(HEADER_RETRY_AFTER)
                        ),
                    )
                    attempt += 1
                    continue
                break
        if cancel_token is not None:
            cancel_token.detach()
        self._pool.release(conn)
        if self._breaker is not None:
            self._breaker.on_success()
        if policy is not None:
            policy.note_success()
        if self._verbose:
            print(response.status, response.headers)
        return response.status, response.headers, payload

    def _get(self, path, headers=None, query_params=None):
        return self._request("GET", path, headers=headers, query_params=query_params)

    def _post(self, path, body=b"", headers=None, query_params=None,
              cancel_token=None):
        return self._request("POST", path, body=body, headers=headers,
                             query_params=query_params,
                             cancel_token=cancel_token)

    # -- health --------------------------------------------------------------

    def is_server_live(self, headers=None, query_params=None) -> bool:
        status, _, _ = self._get(EP_HEALTH_LIVE, headers, query_params)
        return status == 200

    def is_server_ready(self, headers=None, query_params=None) -> bool:
        status, _, _ = self._get(EP_HEALTH_READY, headers, query_params)
        return status == 200

    def is_model_ready(self, model_name, model_version="", headers=None, query_params=None) -> bool:
        status, _, _ = self._get(
            model_ready_path(model_name, model_version), headers, query_params
        )
        return status == 200

    # -- metadata / config ---------------------------------------------------

    def get_server_metadata(self, headers=None, query_params=None) -> dict:
        status, _, body = self._get(EP_SERVER_METADATA, headers, query_params)
        _raise_if_error(status, body)
        return json.loads(body)

    def get_model_metadata(self, model_name, model_version="", headers=None, query_params=None) -> dict:
        status, _, body = self._get(
            model_path(model_name, model_version), headers, query_params
        )
        _raise_if_error(status, body)
        return json.loads(body)

    def get_model_config(self, model_name, model_version="", headers=None, query_params=None) -> dict:
        status, _, body = self._get(
            model_config_path(model_name, model_version), headers, query_params
        )
        _raise_if_error(status, body)
        return json.loads(body)

    # -- repository ----------------------------------------------------------

    def get_model_repository_index(self, headers=None, query_params=None) -> list:
        status, _, body = self._post(EP_REPOSITORY_INDEX, b"{}", headers, query_params)
        _raise_if_error(status, body)
        return json.loads(body)

    def load_model(self, model_name, headers=None, query_params=None, config=None, files=None):
        payload = {}
        if config is not None or files is not None:
            parameters = {}
            if config is not None:
                parameters["config"] = config
            if files is not None:
                import base64 as b64

                for path, content in files.items():
                    parameters[path] = b64.b64encode(content).decode()
            payload["parameters"] = parameters
        status, _, body = self._post(
            repository_load_path(model_name),
            json.dumps(payload).encode(),
            headers,
            query_params,
        )
        _raise_if_error(status, body)
        if self._verbose:
            print(f"Loaded model '{model_name}'")

    def unload_model(self, model_name, headers=None, query_params=None, unload_dependents=False):
        payload = {"parameters": {KEY_UNLOAD_DEPENDENTS: unload_dependents}}
        status, _, body = self._post(
            repository_unload_path(model_name),
            json.dumps(payload).encode(),
            headers,
            query_params,
        )
        _raise_if_error(status, body)
        if self._verbose:
            print(f"Unloaded model '{model_name}'")

    # -- statistics ----------------------------------------------------------

    def get_inference_statistics(self, model_name="", model_version="", headers=None, query_params=None) -> dict:
        path = model_stats_path(model_name, model_version)
        status, _, body = self._get(path, headers, query_params)
        _raise_if_error(status, body)
        return json.loads(body)

    # -- trace / log settings ------------------------------------------------

    def update_trace_settings(self, model_name="", settings=None, headers=None, query_params=None) -> dict:
        path = trace_setting_path(model_name)
        status, _, body = self._post(
            path, json.dumps(settings or {}).encode(), headers, query_params
        )
        _raise_if_error(status, body)
        return json.loads(body)

    def get_trace_settings(self, model_name="", headers=None, query_params=None) -> dict:
        path = trace_setting_path(model_name)
        status, _, body = self._get(path, headers, query_params)
        _raise_if_error(status, body)
        return json.loads(body)

    def update_log_settings(self, settings: dict, headers=None, query_params=None) -> dict:
        status, _, body = self._post(
            EP_LOGGING, json.dumps(settings or {}).encode(), headers, query_params
        )
        _raise_if_error(status, body)
        return json.loads(body)

    def get_log_settings(self, headers=None, query_params=None) -> dict:
        status, _, body = self._get(EP_LOGGING, headers, query_params)
        _raise_if_error(status, body)
        return json.loads(body)

    def get_flight_recorder(self, format=None, headers=None,
                            query_params=None) -> dict:
        """Dump the server's tail-based flight recorder (slowest-K span
        trees per window plus every error/deadline miss). ``format=
        "perfetto"`` returns Chrome trace-event JSON instead of the
        structured dump."""
        params = dict(query_params or {})
        if format:
            params["format"] = format
        status, _, body = self._get(
            EP_FLIGHT_RECORDER, headers, params or None
        )
        _raise_if_error(status, body)
        return json.loads(body)

    # -- shared memory admin -------------------------------------------------

    def get_system_shared_memory_status(self, region_name="", headers=None, query_params=None) -> list:
        path = shm_admin_path("system", "status", region_name)
        status, _, body = self._get(path, headers, query_params)
        _raise_if_error(status, body)
        return json.loads(body)

    def register_system_shared_memory(self, name, key, byte_size, offset=0, headers=None, query_params=None):
        payload = {"key": key, "offset": offset, "byte_size": byte_size}
        status, _, body = self._post(
            shm_admin_path("system", "register", name),
            json.dumps(payload).encode(),
            headers,
            query_params,
        )
        _raise_if_error(status, body)
        if self._verbose:
            print(f"Registered system shared memory with name '{name}'")

    def unregister_system_shared_memory(self, name="", headers=None, query_params=None):
        path = shm_admin_path("system", "unregister", name)
        status, _, body = self._post(path, b"", headers, query_params)
        _raise_if_error(status, body)

    def get_cuda_shared_memory_status(self, region_name="", headers=None, query_params=None) -> list:
        path = shm_admin_path("cuda", "status", region_name)
        status, _, body = self._get(path, headers, query_params)
        _raise_if_error(status, body)
        return json.loads(body)

    def register_cuda_shared_memory(self, name, raw_handle, device_id, byte_size, headers=None, query_params=None):
        import base64 as b64

        payload = {
            "raw_handle": {"b64": b64.b64encode(raw_handle).decode()},
            "device_id": device_id,
            "byte_size": byte_size,
        }
        status, _, body = self._post(
            shm_admin_path("cuda", "register", name),
            json.dumps(payload).encode(),
            headers,
            query_params,
        )
        _raise_if_error(status, body)

    def unregister_cuda_shared_memory(self, name="", headers=None, query_params=None):
        path = shm_admin_path("cuda", "unregister", name)
        status, _, body = self._post(path, b"", headers, query_params)
        _raise_if_error(status, body)

    def get_tpu_shared_memory_status(self, region_name="", headers=None, query_params=None) -> list:
        """Status of registered TPU device-buffer regions."""
        path = shm_admin_path("tpu", "status", region_name)
        status, _, body = self._get(path, headers, query_params)
        _raise_if_error(status, body)
        return json.loads(body)

    def register_tpu_shared_memory(self, name, raw_handle, device_id, byte_size, headers=None, query_params=None):
        """Register a TPU region by raw co-location handle (base64 on the wire,
        mirroring the CUDA register path http/_client.py:1129-1175)."""
        import base64 as b64

        payload = {
            "raw_handle": {"b64": b64.b64encode(raw_handle).decode()},
            "device_id": device_id,
            "byte_size": byte_size,
        }
        status, _, body = self._post(
            shm_admin_path("tpu", "register", name),
            json.dumps(payload).encode(),
            headers,
            query_params,
        )
        _raise_if_error(status, body)

    def unregister_tpu_shared_memory(self, name="", headers=None, query_params=None):
        path = shm_admin_path("tpu", "unregister", name)
        status, _, body = self._post(path, b"", headers, query_params)
        _raise_if_error(status, body)

    # -- inference -----------------------------------------------------------

    @staticmethod
    def generate_request_body(
        inputs,
        request_id="",
        outputs=None,
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        parameters=None,
    ):
        """Build an infer POST body without sending it
        (reference: http/_client.py:1219-1302). Returns (body, json_size)."""
        return _get_inference_request(
            inputs=inputs,
            request_id=request_id,
            outputs=outputs,
            sequence_id=sequence_id,
            sequence_start=sequence_start,
            sequence_end=sequence_end,
            priority=priority,
            timeout=timeout,
            custom_parameters=parameters,
        )

    @staticmethod
    def parse_response_body(response_body, verbose=False, header_length=None, content_encoding=None):
        """Inverse of generate_request_body for responses
        (reference: http/_client.py:1304-1329)."""
        return InferResult.from_response_body(
            response_body, verbose, header_length, content_encoding
        )

    def _build_infer(
        self,
        model_name,
        inputs,
        model_version,
        outputs,
        request_id,
        sequence_id,
        sequence_start,
        sequence_end,
        priority,
        timeout,
        request_compression_algorithm,
        response_compression_algorithm,
        parameters,
    ):
        request_body, json_size, _total = _get_inference_request_chunks(
            inputs=inputs,
            request_id=request_id,
            outputs=outputs,
            sequence_id=sequence_id,
            sequence_start=sequence_start,
            sequence_end=sequence_end,
            priority=priority,
            timeout=timeout,
            custom_parameters=parameters,
        )
        headers = {}
        if request_compression_algorithm == "gzip":
            headers["Content-Encoding"] = "gzip"
            request_body = gzip.compress(b"".join(request_body))
        elif request_compression_algorithm == "deflate":
            headers["Content-Encoding"] = "deflate"
            request_body = zlib.compress(b"".join(request_body))
        if response_compression_algorithm == "gzip":
            headers["Accept-Encoding"] = "gzip"
        elif response_compression_algorithm == "deflate":
            headers["Accept-Encoding"] = "deflate"
        if json_size is not None:
            headers["Inference-Header-Content-Length"] = str(json_size)

        path = model_infer_path(model_name, model_version)
        return path, request_body, headers

    def infer(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        headers=None,
        query_params=None,
        request_compression_algorithm=None,
        response_compression_algorithm=None,
        parameters=None,
        timers=None,
        traceparent=None,
        cancel_token=None,
        idempotency_key=None,
    ) -> InferResult:
        """Synchronous inference (reference: http/_client.py:1331-1484).

        ``idempotency_key``: optional caller-chosen token sent as the
        ``idempotency-key`` header. Its presence asserts the request may
        safely execute more than once, which authorizes this client's
        RetryPolicy (and any retrying proxy such as the fleet router) to
        replay it after a post-send failure and to hedge it.

        ``timers``: optional ``perf_analyzer._stats.RequestTimers`` — when
        given, the client stamps the six request-phase timestamps into it
        (send = request marshalling, recv = response parse) and attaches it
        to the returned result as ``result.timers``. A non-empty
        ``request_id`` is also propagated as the ``triton-request-id``
        header so server-side trace records can be joined to client timing.
        ``traceparent``: optional W3C Trace Context header value injected
        as the ``traceparent`` header (an explicit
        ``headers={"traceparent": ...}`` wins) so server span records
        continue the caller's trace.
        """
        if timers is not None:
            timers.capture("request_start")
            timers.capture("send_start")
        path, request_body, extra_headers = self._build_infer(
            model_name, inputs, model_version, outputs, request_id,
            sequence_id, sequence_start, sequence_end, priority, timeout,
            request_compression_algorithm, response_compression_algorithm,
            parameters,
        )
        all_headers = dict(headers) if headers else {}
        all_headers.update(extra_headers)
        if request_id:
            all_headers.setdefault("triton-request-id", request_id)
        if traceparent:
            all_headers.setdefault("traceparent", traceparent)
        if idempotency_key:
            all_headers.setdefault(HEADER_IDEMPOTENCY_KEY, idempotency_key)
        if timers is not None:
            timers.capture("send_end")
        status, resp_headers, body = self._post(
            path, request_body, all_headers, query_params,
            cancel_token=cancel_token,
        )
        _raise_if_error(status, body)
        if timers is not None:
            timers.capture("recv_start")
        header_length = resp_headers.get("Inference-Header-Content-Length")
        result = InferResult(
            body,
            int(header_length) if header_length is not None else None,
            resp_headers.get("Content-Encoding"),
        )
        if timers is not None:
            timers.capture("recv_end")
            timers.capture("request_end")
            result.timers = timers
        return result

    def async_infer(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        headers=None,
        query_params=None,
        request_compression_algorithm=None,
        response_compression_algorithm=None,
        parameters=None,
    ) -> InferAsyncRequest:
        """Submit inference on the bounded pool; returns an InferAsyncRequest
        whose get_result() blocks (reference: http/_client.py:1486-1659).
        ``.cancel()`` on the handle travels to the server: an in-flight
        request's connection is closed, which the server's disconnect
        watcher converts into a shed of the queued work."""
        cancel_token = _CancelToken()
        future = self._executor.submit(
            self.infer,
            model_name,
            inputs,
            model_version,
            outputs,
            request_id,
            sequence_id,
            sequence_start,
            sequence_end,
            priority,
            timeout,
            headers,
            query_params,
            request_compression_algorithm,
            response_compression_algorithm,
            parameters,
            None,  # timers
            None,  # traceparent
            cancel_token,
        )
        return InferAsyncRequest(future, self._verbose, cancel_token)

"""InferRequestedOutput for the HTTP client.

Reference parity: tritonclient/http/_requested_output.py.
"""

from tritonclient_tpu.protocol._literals import (
    KEY_BINARY_DATA,
    KEY_CLASSIFICATION,
    KEY_SHM_BYTE_SIZE,
    KEY_SHM_OFFSET,
    KEY_SHM_REGION,
)


class InferRequestedOutput:
    def __init__(self, name: str, binary_data: bool = True, class_count: int = 0):
        self._name = name
        self._parameters = {}
        if class_count != 0:
            self._parameters[KEY_CLASSIFICATION] = class_count
        self._binary = binary_data
        self._parameters[KEY_BINARY_DATA] = binary_data

    def name(self) -> str:
        return self._name

    def set_shared_memory(self, region_name: str, byte_size: int, offset: int = 0):
        if KEY_CLASSIFICATION in self._parameters:
            raise ValueError("shared memory can't be set on a classification output")
        self._parameters.pop(KEY_BINARY_DATA, None)
        self._parameters[KEY_SHM_REGION] = region_name
        self._parameters[KEY_SHM_BYTE_SIZE] = byte_size
        if offset != 0:
            self._parameters[KEY_SHM_OFFSET] = offset
        return self

    def unset_shared_memory(self):
        self._parameters.pop(KEY_SHM_REGION, None)
        self._parameters.pop(KEY_SHM_BYTE_SIZE, None)
        self._parameters.pop(KEY_SHM_OFFSET, None)
        self._parameters[KEY_BINARY_DATA] = self._binary
        return self

    def _get_tensor(self) -> dict:
        return {"name": self._name, "parameters": dict(self._parameters)}

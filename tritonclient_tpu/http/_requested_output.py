"""InferRequestedOutput for the HTTP client.

Reference parity: tritonclient/http/_requested_output.py.
"""


class InferRequestedOutput:
    def __init__(self, name: str, binary_data: bool = True, class_count: int = 0):
        self._name = name
        self._parameters = {}
        if class_count != 0:
            self._parameters["classification"] = class_count
        self._binary = binary_data
        self._parameters["binary_data"] = binary_data

    def name(self) -> str:
        return self._name

    def set_shared_memory(self, region_name: str, byte_size: int, offset: int = 0):
        if "classification" in self._parameters:
            raise ValueError("shared memory can't be set on a classification output")
        self._parameters.pop("binary_data", None)
        self._parameters["shared_memory_region"] = region_name
        self._parameters["shared_memory_byte_size"] = byte_size
        if offset != 0:
            self._parameters["shared_memory_offset"] = offset
        return self

    def unset_shared_memory(self):
        self._parameters.pop("shared_memory_region", None)
        self._parameters.pop("shared_memory_byte_size", None)
        self._parameters.pop("shared_memory_offset", None)
        self._parameters["binary_data"] = self._binary
        return self

    def _get_tensor(self) -> dict:
        return {"name": self._name, "parameters": dict(self._parameters)}

"""HTTP request-body builder + error translation.

Reference parity: tritonclient/http/_utils.py — JSON header with appended
binary blobs; returns (body, json_size) where json_size None means pure JSON
(:85-150); requesting no outputs sets binary_data_output=true (:114-117).
"""

import json
from typing import List, Optional, Tuple
from urllib.parse import quote_plus

from tritonclient_tpu.utils import InferenceServerException, raise_error
from tritonclient_tpu.protocol._literals import (
    KEY_BINARY_DATA_OUTPUT,
    KEY_SEQUENCE_END,
    KEY_SEQUENCE_ID,
    KEY_SEQUENCE_START,
    KEY_TIMEOUT,
    RESERVED_REQUEST_PARAMS,
    STATUS_INVALID,
)

# Upload buffer granularity for chunked request bodies — reference parity
# with the C++ client's 16 MiB curl buffers (http_client.cc:2172-2175).
MAX_UPLOAD_CHUNK_BYTES = 16 * 1024 * 1024


def _get_error(status: int, body: bytes) -> Optional[InferenceServerException]:
    """Build an exception from a non-2xx response (JSON or plain-text body)."""
    if status >= STATUS_INVALID:
        try:
            msg = json.loads(body.decode("utf-8", errors="replace")).get("error", "")
        except (ValueError, AttributeError):
            msg = body.decode("utf-8", errors="replace")
        return InferenceServerException(msg=msg or f"HTTP {status}", status=str(status))
    return None


def _raise_if_error(status: int, body: bytes):
    error = _get_error(status, body)
    if error is not None:
        raise error


def _get_query_string(query_params: Optional[dict]) -> str:
    if not query_params:
        return ""
    parts = []
    for key, value in query_params.items():
        if isinstance(value, (list, tuple)):
            parts.extend(f"{quote_plus(str(key))}={quote_plus(str(v))}" for v in value)
        else:
            parts.append(f"{quote_plus(str(key))}={quote_plus(str(value))}")
    return "?" + "&".join(parts)


def _get_inference_request(
    inputs,
    request_id,
    outputs,
    sequence_id,
    sequence_start,
    sequence_end,
    priority,
    timeout,
    custom_parameters=None,
) -> Tuple[bytes, Optional[int]]:
    """Build the infer POST body; (body, json_size) with json_size=None when
    the body is pure JSON (no appended binary blobs)."""
    chunks, json_size, _total = _get_inference_request_chunks(
        inputs=inputs,
        request_id=request_id,
        outputs=outputs,
        sequence_id=sequence_id,
        sequence_start=sequence_start,
        sequence_end=sequence_end,
        priority=priority,
        timeout=timeout,
        custom_parameters=custom_parameters,
    )
    return b"".join(chunks), json_size


def _get_inference_request_chunks(
    inputs,
    request_id,
    outputs,
    sequence_id,
    sequence_start,
    sequence_end,
    priority,
    timeout,
    custom_parameters=None,
) -> Tuple[List[bytes], Optional[int], int]:
    """Chunked variant of _get_inference_request: no monolithic body copy.

    Returns (chunks, json_size, total_bytes) where chunks is the JSON header
    followed by each input's binary blob, each chunk no larger than
    MAX_UPLOAD_CHUNK_BYTES — the GetNext/16 MiB upload pattern of the
    reference's C++ client (common.h:340-353, http_client.cc:2172-2175)
    applied to the Python path: large tensors stream to the socket in
    bounded writes instead of being joined into one giant buffer.
    """
    infer_request = {}
    parameters = {}
    if request_id:
        infer_request["id"] = request_id
    if sequence_id:
        parameters[KEY_SEQUENCE_ID] = sequence_id
        parameters[KEY_SEQUENCE_START] = sequence_start
        parameters[KEY_SEQUENCE_END] = sequence_end
    if priority:
        parameters["priority"] = priority
    if timeout is not None:
        parameters[KEY_TIMEOUT] = timeout

    infer_request["inputs"] = [i._get_tensor() for i in inputs]
    if outputs:
        infer_request["outputs"] = [o._get_tensor() for o in outputs]
    else:
        parameters[KEY_BINARY_DATA_OUTPUT] = True

    for key, value in (custom_parameters or {}).items():
        if key in RESERVED_REQUEST_PARAMS:
            raise_error(
                f"Parameter {key} is a reserved parameter and cannot be specified."
            )
        parameters[key] = value
    if parameters:
        infer_request["parameters"] = parameters

    request_json = json.dumps(infer_request).encode()
    chunks: List[bytes] = [request_json]
    total = len(request_json)
    has_binary = False
    for infer_input in inputs:
        raw = infer_input._get_binary_data()
        if raw is None:
            continue
        has_binary = True
        total += len(raw)
        view = memoryview(raw)
        for off in range(0, len(view), MAX_UPLOAD_CHUNK_BYTES):
            chunks.append(view[off : off + MAX_UPLOAD_CHUNK_BYTES])
    if not has_binary:
        return chunks, None, total
    return chunks, len(request_json), total

"""HTTP request-body builder + error translation.

Reference parity: tritonclient/http/_utils.py — JSON header with appended
binary blobs; returns (body, json_size) where json_size None means pure JSON
(:85-150); requesting no outputs sets binary_data_output=true (:114-117).
"""

import json
from typing import Optional, Tuple
from urllib.parse import quote_plus

from tritonclient_tpu.utils import InferenceServerException, raise_error


def _get_error(status: int, body: bytes) -> Optional[InferenceServerException]:
    """Build an exception from a non-2xx response (JSON or plain-text body)."""
    if status >= 400:
        try:
            msg = json.loads(body.decode("utf-8", errors="replace")).get("error", "")
        except (ValueError, AttributeError):
            msg = body.decode("utf-8", errors="replace")
        return InferenceServerException(msg=msg or f"HTTP {status}", status=str(status))
    return None


def _raise_if_error(status: int, body: bytes):
    error = _get_error(status, body)
    if error is not None:
        raise error


def _get_query_string(query_params: Optional[dict]) -> str:
    if not query_params:
        return ""
    parts = []
    for key, value in query_params.items():
        if isinstance(value, (list, tuple)):
            parts.extend(f"{quote_plus(str(key))}={quote_plus(str(v))}" for v in value)
        else:
            parts.append(f"{quote_plus(str(key))}={quote_plus(str(value))}")
    return "?" + "&".join(parts)


def _get_inference_request(
    inputs,
    request_id,
    outputs,
    sequence_id,
    sequence_start,
    sequence_end,
    priority,
    timeout,
    custom_parameters=None,
) -> Tuple[bytes, Optional[int]]:
    """Build the infer POST body; (body, json_size) with json_size=None when
    the body is pure JSON (no appended binary blobs)."""
    infer_request = {}
    parameters = {}
    if request_id:
        infer_request["id"] = request_id
    if sequence_id:
        parameters["sequence_id"] = sequence_id
        parameters["sequence_start"] = sequence_start
        parameters["sequence_end"] = sequence_end
    if priority:
        parameters["priority"] = priority
    if timeout is not None:
        parameters["timeout"] = timeout

    infer_request["inputs"] = [i._get_tensor() for i in inputs]
    if outputs:
        infer_request["outputs"] = [o._get_tensor() for o in outputs]
    else:
        # Default to binary data for all outputs when none are requested.
        parameters["binary_data_output"] = True

    for key, value in (custom_parameters or {}).items():
        if key in ("sequence_id", "sequence_start", "sequence_end", "priority", "binary_data_output"):
            raise_error(
                f"Parameter {key} is a reserved parameter and cannot be specified."
            )
        parameters[key] = value
    if parameters:
        infer_request["parameters"] = parameters

    request_json = json.dumps(infer_request).encode()
    binary_blobs = []
    for infer_input in inputs:
        raw = infer_input._get_binary_data()
        if raw is not None:
            binary_blobs.append(raw)
    if not binary_blobs:
        return request_json, None
    return request_json + b"".join(binary_blobs), len(request_json)

"""Auth plugin re-exports for the HTTP flavor (reference: http/auth/__init__.py)."""

from tritonclient_tpu._auth import BasicAuth  # noqa: F401

"""HTTP client package (reference parity: tritonclient/http/__init__.py)."""

from tritonclient_tpu.http._client import (  # noqa: F401
    InferAsyncRequest,
    InferenceServerClient,
)
from tritonclient_tpu.http._infer_input import InferInput  # noqa: F401
from tritonclient_tpu.http._infer_result import InferResult  # noqa: F401
from tritonclient_tpu.http._requested_output import InferRequestedOutput  # noqa: F401
from tritonclient_tpu.utils import InferenceServerException  # noqa: F401

"""InferInput for the HTTP client (JSON-dict tensor descriptor).

Reference parity: tritonclient/http/_infer_input.py:38-272 — per-input
``binary_data`` toggle selects JSON inline data vs an appended binary blob with
a ``binary_data_size`` parameter.
"""

from typing import List

import numpy as np

from tritonclient_tpu.protocol._literals import (
    KEY_BINARY_DATA_SIZE,
    KEY_SHM_BYTE_SIZE,
    KEY_SHM_OFFSET,
    KEY_SHM_REGION,
)
from tritonclient_tpu.utils import (
    np_to_triton_dtype,
    raise_error,
    serialize_bf16_tensor,
    serialize_byte_tensor,
)


class InferInput:
    def __init__(self, name: str, shape: List[int], datatype: str):
        self._name = name
        self._shape = list(shape)
        self._datatype = datatype
        self._parameters = {}
        self._data = None
        self._raw_data = None

    def name(self) -> str:
        return self._name

    def datatype(self) -> str:
        return self._datatype

    def shape(self) -> List[int]:
        return list(self._shape)

    def set_shape(self, shape: List[int]):
        self._shape = list(shape)
        return self

    def set_data_from_numpy(self, input_tensor, binary_data: bool = True):
        """Attach tensor data, as an appended binary blob (default) or inline
        JSON (binary_data=False)."""
        if not isinstance(input_tensor, np.ndarray):
            input_tensor = np.asarray(input_tensor)
        dtype = np_to_triton_dtype(input_tensor.dtype)
        if self._datatype == "BF16" and dtype == "FP32":
            pass
        elif dtype != self._datatype:
            raise_error(
                f"got unexpected datatype {dtype} from numpy array, "
                f"expected {self._datatype}"
            )
        valid_shape = len(self._shape) == input_tensor.ndim and all(
            int(a) == b for a, b in zip(self._shape, input_tensor.shape)
        )
        if not valid_shape:
            raise_error(
                f"got unexpected numpy array shape [{', '.join(str(s) for s in input_tensor.shape)}], "
                f"expected [{', '.join(str(s) for s in self._shape)}]"
            )

        self._parameters.pop(KEY_SHM_REGION, None)
        self._parameters.pop(KEY_SHM_BYTE_SIZE, None)
        self._parameters.pop(KEY_SHM_OFFSET, None)

        if not binary_data:
            if self._datatype == "BF16":
                raise_error("BF16 inputs must use binary_data=True (no JSON encoding)")
            self._parameters.pop(KEY_BINARY_DATA_SIZE, None)
            self._raw_data = None
            if self._datatype == "BYTES":
                self._data = []
                try:
                    for obj in np.nditer(input_tensor, flags=["refs_ok"], order="C"):
                        item = obj.item()
                        if isinstance(item, bytes):
                            self._data.append(item.decode("utf-8"))
                        else:
                            self._data.append(str(item))
                except UnicodeDecodeError:
                    raise_error(
                        f'Failed to encode "{item}". Please use binary_data=True '
                        "for BYTES inputs that are not valid UTF-8."
                    )
            else:
                self._data = [i.item() for i in input_tensor.flatten()]
        else:
            self._data = None
            if self._datatype == "BYTES":
                serialized = serialize_byte_tensor(input_tensor)
                self._raw_data = serialized.item() if serialized.size > 0 else b""
            elif self._datatype == "BF16":
                serialized = serialize_bf16_tensor(input_tensor)
                self._raw_data = serialized.item() if serialized.size > 0 else b""
            else:
                self._raw_data = np.ascontiguousarray(input_tensor).tobytes()
            self._parameters[KEY_BINARY_DATA_SIZE] = len(self._raw_data)
        return self

    def set_shared_memory(self, region_name: str, byte_size: int, offset: int = 0):
        """Point this input at a registered shared-memory region."""
        self._data = None
        self._raw_data = None
        self._parameters.pop(KEY_BINARY_DATA_SIZE, None)
        self._parameters[KEY_SHM_REGION] = region_name
        self._parameters[KEY_SHM_BYTE_SIZE] = byte_size
        if offset != 0:
            self._parameters[KEY_SHM_OFFSET] = offset
        return self

    def _get_tensor(self) -> dict:
        tensor = {
            "name": self._name,
            "shape": self._shape,
            "datatype": self._datatype,
        }
        if self._parameters:
            tensor["parameters"] = self._parameters
        if self._data is not None:
            tensor["data"] = self._data
        return tensor

    def _get_binary_data(self):
        return self._raw_data

"""InferResult for the HTTP client: splits JSON header from binary buffers.

Reference parity: tritonclient/http/_infer_result.py:41-242 — the response body
is JSON up to ``Inference-Header-Content-Length``; outputs carrying
``binary_data_size`` map name → offset in the trailing binary buffer.
"""

import gzip
import json
import zlib
from typing import List, Optional
from tritonclient_tpu.protocol._literals import (
    KEY_BINARY_DATA_SIZE,
    KEY_SHM_REGION,
)

import numpy as np

from tritonclient_tpu.utils import (
    deserialize_bf16_tensor,
    deserialize_bytes_tensor,
    raise_error,
    triton_to_np_dtype,
)


class InferResult:
    def __init__(self, response_body: bytes, header_length: Optional[int], content_encoding: Optional[str] = None):
        if content_encoding == "gzip":
            response_body = gzip.decompress(response_body)
        elif content_encoding == "deflate":
            response_body = zlib.decompress(response_body)

        if header_length is None:
            content = response_body
            self._buffer = b""
        else:
            content = response_body[:header_length]
            self._buffer = response_body[header_length:]
        self._result = json.loads(content)

        # Map output name → (offset, size) in the binary buffer.
        self._output_name_to_buffer_map = {}
        offset = 0
        for output in self._result.get("outputs", []):
            params = output.get("parameters", {})
            if KEY_BINARY_DATA_SIZE in params:
                size = int(params[KEY_BINARY_DATA_SIZE])
                self._output_name_to_buffer_map[output["name"]] = (offset, size)
                offset += size

    @classmethod
    def from_response_body(
        cls,
        response_body: bytes,
        verbose: bool = False,
        header_length: Optional[int] = None,
        content_encoding: Optional[str] = None,
    ) -> "InferResult":
        """Build an InferResult directly from a response body (for use with
        generate_request_body/parse_response_body round-trips)."""
        return cls(response_body, header_length, content_encoding)

    def _get_output(self, name: str) -> Optional[dict]:
        for output in self._result.get("outputs", []):
            if output["name"] == name:
                return output
        return None

    def as_numpy(self, name: str, bf16_native: bool = False) -> Optional[np.ndarray]:
        output = self._get_output(name)
        if output is None:
            return None
        if KEY_SHM_REGION in output.get("parameters", {}):
            # Tensor bytes live in the registered region, not the response;
            # the caller reads them via shared_memory.get_contents_as_numpy
            # (same contract as the gRPC InferResult).
            return None
        datatype = output["datatype"]
        shape = list(output["shape"])
        if name in self._output_name_to_buffer_map:
            offset, size = self._output_name_to_buffer_map[name]
            raw = self._buffer[offset : offset + size]
            if datatype == "BYTES":
                return deserialize_bytes_tensor(raw).reshape(shape)
            if datatype == "BF16":
                if bf16_native:
                    import ml_dtypes

                    return np.frombuffer(raw, dtype=ml_dtypes.bfloat16).reshape(shape)
                return deserialize_bf16_tensor(raw).reshape(shape)
            return np.frombuffer(raw, dtype=triton_to_np_dtype(datatype)).reshape(shape)
        data = output.get("data")
        if data is None:
            return None
        if datatype == "BYTES":
            arr = np.array(
                [x.encode() if isinstance(x, str) else bytes(x) for x in data],
                dtype=np.object_,
            )
            return arr.reshape(shape)
        if datatype == "BF16":
            raise_error("BF16 outputs are only supported as binary data")
        return np.array(data, dtype=triton_to_np_dtype(datatype)).reshape(shape)

    def get_output(self, name: str):
        """The JSON dict of the named output (None if absent)."""
        return self._get_output(name)

    def get_response(self) -> dict:
        return self._result

    def output_names(self) -> List[str]:
        return [o["name"] for o in self._result.get("outputs", [])]

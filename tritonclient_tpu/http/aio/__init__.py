"""asyncio HTTP client over aiohttp.

Reference parity: tritonclient/http/aio/__init__.py:92-775 — asyncio mirror of
the sync REST client (auto_decompress disabled so compressed responses flow to
InferResult intact, TCPConnector connection limit = ``conn_limit``). HTTP has
no streaming in the v2 protocol.
"""

import asyncio
import base64
import gzip
import json
import zlib
from typing import Optional

import aiohttp

from tritonclient_tpu import sanitize
from tritonclient_tpu.resilience import (
    PHASE_CONNECT,
    PHASE_RESPONSE,
    CircuitBreaker,
    RetryPolicy,
    parse_retry_after,
)
from tritonclient_tpu.protocol._literals import (
    EP_HEALTH_LIVE,
    HEADER_IDEMPOTENCY_KEY,
    HEADER_RETRY_AFTER,
    HEADER_RETRY_ATTEMPT,
    EP_HEALTH_READY,
    EP_LOGGING,
    EP_REPOSITORY_INDEX,
    EP_SERVER_METADATA,
    KEY_UNLOAD_DEPENDENTS,
    model_config_path,
    model_infer_path,
    model_path,
    model_ready_path,
    model_stats_path,
    repository_load_path,
    repository_unload_path,
    shm_admin_path,
    trace_setting_path,
)
from tritonclient_tpu._client import InferenceServerClientBase
from tritonclient_tpu._request import Request
from tritonclient_tpu.http._infer_input import InferInput  # noqa: F401
from tritonclient_tpu.http._infer_result import InferResult
from tritonclient_tpu.http._requested_output import InferRequestedOutput  # noqa: F401
from tritonclient_tpu.http._utils import (
    _get_inference_request,
    _get_query_string,
    _raise_if_error,
)
from tritonclient_tpu.utils import InferenceServerException, raise_error  # noqa: F401


class InferenceServerClient(InferenceServerClientBase):
    """asyncio REST client; all methods are coroutines."""

    def __init__(
        self,
        url: str,
        verbose: bool = False,
        conn_limit: int = 100,
        conn_timeout: float = 60.0,
        ssl: bool = False,
        ssl_context=None,
        retry_policy: Optional[RetryPolicy] = None,
        circuit_breaker: Optional[CircuitBreaker] = None,
    ):
        """``retry_policy``/``circuit_breaker``: same opt-in resilience
        contract as the sync client — connect-phase failures and
        retryable statuses (429/503, ``Retry-After`` honored) replay
        with ``asyncio.sleep`` backoff; a post-connect failure replays
        ONLY when the request carries ``idempotency_key``. Applied on
        the ``infer`` hot path."""
        super().__init__()
        if url.startswith("http://") or url.startswith("https://"):
            raise_error("url should not include the scheme")
        scheme = "https" if ssl else "http"
        self._url = f"{scheme}://{url}"
        self._verbose = verbose
        self._session = aiohttp.ClientSession(
            connector=aiohttp.TCPConnector(
                limit=conn_limit, ssl=ssl_context if ssl else False
            ),
            timeout=aiohttp.ClientTimeout(total=conn_timeout),
            auto_decompress=False,
        )
        self._retry_policy = retry_policy
        self._breaker = circuit_breaker
        # tpusan: opt the owning loop into event-loop-blocking accounting
        # (no-op unless the sanitizer is active).
        sanitize.note_event_loop()

    async def __aenter__(self):
        return self

    async def __aexit__(self, *exc):
        await self.close()

    async def close(self):
        await self._session.close()

    # -- low level -----------------------------------------------------------

    def _prep_headers(self, headers):
        headers = dict(headers) if headers else {}
        request = Request(headers)
        self._call_plugin(request)
        return request.headers

    async def _get(self, path, headers=None, query_params=None):
        url = f"{self._url}/{path}{_get_query_string(query_params)}"
        if self._verbose:
            print("GET", url)
        async with self._session.get(url, headers=self._prep_headers(headers)) as resp:
            return resp.status, resp.headers, await resp.read()

    async def _post(self, path, body=b"", headers=None, query_params=None,
                    timeout_s: Optional[float] = None):
        url = f"{self._url}/{path}{_get_query_string(query_params)}"
        if self._verbose:
            print("POST", url)
        kwargs = {}
        if timeout_s is not None:
            # Per-request override of the session-wide conn_timeout (the
            # KServe budget as a REAL client deadline, not just a server
            # annotation).
            kwargs["timeout"] = aiohttp.ClientTimeout(total=timeout_s)
        async with self._session.post(
            url, data=body, headers=self._prep_headers(headers), **kwargs
        ) as resp:
            return resp.status, resp.headers, await resp.read()

    @staticmethod
    def _maybe_decompress(headers, body: bytes) -> bytes:
        encoding = headers.get("Content-Encoding", "")
        if encoding == "gzip":
            return gzip.decompress(body)
        if encoding == "deflate":
            return zlib.decompress(body)
        return body

    # -- health --------------------------------------------------------------

    async def is_server_live(self, headers=None, query_params=None) -> bool:
        status, _, _ = await self._get(EP_HEALTH_LIVE, headers, query_params)
        return status == 200

    async def is_server_ready(self, headers=None, query_params=None) -> bool:
        status, _, _ = await self._get(EP_HEALTH_READY, headers, query_params)
        return status == 200

    async def is_model_ready(self, model_name, model_version="", headers=None, query_params=None) -> bool:
        status, _, _ = await self._get(
            model_ready_path(model_name, model_version), headers, query_params
        )
        return status == 200

    # -- metadata / admin ----------------------------------------------------

    async def _get_json(self, path, headers, query_params):
        status, resp_headers, body = await self._get(path, headers, query_params)
        body = self._maybe_decompress(resp_headers, body)
        _raise_if_error(status, body)
        return json.loads(body)

    async def _post_json(self, path, payload, headers, query_params):
        status, resp_headers, body = await self._post(
            path, json.dumps(payload).encode(), headers, query_params
        )
        body = self._maybe_decompress(resp_headers, body)
        _raise_if_error(status, body)
        return json.loads(body) if body else None

    async def get_server_metadata(self, headers=None, query_params=None) -> dict:
        return await self._get_json(EP_SERVER_METADATA, headers, query_params)

    async def get_model_metadata(self, model_name, model_version="", headers=None, query_params=None) -> dict:
        return await self._get_json(
            model_path(model_name, model_version), headers, query_params
        )

    async def get_model_config(self, model_name, model_version="", headers=None, query_params=None) -> dict:
        return await self._get_json(
            model_config_path(model_name, model_version), headers, query_params
        )

    async def get_model_repository_index(self, headers=None, query_params=None) -> list:
        return await self._post_json(EP_REPOSITORY_INDEX, {}, headers, query_params)

    async def load_model(self, model_name, headers=None, query_params=None, config=None, files=None):
        payload = {}
        if config is not None or files is not None:
            parameters = {}
            if config is not None:
                parameters["config"] = config
            if files is not None:
                for path, content in files.items():
                    parameters[path] = base64.b64encode(content).decode()
            payload["parameters"] = parameters
        await self._post_json(
            repository_load_path(model_name), payload, headers, query_params
        )

    async def unload_model(self, model_name, headers=None, query_params=None, unload_dependents=False):
        await self._post_json(
            repository_unload_path(model_name),
            {"parameters": {KEY_UNLOAD_DEPENDENTS: unload_dependents}},
            headers,
            query_params,
        )

    async def get_inference_statistics(self, model_name="", model_version="", headers=None, query_params=None) -> dict:
        path = model_stats_path(model_name, model_version)
        return await self._get_json(path, headers, query_params)

    async def update_trace_settings(self, model_name="", settings=None, headers=None, query_params=None) -> dict:
        path = trace_setting_path(model_name)
        return await self._post_json(path, settings or {}, headers, query_params)

    async def get_trace_settings(self, model_name="", headers=None, query_params=None) -> dict:
        path = trace_setting_path(model_name)
        return await self._get_json(path, headers, query_params)

    async def update_log_settings(self, settings, headers=None, query_params=None) -> dict:
        return await self._post_json(EP_LOGGING, settings or {}, headers, query_params)

    async def get_log_settings(self, headers=None, query_params=None) -> dict:
        return await self._get_json(EP_LOGGING, headers, query_params)

    # -- shared memory admin -------------------------------------------------

    async def get_system_shared_memory_status(self, region_name="", headers=None, query_params=None) -> list:
        path = shm_admin_path("system", "status", region_name)
        return await self._get_json(path, headers, query_params)

    async def register_system_shared_memory(self, name, key, byte_size, offset=0, headers=None, query_params=None):
        await self._post_json(
            shm_admin_path("system", "register", name),
            {"key": key, "offset": offset, "byte_size": byte_size},
            headers,
            query_params,
        )

    async def unregister_system_shared_memory(self, name="", headers=None, query_params=None):
        path = shm_admin_path("system", "unregister", name)
        await self._post_json(path, {}, headers, query_params)

    async def get_tpu_shared_memory_status(self, region_name="", headers=None, query_params=None) -> list:
        path = shm_admin_path("tpu", "status", region_name)
        return await self._get_json(path, headers, query_params)

    async def register_tpu_shared_memory(self, name, raw_handle, device_id, byte_size, headers=None, query_params=None):
        await self._post_json(
            shm_admin_path("tpu", "register", name),
            {
                "raw_handle": {"b64": base64.b64encode(raw_handle).decode()},
                "device_id": device_id,
                "byte_size": byte_size,
            },
            headers,
            query_params,
        )

    async def unregister_tpu_shared_memory(self, name="", headers=None, query_params=None):
        path = shm_admin_path("tpu", "unregister", name)
        await self._post_json(path, {}, headers, query_params)

    # -- inference -----------------------------------------------------------

    async def infer(
        self,
        model_name,
        inputs,
        model_version="",
        outputs=None,
        request_id="",
        sequence_id=0,
        sequence_start=False,
        sequence_end=False,
        priority=0,
        timeout=None,
        headers=None,
        query_params=None,
        request_compression_algorithm=None,
        response_compression_algorithm=None,
        parameters=None,
        timers=None,
        traceparent=None,
        idempotency_key=None,
    ) -> InferResult:
        """``timers``: optional RequestTimers stamped around marshal /
        POST / result wrap, attached to the result as ``result.timers``;
        ``request_id`` also rides as the triton-request-id header and
        ``traceparent`` as the W3C trace-context header (same contract as
        the sync client).

        ``timeout`` (KServe budget, microseconds) is honored as a REAL
        aiohttp per-request deadline, not just a server-side parameter: a
        dead or wedged server can no longer hang this client past its own
        stated deadline (the healthy path sheds server-side with a fast
        504 well before the client bound fires)."""
        if timers is not None:
            timers.capture("request_start")
            timers.capture("send_start")
        request_body, json_size = _get_inference_request(
            inputs=inputs,
            request_id=request_id,
            outputs=outputs,
            sequence_id=sequence_id,
            sequence_start=sequence_start,
            sequence_end=sequence_end,
            priority=priority,
            timeout=timeout,
            custom_parameters=parameters,
        )
        all_headers = dict(headers) if headers else {}
        if request_compression_algorithm == "gzip":
            all_headers["Content-Encoding"] = "gzip"
            request_body = gzip.compress(request_body)
        elif request_compression_algorithm == "deflate":
            all_headers["Content-Encoding"] = "deflate"
            request_body = zlib.compress(request_body)
        if response_compression_algorithm == "gzip":
            all_headers["Accept-Encoding"] = "gzip"
        elif response_compression_algorithm == "deflate":
            all_headers["Accept-Encoding"] = "deflate"
        if json_size is not None:
            all_headers["Inference-Header-Content-Length"] = str(json_size)
        if request_id:
            all_headers.setdefault("triton-request-id", request_id)
        if traceparent:
            all_headers.setdefault("traceparent", traceparent)
        if idempotency_key:
            all_headers.setdefault(HEADER_IDEMPOTENCY_KEY, idempotency_key)
        if timers is not None:
            timers.capture("send_end")

        path = model_infer_path(model_name, model_version)
        policy = self._retry_policy
        idempotent = any(
            k.lower() == HEADER_IDEMPOTENCY_KEY for k in all_headers
        )
        attempt = 0
        while True:
            if self._breaker is not None:
                self._breaker.check()
            if attempt and policy is not None:
                all_headers[HEADER_RETRY_ATTEMPT] = str(attempt)
            try:
                status, resp_headers, body = await self._post(
                    path, request_body, all_headers, query_params,
                    timeout_s=(timeout / 1e6) if timeout else None,
                )
            except asyncio.TimeoutError:
                # The request's own deadline: never replayed (a retry
                # would double the effective timeout).
                if self._breaker is not None:
                    self._breaker.on_failure()
                raise InferenceServerException(
                    msg=f"inference request timed out after its {timeout} "
                    "us deadline (client-side bound)"
                ) from None
            except aiohttp.ClientConnectorError as e:
                if self._breaker is not None:
                    self._breaker.on_failure()
                if policy is not None and policy.should_retry(
                    attempt, policy.classify(PHASE_CONNECT)
                ):
                    await asyncio.sleep(policy.backoff_s(attempt))
                    attempt += 1
                    continue
                raise
            except aiohttp.ClientError as e:  # noqa: F841 — post-connect
                if self._breaker is not None:
                    self._breaker.on_failure()
                # aiohttp does not split send from response read; the
                # request may have executed, so only an idempotency key
                # authorizes a replay.
                if policy is not None and policy.should_retry(
                    attempt,
                    policy.classify(PHASE_RESPONSE, idempotent=idempotent),
                ):
                    await asyncio.sleep(policy.backoff_s(attempt))
                    attempt += 1
                    continue
                raise
            if (
                policy is not None
                and status in policy.retryable_statuses
                and policy.should_retry(
                    attempt,
                    policy.classify(PHASE_RESPONSE, status=status),
                )
            ):
                await asyncio.sleep(policy.backoff_s(
                    attempt,
                    parse_retry_after(resp_headers.get(HEADER_RETRY_AFTER)),
                ))
                attempt += 1
                continue
            break
        if self._breaker is not None:
            self._breaker.on_success()
        if policy is not None:
            policy.note_success()
        _raise_if_error(status, body)
        if timers is not None:
            timers.capture("recv_start")
        header_length = resp_headers.get("Inference-Header-Content-Length")
        result = InferResult(
            body,
            int(header_length) if header_length is not None else None,
            resp_headers.get("Content-Encoding"),
        )
        if timers is not None:
            timers.capture("recv_end")
            timers.capture("request_end")
            result.timers = timers
        return result

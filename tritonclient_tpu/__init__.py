"""tritonclient_tpu — a TPU-native client/server framework speaking the KServe v2
inference protocol.

This package provides the same capabilities as the Triton Inference Server client
libraries (reference: ``tritonclient``), re-designed TPU-first:

- ``tritonclient_tpu.http`` / ``tritonclient_tpu.grpc`` — sync, async and asyncio
  clients for the KServe v2 protocol (REST + gRPC), mirroring the reference's
  ``InferenceServerClient`` / ``InferInput`` / ``InferRequestedOutput`` /
  ``InferResult`` quartet (reference: src/python/library/tritonclient/{http,grpc}/).
- ``tritonclient_tpu.utils`` — dtype mapping (with *real* bfloat16 via ml_dtypes,
  improving on the reference's float32 shim at utils/__init__.py:184), BYTES/BF16
  wire serialization, DLPack interop.
- ``tritonclient_tpu.utils.shared_memory`` — POSIX system shared memory transport
  (ctypes over a native C++ core, reference: utils/shared_memory + libcshm).
- ``tritonclient_tpu.utils.tpu_shared_memory`` — the TPU-native zero-copy plane:
  XLA/PjRt device buffers registered via DLPack so jax.Arrays move in and out of a
  co-located JAX-backend server without host staging (reference analog:
  utils/cuda_shared_memory backed by cudaIpc).
- ``tritonclient_tpu.server`` — an in-process JAX-backed KServe v2 server (HTTP +
  gRPC) used both as the hermetic test fixture and as a real co-located backend.
- ``tritonclient_tpu.models`` — the JAX/Flax model zoo backing the benchmarks
  (simple add/sub, ResNet50, BERT-base).
- ``tritonclient_tpu.parallel`` — device-mesh sharding (dp/tp/sp) for multi-chip
  serving and training via jax.sharding + XLA collectives.
- ``tritonclient_tpu.perf`` — perf_analyzer-equivalent load generator.
"""

from tritonclient_tpu._version import __version__  # noqa: F401
from tritonclient_tpu._client import InferenceServerClientBase  # noqa: F401
from tritonclient_tpu._plugin import InferenceServerClientPlugin  # noqa: F401
from tritonclient_tpu._request import Request  # noqa: F401

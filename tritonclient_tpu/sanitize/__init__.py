"""tpusan — the runtime sanitizer tier that witnesses tpulint's invariants.

tpulint (``tritonclient_tpu/analysis``) proves lock-order, shm-lifecycle,
and async-blocking discipline *statically*; tpusan closes the loop by
watching the same invariants under real execution. The witnesses, most
paired with a static rule:

=======  ====================  ===============================================
pairs    witness               catches at runtime
=======  ====================  ===============================================
TPU007   lock-order            cycles in the live per-thread lock-acquisition
                               graph over the project's *named* locks, and a
                               named lock held across a blocking call
                               (``time.sleep``, ``mmap.mmap``,
                               ``socket.create_connection``,
                               ``jax.device_put``); both stacks recorded
TPU006   shm-lifecycle         the create/register/set/read/unregister/destroy
                               state machine driven by real calls through both
                               shm packages and the server registries:
                               use-after-unregister/destroy, double-register,
                               destroy-while-registered, handles leaked at
                               process exit
TPU001   async-blocking        ``time.sleep``/``socket.create_connection`` on
                               a thread with a running event loop, and
                               event-loop callbacks exceeding the
                               slow-callback threshold
TPU009   lockset (races)       empty candidate lockset on a field touched by
                               ≥2 threads with a write — Eraser refinement
                               over the named locks at explicit
                               ``note_field_access`` adoption sites
                               (``_races.py``)
TPU012   mem-reconcile         the memscope ledger's reconciliation
                               invariant: a finished/shed/cancelled
                               request whose per-owner device-memory
                               bytes did not return to zero — the
                               finding carries the allocation-site AND
                               leak-site stacks (``_mem.py``; dynamic-
                               only, no static pair)
TPU015   donation poisoner     a buffer donated to a jitted callable
                               (``donate_argnums``) read after the
                               dispatch — garbage on real TPUs while the
                               CPU tier runs green; the finding carries
                               the donation-site AND read-site stacks
                               (``_jax.py``)
TPU016   transfer witness      an implicit device transfer under
                               ``jax.transfer_guard("disallow")`` — the
                               degenerate sharding-drift reshard, a host
                               round-trip per call (``_jax.py``)
TPU017   compile-cache watcher distinct lowerings of a watched callable
                               exceeding its declared bucket budget — an
                               unbucketed per-request magnitude shaping
                               traced operands; also feeds the
                               nv_engine_compile_cache_entries /
                               nv_engine_retrace_total metrics plane
                               (``_jax.py``)
=======  ====================  ===============================================

Activation: ``TPUSAN=1`` in the environment (the test suite's
``conftest.py`` then enables it for the whole session and fails the run
on findings), or programmatic ``sanitize.enable()``. ``TPUSAN=strict``
(or ``TPUSAN_MODE=strict``) raises :class:`TpusanError` at the violation
site; the default ``report`` mode records findings and lets execution
continue. ``TPUSAN_REPORT=<path>`` writes the findings at process exit —
``.sarif`` extension selects SARIF 2.1.0, anything else JSON.

Findings reuse tpulint's ``Finding`` shape and ``rule::path::message``
fingerprints, so runtime findings round-trip through the same
``--baseline`` machinery and merge with the static SARIF upload in code
scanning. ``scripts/tpusan_report.py`` diffs a runtime report against the
static picture (witnessed / never-exercised / unpredicted).

Zero overhead when inactive: the ``named_lock``/``named_rlock``/
``named_condition`` factories return plain ``threading`` primitives
unless the sanitizer is active at construction time, and no syscalls are
patched until ``enable()``.
"""

import atexit
import json
import os
import threading
import traceback
from typing import Dict, List, Optional

from tritonclient_tpu.analysis._engine import Finding

__all__ = [
    "TpusanError",
    "capture",
    "check_leaks",
    "disable",
    "enable",
    "enabled",
    "findings",
    "mode",
    "named_condition",
    "named_lock",
    "named_rlock",
    "note_event_loop",
    "note_field_access",
    "report_finding",
    "reset",
    "schedule_controller",
    "set_schedule_controller",
    "write_report",
]

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SAN_DIR = os.path.dirname(os.path.abspath(__file__))

#: Witness rule metadata for the SARIF driver block. Same ids as the
#: static rules they pair with — that identity is what lets the two
#: report streams merge.
RULES_META = [
    {
        "id": "TPU001",
        "name": "async-blocking",
        "shortDescription": {
            "text": "blocking call or slow callback witnessed on a running "
            "event-loop thread"
        },
    },
    {
        "id": "TPU006",
        "name": "shm-lifecycle",
        "shortDescription": {
            "text": "shared-memory lifecycle violation witnessed at runtime"
        },
    },
    {
        "id": "TPU007",
        "name": "lock-order",
        "shortDescription": {
            "text": "lock-order cycle or lock-held-across-blocking-call "
            "witnessed at runtime"
        },
    },
    {
        "id": "TPU009",
        "name": "guarded-by",
        "shortDescription": {
            "text": "empty lockset witnessed on a cross-thread field "
            "access (Eraser refinement over the named locks)"
        },
    },
    {
        "id": "TPU012",
        "name": "mem-reconcile",
        "shortDescription": {
            "text": "device-memory ledger leak: a finished/shed/"
            "cancelled request's memscope bytes did not return to zero"
        },
    },
    {
        "id": "TPU015",
        "name": "donation-discipline",
        "shortDescription": {
            "text": "read-after-donate witnessed: a buffer donated to a "
            "jitted callable was touched again (garbage on real TPUs)"
        },
    },
    {
        "id": "TPU016",
        "name": "sharding-drift",
        "shortDescription": {
            "text": "implicit device transfer witnessed under "
            "jax.transfer_guard: placement disagrees with the boundary"
        },
    },
    {
        "id": "TPU017",
        "name": "bucket-discipline",
        "shortDescription": {
            "text": "compile-cache overflow witnessed: distinct lowerings "
            "exceeded the callable's declared bucket budget"
        },
    },
]


class TpusanError(AssertionError):
    """Raised at the violation site in strict mode (``TPUSAN=strict``)."""


class _State:
    def __init__(self):
        self.active = False
        self.mode = "report"
        self.depth = 0  # enable() nesting
        self.lock = threading.Lock()
        self.records: List[dict] = []  # finding dicts incl. stacks
        self.fingerprints: set = set()  # dedupe: one record per fingerprint
        self.env_session = False  # activated by TPUSAN env (atexit reports)
        self.atexit_registered = False


_STATE = _State()


def _env_flag() -> Optional[str]:
    raw = os.environ.get("TPUSAN", "").strip().lower()
    if raw in ("", "0", "false", "off"):
        return None
    return raw


def enabled() -> bool:
    return _STATE.active


def mode() -> str:
    return _STATE.mode


def strict() -> bool:
    return _STATE.active and _STATE.mode == "strict"


def enable(mode: Optional[str] = None):
    """Activate the witnesses (idempotent; nests with :func:`disable`).

    ``mode``: ``"report"`` (record, keep running) or ``"strict"`` (raise
    :class:`TpusanError` at the violation). Defaults to ``TPUSAN_MODE``,
    then ``TPUSAN=strict``, then ``report``.
    """
    from tritonclient_tpu.sanitize import _aio, _blocking, _jax, _mem, _shm

    with _STATE.lock:
        _STATE.depth += 1
        if mode is None:
            mode = os.environ.get("TPUSAN_MODE", "").strip().lower() or (
                "strict" if _env_flag() == "strict" else "report"
            )
        if mode not in ("report", "strict"):
            raise ValueError(f"unknown tpusan mode: {mode!r}")
        _STATE.mode = mode
        already = _STATE.active
        _STATE.active = True
        if not _STATE.atexit_registered:
            _STATE.atexit_registered = True
            atexit.register(_atexit_report)
    if not already:
        _blocking.install()
        _shm.install()
        _aio.install()
        _mem.install()
        _jax.install()


def disable():
    """Deactivate and unpatch once every :func:`enable` is balanced."""
    from tritonclient_tpu.sanitize import _aio, _blocking, _jax, _mem, _shm

    with _STATE.lock:
        _STATE.depth = max(0, _STATE.depth - 1)
        if _STATE.depth:
            return
        _STATE.active = False
    _aio.uninstall()
    _shm.uninstall()
    _blocking.uninstall()
    _mem.uninstall()
    _jax.uninstall()


def reset():
    """Drop recorded findings and witness state (locks graph, shm states,
    field locksets)."""
    from tritonclient_tpu.sanitize import _jax, _locks, _mem, _races, _shm

    with _STATE.lock:
        _STATE.records.clear()
        _STATE.fingerprints.clear()
    _locks.reset()
    _races.reset()
    _shm.reset()
    _mem.reset()
    _jax.reset()


def _project_site(skip_sanitize: bool = True):
    """(repo-relative path, line, stack text) of the violation site: the
    innermost frame outside this package (and outside stdlib internals),
    so fingerprints point at project code the way tpulint's do."""
    stack = traceback.extract_stack()
    chosen = None
    for frame in reversed(stack):
        fn = os.path.abspath(frame.filename)
        if skip_sanitize and fn.startswith(_SAN_DIR):
            continue
        if fn.startswith(_REPO_ROOT + os.sep):
            chosen = frame
            break
    if chosen is None:  # violation entirely outside the repo: last frame
        for frame in reversed(stack):
            if not os.path.abspath(frame.filename).startswith(_SAN_DIR):
                chosen = frame
                break
    path = os.path.abspath(chosen.filename) if chosen else "<unknown>"
    if path.startswith(_REPO_ROOT + os.sep):
        path = os.path.relpath(path, _REPO_ROOT)
    text = "".join(traceback.format_list(stack[-12:]))
    return path.replace(os.sep, "/"), (chosen.lineno or 1) if chosen else 1, text


def report_finding(
    rule: str,
    message: str,
    path: Optional[str] = None,
    line: Optional[int] = None,
    stacks: Optional[List[str]] = None,
):
    """Record one runtime finding (and raise in strict mode).

    ``path``/``line`` default to the innermost project frame of the
    current stack. ``message`` must be deterministic (no durations,
    addresses, thread ids): the ``rule::path::message`` fingerprint is
    the baseline/code-scanning identity.
    """
    if not _STATE.active:
        return
    site_path, site_line, site_stack = _project_site()
    if path is None:
        path = site_path
    if line is None:
        line = site_line
    record = {
        "rule": rule,
        "path": path,
        "line": int(line),
        "col": 0,
        "message": message,
        "stacks": list(stacks or []) + [site_stack],
    }
    fp = f"{rule}::{path}::{message}"
    record["fingerprint"] = fp
    with _STATE.lock:
        if fp not in _STATE.fingerprints:
            _STATE.fingerprints.add(fp)
            _STATE.records.append(record)
    if _STATE.mode == "strict":
        raise TpusanError(f"tpusan: {rule} {path}:{line}: {message}")


def findings() -> List[Finding]:
    """Recorded findings as tpulint ``Finding`` objects (fingerprint-
    compatible with the baseline machinery)."""
    with _STATE.lock:
        records = list(_STATE.records)
    return [
        Finding(r["rule"], r["path"], r["line"], r["col"], r["message"])
        for r in records
    ]


def records() -> List[dict]:
    """Raw finding records including captured stacks."""
    with _STATE.lock:
        return [dict(r) for r in _STATE.records]


class capture:
    """Context manager isolating findings seeded inside the block.

    Seeded-violation tests run under a session-wide sanitizer; without
    isolation their deliberate findings would fail the session's
    zero-finding gate. ``.findings``/``.records`` are live inside the
    block; on exit the block's findings are removed from the global
    store (and stay readable on the capture object).
    """

    def __init__(self):
        self._taken: Optional[List[dict]] = None
        self._base = 0

    def __enter__(self):
        with _STATE.lock:
            self._base = len(_STATE.records)
        return self

    @property
    def records(self) -> List[dict]:
        if self._taken is not None:
            return [dict(r) for r in self._taken]
        with _STATE.lock:
            return [dict(r) for r in _STATE.records[self._base:]]

    @property
    def findings(self) -> List[Finding]:
        return [
            Finding(r["rule"], r["path"], r["line"], r["col"], r["message"])
            for r in self.records
        ]

    def __exit__(self, exc_type, exc, tb):
        with _STATE.lock:
            self._taken = _STATE.records[self._base:]
            del _STATE.records[self._base:]
            for r in self._taken:
                _STATE.fingerprints.discard(r["fingerprint"])
        return False


def check_leaks():
    """Report handles created but never destroyed (TPU006 leak arm).

    Called at process exit for env-activated sessions and by the pytest
    plugin at session finish; callable any time (e.g. after a test that
    owns its regions' full lifecycle).
    """
    from tritonclient_tpu.sanitize import _shm

    _shm.report_leaks()


def write_report(path: str):
    """Write recorded findings: SARIF 2.1.0 for ``.sarif`` paths, JSON
    (with stacks) otherwise."""
    if path.endswith(".sarif"):
        from tritonclient_tpu.analysis._sarif import render_sarif

        doc = render_sarif(findings(), RULES_META, tool_name="tpusan")
        with open(path, "w", encoding="utf-8") as f:
            f.write(doc)
        return
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"tool": "tpusan", "findings": records()}, f, indent=2)
        f.write("\n")


def render_text() -> str:
    found = findings()
    lines = [f.text() for f in found]
    noun = "finding" if len(found) == 1 else "findings"
    lines.append(f"tpusan: {len(found)} {noun}")
    return "\n".join(lines)


def _atexit_report():
    if not _STATE.active:
        return
    try:
        check_leaks()
    except TpusanError:
        pass  # strict-mode leak at exit: still reported below
    except Exception:
        pass
    out = os.environ.get("TPUSAN_REPORT", "")
    if out:
        try:
            write_report(out)
        except OSError:
            pass


# --------------------------------------------------------------------------- #
# named-lock factories (adoption points in server/_core, shm, gpt_engine)     #
# --------------------------------------------------------------------------- #

#: When set (by ``tritonclient_tpu.mc``), the factories below hand lock
#: construction to the model checker's cooperative scheduler instead of
#: ``threading`` — the sanitizer's instrumentation points double as
#: tpumc's schedule-control points. Thread-confined by convention: only
#: the checker's driver thread flips it, around a fully serialized run.
_SCHED_CONTROLLER = None


def set_schedule_controller(controller):
    """Install (or with ``None`` remove) a tpumc schedule controller.

    While installed, :func:`named_lock`/:func:`named_rlock`/
    :func:`named_condition` return the controller's schedule-controlled
    primitives and :func:`note_field_access` also feeds the controller,
    so code constructed inside a model-checking run is steered through
    every interleaving the explorer enumerates. Returns the previously
    installed controller so callers can restore it.
    """
    global _SCHED_CONTROLLER
    previous = _SCHED_CONTROLLER
    # Install/remove happen only in the explorer's single-threaded
    # phases (before model threads start, after they are parked or
    # aborted), so the bare write never overlaps a reader.
    _SCHED_CONTROLLER = controller  # tpulint: disable=TPU009
    return previous


def schedule_controller():
    """The installed tpumc schedule controller, or ``None``."""
    return _SCHED_CONTROLLER


def named_lock(name: str):
    """A ``threading.Lock`` known to the lock-order witness by ``name``.

    Returns a plain lock when the sanitizer is inactive at construction
    (zero overhead on the hot path); an instrumented wrapper otherwise.
    tpulint's TPU002/TPU007 recognize this factory as a lock constructor,
    so adoption does not shrink the static graph.
    """
    if _SCHED_CONTROLLER is not None:
        return _SCHED_CONTROLLER.make_lock(name, reentrant=False)
    lock = threading.Lock()
    if not _STATE.active:
        return lock
    from tritonclient_tpu.sanitize._locks import TrackedLock

    return TrackedLock(name, lock, reentrant=False)


def named_rlock(name: str):
    """``threading.RLock`` variant of :func:`named_lock`."""
    if _SCHED_CONTROLLER is not None:
        return _SCHED_CONTROLLER.make_lock(name, reentrant=True)
    lock = threading.RLock()
    if not _STATE.active:
        return lock
    from tritonclient_tpu.sanitize._locks import TrackedLock

    return TrackedLock(name, lock, reentrant=True)


def named_condition(name: str):
    """``threading.Condition`` known to the lock-order witness by ``name``."""
    if _SCHED_CONTROLLER is not None:
        return _SCHED_CONTROLLER.make_condition(name)
    cond = threading.Condition()
    if not _STATE.active:
        return cond
    from tritonclient_tpu.sanitize._locks import TrackedCondition

    return TrackedCondition(name, cond)


def note_field_access(owner, field: str, write: bool = True,
                      label: Optional[str] = None):
    """TPU009 lockset witness: record one access to ``owner.field``.

    Eraser refinement over the named locks — see ``_races.py``. No-op
    (one predicate check) while the sanitizer is inactive, so hot-path
    adoption sites cost nothing in production.
    """
    if _SCHED_CONTROLLER is not None:
        _SCHED_CONTROLLER.field_access(owner, field, write=write, label=label)
    if not _STATE.active:
        return
    from tritonclient_tpu.sanitize import _races

    _races.note_field_access(owner, field, write=write, label=label)


def note_event_loop():
    """Opt the calling thread's running loop into watchdog accounting.

    The aio clients call this at construction; it is a no-op when the
    sanitizer is inactive. The ``Handle._run`` patch already times every
    loop, so this only lowers the slow-callback threshold source of truth
    onto loops the project actually owns.
    """
    if not _STATE.active:
        return
    from tritonclient_tpu.sanitize import _aio

    _aio.note_event_loop()

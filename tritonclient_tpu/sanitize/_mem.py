"""Device-memory reconciliation witness (rule TPU012).

The fourth runtime witness, alongside locks (``_locks``/``_races``),
shared memory (``_shm``) and the event loop (``_blocking``/``_aio``):
it pairs with the memscope ledger (``tritonclient_tpu._memscope``)
rather than a static lint — the reconciliation invariant ("after any
request finishes, sheds, or cancels, the ledger's live bytes for that
request return to zero") is only checkable on *real* allocation
traffic.

Protocol:

* ``_memscope.owner_begin`` calls :func:`note_alloc` — the allocation
  site stack is captured here, keyed by ``(scope, pool, owner)``;
* ``_memscope.owner_finish`` calls :func:`report_leak` when the owner's
  ledger bytes are nonzero — the finding carries BOTH the allocation
  stack and the leak-site stack (``report_finding`` appends the current
  site automatically);
* :func:`drop_alloc` forgets a cleanly-reconciled owner's stack.

Events only fire while the sanitizer is active; the stack table is
bounded by in-flight owners (every terminal path drops its key).
"""

import threading
import traceback
from typing import Dict, Tuple

_LOCK = threading.Lock()
#: (scope, pool, owner) -> allocation-site stack text.
_ALLOC_STACKS: Dict[Tuple[str, str, str], str] = {}
_installed = False


def _active() -> bool:
    from tritonclient_tpu import sanitize

    return sanitize.enabled() and _installed


# tpulint: disable=TPU009 - benign single-rebind mode publication
def install():
    global _installed
    _installed = True


def uninstall():
    global _installed
    _installed = False


def reset():
    with _LOCK:
        _ALLOC_STACKS.clear()


def note_alloc(key: Tuple[str, str, str]):
    """Record the allocation site of an owner's reservation."""
    if not _active():
        return
    stack = "".join(traceback.format_list(traceback.extract_stack()[-12:]))
    with _LOCK:
        _ALLOC_STACKS[key] = stack


def drop_alloc(key: Tuple[str, str, str]):
    with _LOCK:
        _ALLOC_STACKS.pop(key, None)


def report_leak(scope: str, pool: str, owner: str, nbytes: int):
    """An owner finished with nonzero ledger bytes: a page left the pool
    without leaving the ledger (or vice versa). The message is
    deterministic per (scope, pool, owner) so the fingerprint is stable
    across runs."""
    if not _active():
        return
    from tritonclient_tpu import sanitize

    with _LOCK:
        alloc_stack = _ALLOC_STACKS.get((scope, pool, owner))
    stacks = [alloc_stack] if alloc_stack else None
    sanitize.report_finding(
        "TPU012",
        f"device-memory ledger leak: owner '{owner}' finished holding "
        f"{int(nbytes)} bytes in pool {scope}/{pool} (allocation and "
        "leak-site stacks attached)",
        stacks=stacks,
    )

"""Event-loop blocking watchdog (pairs with tpulint TPU001).

Two arms:

* blocking-call: the patched syscalls (``_blocking.py``) call
  :func:`note_blocking`; a ``time.sleep`` or synchronous connect on a
  thread that currently runs an asyncio event loop is exactly the bug
  TPU001 proves statically for ``async def`` bodies — witnessed here for
  every path that actually executes, including ones the AST cannot see
  (callbacks, dynamically dispatched handlers).
* slow-callback: ``asyncio.events.Handle._run`` is wrapped to time each
  callback. One exceeding the threshold (``TPUSAN_SLOW_CALLBACK_S``,
  default 1.0 s — generous enough that first-use XLA compiles on a CPU
  test loop do not trip it; tighten in dedicated runs) is reported with
  a deterministic message (callback qualname, no duration) so the
  fingerprint is stable across runs.
"""

import asyncio
import functools
import os

_ORIG_HANDLE_RUN = None


def _threshold() -> float:
    try:
        return float(os.environ.get("TPUSAN_SLOW_CALLBACK_S", "1.0"))
    except ValueError:
        return 1.0


def note_event_loop():
    """Accounting hook for project-owned loops (aio clients call this);
    the Handle patch is global, so this is currently informational."""


def note_blocking(callname: str):
    from tritonclient_tpu import sanitize

    if asyncio._get_running_loop() is None:
        return
    sanitize.report_finding(
        "TPU001",
        f"blocking call `{callname}` witnessed on a running event-loop "
        "thread; use the aio equivalent or an executor",
    )


def _callback_name(handle) -> str:
    cb = getattr(handle, "_callback", None)
    if isinstance(cb, functools.partial):
        cb = cb.func
    inner = getattr(cb, "__wrapped__", None)
    if inner is not None:
        cb = inner
    for attr in ("__qualname__", "__name__"):
        name = getattr(cb, attr, None)
        if name:
            return name
    return type(cb).__name__ if cb is not None else "callback"


def install():
    global _ORIG_HANDLE_RUN
    if _ORIG_HANDLE_RUN is not None:
        return
    import time as _time

    from tritonclient_tpu import sanitize

    orig = asyncio.events.Handle._run
    _ORIG_HANDLE_RUN = orig

    def _run(self):
        t0 = _time.monotonic()
        try:
            return orig(self)
        finally:
            if (
                sanitize.enabled()
                and _time.monotonic() - t0 > _threshold()
            ):
                try:
                    name = _callback_name(self)
                except Exception:
                    name = "callback"
                try:
                    sanitize.report_finding(
                        "TPU001",
                        f"event-loop callback `{name}` blocked the loop "
                        "past the slow-callback threshold",
                    )
                except sanitize.TpusanError:
                    raise
    asyncio.events.Handle._run = _run


def uninstall():
    global _ORIG_HANDLE_RUN
    if _ORIG_HANDLE_RUN is not None:
        asyncio.events.Handle._run = _ORIG_HANDLE_RUN
        _ORIG_HANDLE_RUN = None

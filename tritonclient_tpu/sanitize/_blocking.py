"""Blocking-syscall patches shared by the lock and event-loop witnesses.

Installed by ``sanitize.enable()`` and removed by ``disable()``; each
wrapper forwards to the original after notifying:

* ``_locks.note_blocking`` — a *named* lock held across the call is the
  held-while-blocking arm of the TPU007 witness;
* ``_aio.note_blocking`` — ``time.sleep``/``socket.create_connection``
  on a thread with a running event loop is the TPU001 witness (the
  device/mmap calls are *not* reported there: the aio server deliberately
  enqueues device work from the loop thread — dispatch-enqueue is
  non-blocking by design and policed by the slow-callback watchdog
  instead).

``jax.device_put`` is only patched when jax is already imported at
enable time (the test conftest imports jax first); a missing jax is a
skipped patch, never an import.
"""

import mmap
import socket
import sys
import time

_PATCHED = {}

#: blocking calls the TPU001 (event-loop) witness reports; the TPU007
#: held-while-blocking arm reports every patched call.
LOOP_BLOCKING = {"time.sleep", "socket.create_connection"}


def _notify(callname: str):
    from tritonclient_tpu import sanitize
    from tritonclient_tpu.sanitize import _aio, _locks

    if not sanitize.enabled():
        return
    _locks.note_blocking(callname)
    if callname in LOOP_BLOCKING:
        _aio.note_blocking(callname)


def install():
    if _PATCHED:
        return

    orig_sleep = time.sleep

    def sleep(secs):
        _notify("time.sleep")
        return orig_sleep(secs)

    _PATCHED["time.sleep"] = (time, "sleep", orig_sleep)
    time.sleep = sleep

    orig_mmap = mmap.mmap

    def mmap_ctor(*args, **kwargs):
        _notify("mmap.mmap")
        return orig_mmap(*args, **kwargs)

    _PATCHED["mmap.mmap"] = (mmap, "mmap", orig_mmap)
    mmap.mmap = mmap_ctor

    orig_conn = socket.create_connection

    def create_connection(*args, **kwargs):
        _notify("socket.create_connection")
        return orig_conn(*args, **kwargs)

    _PATCHED["socket.create_connection"] = (
        socket, "create_connection", orig_conn,
    )
    socket.create_connection = create_connection

    jax = sys.modules.get("jax")
    if jax is not None and hasattr(jax, "device_put"):
        orig_put = jax.device_put

        def device_put(*args, **kwargs):
            _notify("jax.device_put")
            return orig_put(*args, **kwargs)

        _PATCHED["jax.device_put"] = (jax, "device_put", orig_put)
        jax.device_put = device_put


def uninstall():
    for mod, attr, orig in _PATCHED.values():
        setattr(mod, attr, orig)
    _PATCHED.clear()

"""Shared-memory lifecycle witness (pairs with tpulint TPU006).

Drives the ``create -> register -> set/read -> unregister -> destroy``
state machine on *real* calls: the module-level APIs of both
``utils/shared_memory`` (system plane) and ``utils/tpu_shared_memory``
(device plane) are wrapped at enable time, and the server-side
registries (``server/_core.SystemShmRegistry``/``TpuShmRegistry``)
report register/unregister at their single choke points. State is keyed
by ``(kind, region name)`` — the same identity the protocol uses.

Violations (strict mode raises, report mode records):

* use-after-unregister — ``set_*``/``get_contents``/``as_*`` on a region
  whose registration was dropped (the parked-PjRt-buffer corruption
  hazard on the zero-copy plane);
* use-after-destroy — any use after ``destroy_shared_memory_region``;
* double-register — registering a name that is already registered
  without an intervening unregister;
* destroy-while-registered — destroying a region the server still maps;
* leaked handles — regions created but never destroyed, reported by
  :func:`report_leaks` (process exit / pytest session finish).

Events only fire while the sanitizer is active; the wrappers forward to
the originals first where failure must not change state (a register that
raises never marks the region registered).
"""

import threading
from typing import Dict, Optional, Set, Tuple

_LOCK = threading.Lock()
#: (kind, name) -> "created" | "registered" | "unregistered" | "destroyed"
_STATES: Dict[Tuple[str, str], str] = {}
#: (kind, name) created through the CLIENT-side module APIs — the only
#: keys the exit-time leak check may blame (a server registry name with
#: no client handle, e.g. an alias registration, is not a leakable
#: handle).
_CREATED: Set[Tuple[str, str]] = set()
#: (kind, name) -> registry-instance ids currently holding a server-side
#: registration. The fleet tier runs N replica registries in ONE test
#: process, each legitimately registering the same region name (the
#: router fans admin state out to every replica) — double-register is a
#: violation per REGISTRY, not per process, and a region is
#: "unregistered" only when no registry holds it.
_SERVER_REGS: Dict[Tuple[str, str], Set[int]] = {}
_PATCHED = []


def reset():
    with _LOCK:
        _STATES.clear()
        _CREATED.clear()
        _SERVER_REGS.clear()


def _report(message: str):
    from tritonclient_tpu import sanitize

    sanitize.report_finding("TPU006", message)


def _set_state(kind: str, name: str, state: str):
    with _LOCK:
        _STATES[(kind, name)] = state


def _get_state(kind: str, name: str) -> Optional[str]:
    with _LOCK:
        return _STATES.get((kind, name))


def on_create(kind: str, name: str):
    # Re-creating a name after destroy is the normal reuse pattern;
    # leak detection happens at exit, not here.
    with _LOCK:
        _STATES[(kind, name)] = "created"
        _CREATED.add((kind, name))


def on_register(kind: str, name: str, registry=None):
    """``registry`` identifies the server-side registry instance (None
    for registrations observed without one — treated as a single
    anonymous registry)."""
    rid = id(registry) if registry is not None else 0
    with _LOCK:
        regs = _SERVER_REGS.setdefault((kind, name), set())
        duplicate = rid in regs
        if not duplicate:
            regs.add(rid)
            _STATES[(kind, name)] = "registered"
    if duplicate:
        _report(
            f"{kind} shared-memory region '{name}' registered twice "
            "without an intervening unregister"
        )


def on_unregister(kind: str, name: Optional[str], registry=None):
    rid = id(registry) if registry is not None else 0
    with _LOCK:
        if name:
            keys = [(kind, name)] if (kind, name) in _STATES else []
        else:  # unregister-all for this plane
            keys = [k for k, s in _STATES.items()
                    if k[0] == kind and s == "registered"]
        for key in keys:
            regs = _SERVER_REGS.get(key)
            if regs is not None:
                regs.discard(rid)
                if regs:
                    continue  # still registered on another replica
            if _STATES[key] == "registered":
                _STATES[key] = "unregistered"


def on_use(kind: str, name: str, what: str):
    state = _get_state(kind, name)
    if state == "unregistered":
        _report(
            f"{kind} shared-memory region '{name}' used ({what}) after "
            "unregister"
        )
    elif state == "destroyed":
        _report(
            f"{kind} shared-memory region '{name}' used ({what}) after "
            "destroy"
        )


def on_destroy(kind: str, name: str):
    if _get_state(kind, name) == "registered":
        _report(
            f"{kind} shared-memory region '{name}' destroyed while still "
            "registered with the server"
        )
    with _LOCK:
        _SERVER_REGS.pop((kind, name), None)
        _STATES[(kind, name)] = "destroyed"


def on_registry_dropped(registry):
    """Forget a dead registry's registrations.

    A stopped/crashed server no longer maps anything: fleet crash
    drills stop an ``InferenceServer`` and start a fresh one on the
    same ports, and the dead instance's registrations must not pin
    regions "registered" forever (``InferenceServer.stop`` reports its
    core's registries here). No-op when the sanitizer is off."""
    if not _active():
        return
    rid = id(registry)
    with _LOCK:
        for key, regs in _SERVER_REGS.items():
            if rid in regs:
                regs.discard(rid)
                if not regs and _STATES.get(key) == "registered":
                    _STATES[key] = "unregistered"


def report_leaks():
    with _LOCK:
        leaked = sorted(
            key for key, state in _STATES.items()
            if state != "destroyed" and key in _CREATED
        )
    for kind, name in leaked:
        _report(
            f"{kind} shared-memory region '{name}' was never destroyed "
            "(leaked handle at exit)"
        )


# --------------------------------------------------------------------------- #
# patch points                                                                #
# --------------------------------------------------------------------------- #


def _active() -> bool:
    from tritonclient_tpu import sanitize

    return sanitize.enabled()


def _wrap_module_fn(mod, attr, event):
    """Patch ``mod.attr`` so a successful call emits ``event(result,
    *args)``; the original result passes through untouched."""
    orig = getattr(mod, attr)

    def wrapper(*args, **kwargs):
        out = orig(*args, **kwargs)
        if _active():
            event(out, *args, **kwargs)  # strict-mode TpusanError surfaces
        return out

    _PATCHED.append((mod, attr, orig))
    setattr(mod, attr, wrapper)


def _region_name(handle) -> str:
    return getattr(handle, "triton_shm_name", str(handle))


def install():
    if _PATCHED:
        return
    import tritonclient_tpu.utils.tpu_shared_memory as tpushm

    _wrap_module_fn(
        tpushm, "create_shared_memory_region",
        lambda out, *a, **k: on_create("tpu", _region_name(out)),
    )
    _wrap_module_fn(
        tpushm, "create_sharded_memory_region",
        lambda out, *a, **k: on_create("tpu", _region_name(out)),
    )
    for fn, what in (
        ("set_shared_memory_region", "set"),
        ("set_shared_memory_region_from_dlpack", "set"),
        ("get_contents_as_numpy", "read"),
        ("as_shared_memory_tensor", "read"),
    ):
        _wrap_module_fn(
            tpushm, fn,
            lambda out, h, *a, _w=what, **k: on_use(
                "tpu", _region_name(h), _w
            ),
        )
    _wrap_module_fn(
        tpushm, "destroy_shared_memory_region",
        lambda out, h, *a, **k: on_destroy("tpu", _region_name(h)),
    )

    try:
        import tritonclient_tpu.utils.shared_memory as sysshm
    except Exception:  # pragma: no cover - native lib genuinely absent
        sysshm = None
    if sysshm is not None:
        _wrap_module_fn(
            sysshm, "create_shared_memory_region",
            lambda out, *a, **k: on_create("system", _region_name(out)),
        )
        for fn, what in (
            ("set_shared_memory_region", "set"),
            ("set_shared_memory_region_from_dlpack", "set"),
            ("get_contents_as_numpy", "read"),
        ):
            _wrap_module_fn(
                sysshm, fn,
                lambda out, h, *a, _w=what, **k: on_use(
                    "system", _region_name(h), _w
                ),
            )
        _wrap_module_fn(
            sysshm, "destroy_shared_memory_region",
            lambda out, h, *a, **k: on_destroy("system", _region_name(h)),
        )

    from tritonclient_tpu.server import _core

    def _registry_events(cls, kind):
        orig_register = cls.register
        orig_unregister = cls.unregister

        def register(self, name, *args, **kwargs):
            if not _active():
                return orig_register(self, name, *args, **kwargs)
            # Checked BEFORE the call: the server's register is a replace
            # (the old mapping is dropped silently), so double-register
            # must be witnessed at the protocol level — per REGISTRY
            # instance (N fleet replicas in one process each legitimately
            # hold the fanned-out registration). A register that then
            # FAILS rolls this registry's mark back — a rejected handle
            # never advances the region's lifecycle.
            prev = _get_state(kind, name)
            on_register(kind, name, registry=self)
            try:
                return orig_register(self, name, *args, **kwargs)
            except BaseException:
                on_unregister(kind, name, registry=self)
                with _LOCK:
                    if not _SERVER_REGS.get((kind, name)):
                        if prev is None and (kind, name) not in _CREATED:
                            _STATES.pop((kind, name), None)
                            _SERVER_REGS.pop((kind, name), None)
                        elif prev is not None:
                            _STATES[(kind, name)] = prev
                raise

        def unregister(self, name, *args, **kwargs):
            out = orig_unregister(self, name, *args, **kwargs)
            if _active():
                on_unregister(kind, name, registry=self)
            return out

        _PATCHED.append((cls, "register", orig_register))
        _PATCHED.append((cls, "unregister", orig_unregister))
        cls.register = register
        cls.unregister = unregister

    _registry_events(_core.SystemShmRegistry, "system")
    _registry_events(_core.TpuShmRegistry, "tpu")


def uninstall():
    for obj, attr, orig in _PATCHED:
        setattr(obj, attr, orig)
    _PATCHED.clear()

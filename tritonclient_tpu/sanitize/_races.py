"""Runtime lockset witness (pairs with tpulint TPU009).

The Eraser algorithm over the project's *named* locks: every call to
:func:`note_field_access` intersects the field's candidate lockset with
the set of tracked locks the calling thread holds (``_locks.
held_lock_names``). A field whose candidate set goes empty after it has
been touched by ≥2 threads with at least one write has no lock that was
held on every access — the dynamic counterpart of the static rule's
majority-vote guard inference, and the arbiter for its benign-publication
false positives: a field the static pass flags but the witness never
reports under a racing workload was published safely.

State machine per field (Eraser's refinement schedule):

* **exclusive** — one thread has touched the field; the candidate set
  tracks the *latest* access's held locks (init-time accesses before the
  sharing thread exists must not poison the set);
* **shared** — ≥2 threads, reads only: candidate set refines by
  intersection but an empty set is not reported (read-read is benign);
* **shared-modified** — ≥2 threads with a write: an empty candidate set
  is a witnessed race, reported once per field with the access stacks.

Instrumentation is explicit — product code calls ``sanitize.
note_field_access(owner, "field", write=...)`` at the shared-state access
it wants witnessed (zero overhead when the sanitizer is inactive: one
predicate check). Identity is per *instance* (``id(owner)``) so two
independent objects never alias; labels are ``ClassName.field`` so the
finding fingerprint stays deterministic across runs.
"""

import threading
import traceback
from typing import Dict, Optional, Set, Tuple

_STATE_LOCK = threading.Lock()
_FIELDS: Dict[Tuple[int, str], "_FieldState"] = {}


class _FieldState:
    __slots__ = ("label", "threads", "lockset", "written", "reported",
                 "first_stack")

    def __init__(self, label: str, tid: int, held: Set[str], stack: str,
                 written: bool):
        self.label = label
        self.threads = {tid}
        self.lockset: Set[str] = set(held)
        self.written = written
        self.reported = False
        self.first_stack = stack


def reset():
    with _STATE_LOCK:
        _FIELDS.clear()


def note_field_access(owner, field: str, write: bool = True,
                      label: Optional[str] = None):
    """Record one access to ``owner.field`` by the calling thread.

    ``owner`` is the instance (or any hashable stand-in — a module name
    string works for module globals); ``label`` overrides the reported
    ``ClassName.field`` name. No-op while the sanitizer is inactive.
    """
    from tritonclient_tpu import sanitize
    from tritonclient_tpu.sanitize._locks import held_lock_names

    if not sanitize.enabled():
        return
    if label is None:
        owner_name = owner if isinstance(owner, str) else type(owner).__name__
        label = f"{owner_name}.{field}"
    held = set(held_lock_names())
    tid = threading.get_ident()
    stack = "".join(traceback.format_stack(limit=8))
    racy = None
    with _STATE_LOCK:
        st = _FIELDS.get((id(owner), field))
        if st is None:
            _FIELDS[(id(owner), field)] = _FieldState(
                label, tid, held, stack, write)
            return
        if tid in st.threads and len(st.threads) == 1:
            # Still exclusive: track the latest lockset rather than
            # intersecting — single-thread init writes without the lock
            # are the canonical benign publication.
            st.lockset = held
            st.written = st.written or write
            st.first_stack = stack
            return
        st.threads.add(tid)
        st.lockset &= held
        st.written = st.written or write
        if st.written and not st.lockset and not st.reported:
            st.reported = True
            racy = (st.label, st.first_stack)
    if racy is not None:
        label, first_stack = racy
        sanitize.report_finding(
            "TPU009",
            f"unsynchronized shared access witnessed on `{label}`: no "
            "common lock held across threads (empty lockset after a "
            "cross-thread write)",
            stacks=[first_stack, stack],
        )

"""JAX compute-plane witnesses (rules TPU015 / TPU016 / TPU017).

The runtime complement of tpushape (``analysis/_shapes.py``): the static
rules prove what they can from the AST; these witnesses catch what only
real dispatch traffic shows — and classify the static findings as
witnessed/unexercised via ``scripts/tpusan_report.py``.

Three witnesses:

* **Donation poisoner** (TPU015). :func:`donating` wraps a callable that
  was jitted with ``donate_argnums``: after each call the operands at the
  donated slots are *poisoned* (identity-tracked with the donation-site
  stack); passing a poisoned array back into any wrapped callable — or
  touching it through :func:`check_read` — reports a read-after-donate
  with BOTH stacks (donation site + read site). This matters because the
  CPU backend *ignores* donation: tier-1 tests run green while the same
  read returns garbage on a real TPU.

* **Transfer witness** (TPU016). :func:`check_transfers` wraps a call in
  ``jax.transfer_guard("disallow")``; an implicit device transfer inside
  (the degenerate form of a sharding-drift reshard: a host round-trip)
  reports TPU016 and, in report mode, re-runs the call unguarded so the
  program keeps going.

* **Compile-cache watcher** (TPU017). :func:`declare_bucket_budget` sets
  the number of distinct lowerings a callable is *allowed* (the bucket
  family size, e.g. ``log2(cap)`` for a pow2 bucketer);
  :func:`note_lowering` records each dispatch signature, feeds the
  stepscope compile plane (``nv_engine_compile_cache_entries`` /
  ``nv_engine_retrace_total``), and reports TPU017 once distinct
  signatures exceed the declared budget — the runtime proof of an
  unbucketed shape family.

Events only fire while the sanitizer is active; all tables are bounded
(poison table by live arrays via weakrefs, lowering table by the real
compile cache it mirrors).
"""

import threading
import traceback
import weakref
from typing import Dict, Optional, Tuple

_LOCK = threading.Lock()
#: id(array) -> (label, donation-site stack). Entries evaporate with the
#: array via weakref callbacks, so id reuse cannot mis-poison.
_POISONED: Dict[int, Tuple[str, str]] = {}
#: Keep the weakrefs alive until their referents die.
_POISON_REFS: Dict[int, object] = {}
#: callable label -> declared max distinct lowerings.
_BUDGETS: Dict[str, int] = {}
#: callable label -> set of distinct dispatch-signature keys.
_LOWERINGS: Dict[str, set] = {}
#: labels whose budget overflow was already reported (one finding each).
_OVERFLOWED: set = set()
_installed = False


def _active() -> bool:
    from tritonclient_tpu import sanitize

    return sanitize.enabled() and _installed


# tpulint: disable=TPU009 - benign single-rebind mode publication
def install():
    global _installed
    _installed = True


def uninstall():
    global _installed
    _installed = False


def reset():
    with _LOCK:
        _POISONED.clear()
        _POISON_REFS.clear()
        _BUDGETS.clear()
        _LOWERINGS.clear()
        _OVERFLOWED.clear()


def _stack() -> str:
    return "".join(traceback.format_list(traceback.extract_stack()[-12:]))


# -- donation poisoner (TPU015) --------------------------------------------- #


def _poison(obj, label: str):
    key = id(obj)
    stack = _stack()

    def _expire(_ref, _key=key):
        with _LOCK:
            _POISONED.pop(_key, None)
            _POISON_REFS.pop(_key, None)

    try:
        ref = weakref.ref(obj, _expire)
    except TypeError:  # not weakref-able: don't track (id reuse hazard)
        return
    with _LOCK:
        _POISONED[key] = (label, stack)
        _POISON_REFS[key] = ref


def _unpoison(obj):
    with _LOCK:
        _POISONED.pop(id(obj), None)
        _POISON_REFS.pop(id(obj), None)


def check_read(obj, where: str = ""):
    """Report TPU015 if ``obj`` was donated earlier (both stacks attached).

    The wrapped callables call this on every operand; engine code can
    also call it directly at an explicit read site. Returns True when a
    read-after-donate was reported."""
    if not _active():
        return False
    with _LOCK:
        hit = _POISONED.get(id(obj))
    if hit is None:
        return False
    label, donate_stack = hit
    from tritonclient_tpu import sanitize

    suffix = f" at {where}" if where else ""
    sanitize.report_finding(
        "TPU015",
        f"read-after-donate: a buffer donated to `{label}` was read"
        f"{suffix} — on TPU the donated buffer is invalidated by the "
        "dispatch, so this read returns garbage (donation and read-site "
        "stacks attached)",
        stacks=[donate_stack],
    )
    return True


def donating(fn, donate_argnums=(), label: Optional[str] = None):
    """Wrap a donating callable with the read-after-donate poisoner.

    ``donate_argnums`` must mirror the ``jax.jit(..., donate_argnums=)``
    the callable was built with. Every call first checks all operands
    against the poison table (a poisoned operand is a read-after-donate),
    then runs ``fn``, then poisons the operands at the donated slots.
    Rebinding the result over the donated name — the correct discipline —
    naturally retires the poisoned object."""
    name = label or getattr(fn, "__name__", repr(fn))
    slots = tuple(int(i) for i in donate_argnums)

    def wrapper(*args, **kwargs):
        if not _active():
            return fn(*args, **kwargs)
        for i, arg in enumerate(args):
            check_read(arg, where=f"argument {i} of `{name}`")
        result = fn(*args, **kwargs)
        for i in slots:
            if i < len(args):
                _poison(args[i], name)
        return result

    wrapper.__name__ = f"tpusan_donating[{name}]"
    wrapper.__wrapped__ = fn
    return wrapper


# -- transfer witness (TPU016) ---------------------------------------------- #


def check_transfers(fn, label: Optional[str] = None):
    """Wrap ``fn`` in ``jax.transfer_guard("disallow")``.

    An implicit device transfer inside the call — the degenerate
    sharding-drift reshard, a silent host round-trip on every step —
    reports TPU016; in report mode the call is then retried unguarded so
    execution continues (strict mode raises at the report)."""
    name = label or getattr(fn, "__name__", repr(fn))

    def wrapper(*args, **kwargs):
        if not _active():
            return fn(*args, **kwargs)
        try:
            import jax

            guard = jax.transfer_guard("disallow")
        except Exception:  # jax absent or too old: witness degrades to off
            return fn(*args, **kwargs)
        try:
            with guard:
                return fn(*args, **kwargs)
        except Exception as exc:
            if "transfer" not in str(exc).lower():
                raise
            from tritonclient_tpu import sanitize

            sanitize.report_finding(
                "TPU016",
                f"implicit device transfer witnessed inside `{name}`: an "
                "operand's placement disagrees with the boundary it "
                "crosses, forcing a silent host round-trip on every call "
                "— align the producer sharding with the consumer spec",
            )
            return fn(*args, **kwargs)

    wrapper.__name__ = f"tpusan_transfers[{name}]"
    wrapper.__wrapped__ = fn
    return wrapper


# -- compile-cache watcher (TPU017) ----------------------------------------- #


def declare_bucket_budget(label: str, budget: int):
    """Declare how many distinct lowerings ``label`` is allowed.

    The budget is the size of the callable's intended shape family — a
    pow2 bucketer with cap C yields ``log2(C)+1`` shapes. Exceeding it at
    runtime proves an unbucketed per-request magnitude reached the
    traced operands (the dynamic face of static rule TPU017)."""
    with _LOCK:
        _BUDGETS[label] = int(budget)


def signature_key(*operands) -> str:
    """The dispatch-signature key XLA's compile cache would use: the
    (shape, dtype) tuple of every array operand, ``repr`` for scalars."""
    parts = []
    for op in operands:
        shape = getattr(op, "shape", None)
        dtype = getattr(op, "dtype", None)
        if shape is not None:
            parts.append(f"{tuple(shape)}:{dtype}")
        else:
            parts.append(repr(op))
    return ";".join(parts)


def note_lowering(label: str, key: str, model: str = "engine"):
    """Record one dispatch signature for ``label``.

    Feeds the stepscope compile plane unconditionally-of-budget (the
    metrics family exists even for well-bucketed callables); reports
    TPU017 once — with the offending signature count — when distinct
    signatures exceed the declared bucket budget."""
    if not _active():
        return
    from tritonclient_tpu import _stepscope

    _stepscope.note_compile(model, label, key)
    with _LOCK:
        keys = _LOWERINGS.setdefault(label, set())
        keys.add(key)
        budget = _BUDGETS.get(label)
        overflow = (
            budget is not None
            and len(keys) > budget
            and label not in _OVERFLOWED
        )
        if overflow:
            _OVERFLOWED.add(label)
            count = len(keys)
    if not overflow:
        return
    from tritonclient_tpu import sanitize

    sanitize.report_finding(
        "TPU017",
        f"compile-cache overflow: `{label}` reached {count} distinct "
        f"lowerings against a declared bucket budget of {budget} — a "
        "per-request magnitude is shaping its traced operands without "
        "bucketing (one XLA compile per distinct size)",
    )


def watched(fn, label: Optional[str] = None, model: str = "engine"):
    """Wrap a jitted callable with the compile-cache watcher: every call
    records its operand signature via :func:`note_lowering`."""
    name = label or getattr(fn, "__name__", repr(fn))

    def wrapper(*args, **kwargs):
        if _active():
            note_lowering(name, signature_key(*args), model=model)
        return fn(*args, **kwargs)

    wrapper.__name__ = f"tpusan_watched[{name}]"
    wrapper.__wrapped__ = fn
    return wrapper

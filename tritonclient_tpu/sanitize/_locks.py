"""Lock-order witness (pairs with tpulint TPU007).

Instruments the project's *named* ``threading.Lock``/``RLock``/
``Condition`` instances (created through ``sanitize.named_lock`` and
friends). Per thread, the witness keeps the ordered list of currently
held locks with the stack captured at each acquire; every nested acquire
adds name-level edges to a process-global acquisition graph. A new edge
closing a cycle is reported with both acquisition stacks — the runtime
counterpart of TPU007's static with-nesting/calls-under-lock graph.

Two further arms:

* same-instance re-acquire of a non-reentrant lock is reported *before*
  the acquire blocks (in strict mode that turns a guaranteed deadlock
  into a diagnosable exception);
* a named lock held across a known blocking call (``time.sleep``,
  ``mmap.mmap``, ``socket.create_connection``, ``jax.device_put`` — see
  ``_blocking.py``) is reported as held-while-blocking.

Name-level identity mirrors the static rule's declaration-level nodes:
sibling instances of the same declaration share a node, but a same-name
edge is only recorded when it is literally the same object (two distinct
regions locking in sequence is not a cycle).
"""

import threading
import traceback
from typing import Dict, List, Set, Tuple

_tls = threading.local()

_GRAPH_LOCK = threading.Lock()
#: name -> set of names acquired while holding it
_EDGES: Dict[str, Set[str]] = {}
#: (a, b) -> (stack holding a, stack acquiring b) for the first sighting
_EDGE_SITES: Dict[Tuple[str, str], Tuple[str, str]] = {}
_REPORTED_CYCLES: Set[Tuple[str, ...]] = set()


def reset():
    with _GRAPH_LOCK:
        _EDGES.clear()
        _EDGE_SITES.clear()
        _REPORTED_CYCLES.clear()


def _held() -> List:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


class _Held:
    __slots__ = ("obj", "name", "stack", "count")

    def __init__(self, obj, name, stack):
        self.obj = obj
        self.name = name
        self.stack = stack
        self.count = 1


def held_lock_names() -> List[str]:
    """Names of tracked locks the calling thread currently holds."""
    return [h.name for h in _held()]


def note_blocking(callname: str):
    """Called by the patched blocking syscalls: report every tracked lock
    held by this thread across the call."""
    from tritonclient_tpu import sanitize

    for h in _held():
        sanitize.report_finding(
            "TPU007",
            f"lock '{h.name}' held across blocking call `{callname}`",
            stacks=[h.stack],
        )


def _find_path(graph: Dict[str, Set[str]], src: str, dst: str):
    """Shortest edge path src -> ... -> dst, or None."""
    if src == dst:
        return [src]
    seen = {src}
    frontier = [[src]]
    while frontier:
        nxt = []
        for path in frontier:
            for peer in sorted(graph.get(path[-1], ())):
                if peer == dst:
                    return path + [peer]
                if peer not in seen:
                    seen.add(peer)
                    nxt.append(path + [peer])
        frontier = nxt
    return None


def _before_acquire(lock):
    """Record edges held-locks -> lock; report cycles and self-deadlock.

    Runs before the underlying acquire so a strict-mode report can
    preempt a guaranteed same-thread deadlock.
    """
    from tritonclient_tpu import sanitize

    if not sanitize.enabled():
        return None
    held = _held()
    for h in held:
        if h.obj is lock._inner or h.obj is lock:
            if lock._reentrant:
                return None  # RLock/Condition re-entry: no new edge
            sanitize.report_finding(
                "TPU007",
                f"non-reentrant lock '{lock._name}' re-acquired by the "
                "holding thread (guaranteed self-deadlock)",
                stacks=[h.stack],
            )
            return None
    stack = "".join(traceback.format_stack(limit=12))
    new_cycles = []
    with _GRAPH_LOCK:
        for h in held:
            if h.name == lock._name:
                continue  # sibling instances of one declaration: no edge
            edges = _EDGES.setdefault(h.name, set())
            if lock._name in edges:
                continue
            # Adding h.name -> lock._name: a pre-existing path the other
            # way means the project acquires these declarations in both
            # orders — the deadlock condition TPU007 proves statically.
            back = _find_path(_EDGES, lock._name, h.name)
            edges.add(lock._name)
            _EDGE_SITES[(h.name, lock._name)] = (h.stack, stack)
            if back is not None:
                cycle = back + [lock._name]
                key = tuple(sorted(set(cycle)))
                if key not in _REPORTED_CYCLES:
                    _REPORTED_CYCLES.add(key)
                    new_cycles.append((cycle, h.stack, stack))
    for cycle, held_stack, acq_stack in new_cycles:
        sanitize.report_finding(
            "TPU007",
            "lock-order cycle witnessed at runtime: "
            + " -> ".join(f"'{n}'" for n in cycle),
            stacks=[held_stack, acq_stack],
        )
    return stack


def _after_acquire(lock, stack):
    held = _held()
    for h in held:
        if h.obj is lock._inner:
            h.count += 1
            return
    held.append(
        _Held(
            lock._inner,
            lock._name,
            stack or "".join(traceback.format_stack(limit=12)),
        )
    )


def _after_release(lock):
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i].obj is lock._inner:
            held[i].count -= 1
            if held[i].count <= 0:
                del held[i]
            return


class TrackedLock:
    """Witness wrapper around a ``threading.Lock``/``RLock``."""

    _is_tpusan_tracked = True

    def __init__(self, name: str, inner, reentrant: bool):
        self._name = name
        self._inner = inner
        self._reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1):
        stack = _before_acquire(self)
        got = self._inner.acquire(blocking, timeout)
        if got:
            _after_acquire(self, stack)
        return got

    def release(self):
        self._inner.release()
        _after_release(self)

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def __repr__(self):
        return f"TrackedLock({self._name!r}, {self._inner!r})"


class TrackedCondition:
    """Witness wrapper around a ``threading.Condition``.

    ``wait`` drops the held entry for its duration (the underlying
    condition releases the lock while waiting) and restores it on wakeup.
    """

    _is_tpusan_tracked = True
    _reentrant = True  # Condition's default lock is an RLock

    def __init__(self, name: str, inner: threading.Condition):
        self._name = name
        self._cond = inner
        # TrackedLock-shaped view over the condition's underlying lock so
        # the shared acquire/release bookkeeping applies unchanged.
        self._inner = inner._lock  # the RLock inside the Condition

    def acquire(self, *args):
        stack = _before_acquire(self)
        got = self._cond.acquire(*args)
        if got:
            _after_acquire(self, stack)
        return got

    def release(self):
        self._cond.release()
        _after_release(self)

    def wait(self, timeout=None):
        _after_release(self)
        try:
            return self._cond.wait(timeout)
        finally:
            _after_acquire(self, None)

    def wait_for(self, predicate, timeout=None):
        _after_release(self)
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            _after_acquire(self, None)

    def notify(self, n: int = 1):
        self._cond.notify(n)

    def notify_all(self):
        self._cond.notify_all()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def __repr__(self):
        return f"TrackedCondition({self._name!r})"

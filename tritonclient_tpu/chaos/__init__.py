"""tpuchaos — deterministic, seeded fault injection at named choke points.

The third tier of the lint→witness ladder: **tpulint** proves the
invariants statically, **tpusan** witnesses them under execution, and
**tpuchaos** witnesses them under *injected failure* — the only way to
prove the resilience layer (retries, breakers, failover, crash
recovery) actually holds the availability the fleet tier promises.

Activation mirrors tpusan: ``TPUCHAOS=<seed>:<plan>`` in the
environment (parsed at first import), or programmatic
:func:`enable`/:func:`session`. **Zero overhead when off**: the choke
points call :func:`fire`, whose first instruction is a module-flag
check, and :func:`operation` returns a shared no-op context manager.

Choke points are *named sites* instrumented in the protocol clients,
the fleet router, and the shm paths (each spells its site once as a
module constant and calls ``chaos.fire(SITE)``):

=============================  ==============================================
site                           where it fires
=============================  ==============================================
``http.connect``               client HTTP connection establishment
``http.send``                  client HTTP request write (headers+body)
``http.response``              client HTTP response read (post-send)
``grpc.call``                  client gRPC unary invocation
``fleet.exchange.connect``     router→replica connection checkout
``fleet.exchange.send``        router→replica proxied request write
``fleet.exchange.response``    router→replica proxied response read
``shm.register``               shared-memory region create/register (mmap)
=============================  ==============================================

Faults (see ``_plan.FAULTS``) raise the exception the real failure
would (``ConnectionRefusedError``, ``ConnectionResetError`` for
RST/mid-response FIN, ``BrokenPipeError`` for partial writes,
``socket.timeout``, gRPC ``UNAVAILABLE``, ``OSError(ENOMEM)``), inject
latency, or — via :class:`~tritonclient_tpu.chaos._controller.
ChaosController` — SIGKILL/SIGSTOP replica subprocesses.

Every injection is recorded ``{seq, site, fault, rule, op, survived}``;
wrapping a logical operation in ``with chaos.operation("infer")`` marks
its injections **survived** when the operation completes without
raising — that is the report's "N faults injected, M survived"
arithmetic tests and the CI chaos lane assert on.
:func:`write_report` renders JSON (or SARIF for ``.sarif`` paths,
merging with the tpulint/tpusan code-scanning streams).
"""

import errno
import json
import os
import socket
import threading
import time
from typing import List, Optional

from tritonclient_tpu import sanitize
from tritonclient_tpu.chaos._plan import (  # noqa: F401
    FAULT_ENOMEM,
    FAULT_LATENCY,
    FAULT_PARTIAL,
    FAULT_REFUSED,
    FAULT_RESET,
    FAULT_SIGKILL,
    FAULT_SIGSTOP,
    FAULT_TIMEOUT,
    FAULT_UNAVAILABLE,
    FAULTS,
    Plan,
    PlanError,
    Rule,
    parse_plan,
)

__all__ = [
    "ChaosInjection",
    "Plan",
    "PlanError",
    "active",
    "disable",
    "enable",
    "fire",
    "injections",
    "operation",
    "session",
    "summary",
    "write_report",
]

#: Canonical site names (spelled once here; choke points import them).
SITE_HTTP_CONNECT = "http.connect"
SITE_HTTP_SEND = "http.send"
SITE_HTTP_RESPONSE = "http.response"
SITE_GRPC_CALL = "grpc.call"
SITE_FLEET_CONNECT = "fleet.exchange.connect"
SITE_FLEET_SEND = "fleet.exchange.send"
SITE_FLEET_RESPONSE = "fleet.exchange.response"
#: Per-replica proxy site: the full name is this prefix + the replica
#: name (``fleet.exchange.replica.r2``), so a plan can fault ONE
#: replica's traffic — the cohort-drill lever (inject latency into the
#: canary cohort only, leave the baseline clean).
SITE_FLEET_REPLICA_PREFIX = "fleet.exchange.replica."
SITE_SHM_REGISTER = "shm.register"


class ChaosInjection(Exception):
    """Mixin marker carried by every chaos-raised exception so reports
    and tests can tell an injected fault from an organic one."""


class ChaosConnectionRefused(ChaosInjection, ConnectionRefusedError):
    pass


class ChaosConnectionReset(ChaosInjection, ConnectionResetError):
    pass


class ChaosBrokenPipe(ChaosInjection, BrokenPipeError):
    pass


class ChaosTimeout(ChaosInjection, socket.timeout):
    pass


class ChaosOSError(ChaosInjection, OSError):
    pass


class _State:
    def __init__(self):
        self.active = False
        self.plan: Optional[Plan] = None
        self.started_at = 0.0
        self.lock = sanitize.named_lock("chaos._State.lock")
        self.records: List[dict] = []
        self.seq = 0
        self.tls = threading.local()  # per-thread operation stack


_STATE = _State()


def active() -> bool:
    return _STATE.active


def enable(seed: int = 0, plan: str = ""):
    """Activate injection with a seeded plan (idempotent re-arm: a
    second enable replaces the plan and resets counters/records)."""
    with _STATE.lock:
        _STATE.plan = plan if isinstance(plan, Plan) else Plan(plan, seed)
        _STATE.plan.reseed()
        _STATE.records = []
        _STATE.seq = 0
        _STATE.started_at = time.monotonic()
        _STATE.active = True


def disable():
    with _STATE.lock:
        _STATE.active = False
        _STATE.plan = None


class session:
    """``with chaos.session(seed, plan):`` — enable for a block, always
    disable after (test-friendly)."""

    def __init__(self, seed: int = 0, plan: str = ""):
        self._seed = seed
        self._plan = plan

    def __enter__(self):
        enable(self._seed, self._plan)
        return self

    def __exit__(self, *exc):
        disable()
        return False


# -- operations (survival tracking) ----------------------------------------- #


class _NoOp:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoOp()


class _Operation:
    """One logical client operation; injections fired on this thread
    while it is open belong to it. Exiting cleanly marks them survived."""

    __slots__ = ("name", "injection_seqs")

    def __init__(self, name: str):
        self.name = name
        self.injection_seqs: List[int] = []

    def __enter__(self):
        stack = getattr(_STATE.tls, "ops", None)
        if stack is None:
            stack = _STATE.tls.ops = []
        stack.append(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        stack = getattr(_STATE.tls, "ops", [])
        if stack and stack[-1] is self:
            stack.pop()
        if exc_type is None and self.injection_seqs:
            with _STATE.lock:
                seqs = set(self.injection_seqs)
                for record in _STATE.records:
                    if record["seq"] in seqs:
                        record["survived"] = True
        return False


def operation(name: str):
    """Scope one logical operation (an infer, a proxied exchange) for
    survived-fault accounting. No-op (shared object) when chaos is off."""
    if not _STATE.active:
        return _NOOP
    return _Operation(name)


# -- the choke-point hook ---------------------------------------------------- #


def _enact(rule: Rule):
    if rule.fault == FAULT_LATENCY:
        # Deliberate injected latency (the fault itself); chaos tests
        # never run this on an event loop thread.
        time.sleep(rule.ms / 1000.0)  # tpulint: disable=TPU001
        return
    if rule.fault == FAULT_REFUSED:
        raise ChaosConnectionRefused(
            errno.ECONNREFUSED, f"tpuchaos[{rule.site}]: injected connection refused"
        )
    if rule.fault == FAULT_RESET:
        raise ChaosConnectionReset(
            errno.ECONNRESET, f"tpuchaos[{rule.site}]: injected connection reset"
        )
    if rule.fault == FAULT_PARTIAL:
        raise ChaosBrokenPipe(
            errno.EPIPE, f"tpuchaos[{rule.site}]: injected partial write"
        )
    if rule.fault == FAULT_TIMEOUT:
        raise ChaosTimeout(f"tpuchaos[{rule.site}]: injected timeout")
    if rule.fault == FAULT_ENOMEM:
        raise ChaosOSError(
            errno.ENOMEM, f"tpuchaos[{rule.site}]: injected mmap failure"
        )
    if rule.fault == FAULT_UNAVAILABLE:
        raise _grpc_unavailable(rule.site)
    # sigkill/sigstop rules are controller-enacted; firing one at an
    # in-process site is a plan mistake — surface it loudly.
    raise PlanError(
        f"fault '{rule.fault}' at in-process site '{rule.site}' "
        "is controller-enacted (sigkill/sigstop name a replica site)"
    )


def _grpc_unavailable(site: str):
    import grpc

    class _ChaosRpcError(ChaosInjection, grpc.RpcError):
        """Duck-types the surface the clients read (code/details)."""

        def code(self):
            return grpc.StatusCode.UNAVAILABLE

        def details(self):
            return f"tpuchaos[{site}]: injected channel breakage"

        def __str__(self):
            return self.details()

    return _ChaosRpcError()


def fire(site: str):
    """The choke-point hook: decide per matching rule, record, enact.

    When off this is one attribute load + branch. When a fault fires it
    raises (or sleeps, for latency) — the instrumented code treats the
    raise exactly like the organic failure it models.
    """
    if not _STATE.active:
        return
    with _STATE.lock:
        plan = _STATE.plan
        if plan is None:
            return
        elapsed = time.monotonic() - _STATE.started_at
        fired: Optional[Rule] = None
        for rule in plan.for_site(site):
            if rule.decide(elapsed) and fired is None:
                fired = rule
        if fired is None:
            return
        _STATE.seq += 1
        record = {
            "seq": _STATE.seq,
            "site": site,
            "fault": fired.fault,
            "rule": fired.spec(),
            "op": None,
            "survived": False,
        }
        _STATE.records.append(record)
    ops = getattr(_STATE.tls, "ops", None)
    if ops:
        record["op"] = ops[-1].name
        ops[-1].injection_seqs.append(record["seq"])
    _enact(fired)


def note_injection(site: str, fault: str, detail: str = ""):
    """Record an injection enacted OUTSIDE a choke point (the controller
    SIGKILLing a replica). Survival is the scenario's to assert."""
    if not _STATE.active:
        return None
    with _STATE.lock:
        _STATE.seq += 1
        record = {
            "seq": _STATE.seq,
            "site": site,
            "fault": fault,
            "rule": detail or f"{site}={fault}",
            "op": None,
            "survived": False,
        }
        _STATE.records.append(record)
    return record["seq"]


def mark_survived(seq: int):
    with _STATE.lock:
        for record in _STATE.records:
            if record["seq"] == seq:
                record["survived"] = True
                return


# -- reporting --------------------------------------------------------------- #


def injections() -> List[dict]:
    with _STATE.lock:
        return [dict(r) for r in _STATE.records]


def summary() -> dict:
    with _STATE.lock:
        records = list(_STATE.records)
        plan = _STATE.plan
    survived = sum(1 for r in records if r["survived"])
    by_site: dict = {}
    for r in records:
        site = by_site.setdefault(
            r["site"], {"injected": 0, "survived": 0}
        )
        site["injected"] += 1
        site["survived"] += 1 if r["survived"] else 0
    return {
        "tool": "tpuchaos",
        "seed": plan.seed_value if plan else None,
        "plan": plan.text if plan else "",
        "injected": len(records),
        "survived": survived,
        "by_site": by_site,
    }


def write_report(path: str):
    """Chaos report: SARIF 2.1.0 for ``.sarif`` paths (one result per
    distinct site+fault, merged alongside tpulint/tpusan in code
    scanning), JSON (full per-injection records) otherwise."""
    if path.endswith(".sarif"):
        from tritonclient_tpu.analysis._engine import Finding
        from tritonclient_tpu.analysis._sarif import render_sarif

        seen = {}
        for r in injections():
            key = (r["site"], r["fault"])
            seen.setdefault(key, 0)
            seen[key] += 1
        findings = [
            Finding(
                "TPUCHAOS", site, 1, 0,
                f"injected fault '{fault}' x{count}",
            )
            for (site, fault), count in sorted(seen.items())
        ]
        meta = [{
            "id": "TPUCHAOS",
            "name": "fault-injection",
            "shortDescription": {"text": "deterministic injected fault"},
        }]
        with open(path, "w", encoding="utf-8") as f:
            f.write(render_sarif(findings, meta, tool_name="tpuchaos"))
        return
    doc = summary()
    doc["faults"] = injections()
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")


# -- env activation (mirrors tpusan) ----------------------------------------- #


def _maybe_enable_from_env():
    raw = os.environ.get("TPUCHAOS", "").strip()
    if not raw or raw in ("0", "false", "off"):
        return
    seed_text, _, plan_text = raw.partition(":")
    try:
        seed = int(seed_text)
    except ValueError:
        seed, plan_text = 0, raw
    enable(seed, plan_text)


def env_seed(default: int = 42) -> int:
    """The seed named by ``TPUCHAOS`` (for scenarios that honor the CI
    lane's fixed seed), or ``default``."""
    raw = os.environ.get("TPUCHAOS", "").strip()
    seed_text = raw.partition(":")[0]
    try:
        return int(seed_text)
    except ValueError:
        return default


_maybe_enable_from_env()

"""ChaosController: process-level faults against real replica processes.

In-process choke points can fake transport failures, but a crashed
replica is not a fake — SIGKILL drops every in-flight request, resets
every connection, and erases all admin state (shm registrations,
repository loads, trace settings). The controller owns the replica
subprocesses (``python -m tritonclient_tpu.fleet.serve``) so chaos
scenarios can kill, wedge (SIGSTOP), resume, and **restart** them —
restart re-binds the SAME ports, which is what lets a router identify
the rejoined process as the replica it ejected and replay its journaled
admin state.

Usage::

    with ChaosController() as ctl:
        r0 = ctl.spawn("r0", service_ms=5)
        r1 = ctl.spawn("r1", service_ms=5)
        ... route traffic ...
        ctl.sigkill("r0")          # crash mid-flight (recorded as an injection)
        ... assert failover ...
        ctl.restart("r0")          # same ports; router replays admin state
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional

from tritonclient_tpu import chaos, sanitize

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


class ReplicaProcess:
    """One controller-owned replica subprocess and its respawn recipe."""

    __slots__ = ("name", "proc", "http_address", "grpc_address",
                 "service_ms", "model_set", "kills", "stops")

    def __init__(self, name: str, proc, http_address: str,
                 grpc_address: str, service_ms: float, model_set: str):
        self.name = name
        self.proc = proc
        self.http_address = http_address
        self.grpc_address = grpc_address
        self.service_ms = service_ms
        self.model_set = model_set
        self.kills = 0
        self.stops = 0

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.poll() is None


class ChaosController:
    """Spawn/kill/wedge/restart replica processes deterministically."""

    def __init__(self, spawn_timeout_s: float = 60.0,
                 env: Optional[dict] = None):
        self.spawn_timeout_s = float(spawn_timeout_s)
        self._env = dict(env) if env else dict(os.environ)
        # Replica processes must not inherit an ambient chaos plan: the
        # faults under test are the CONTROLLER's to inject.
        self._env.pop("TPUCHAOS", None)
        self._env.setdefault("JAX_PLATFORMS", "cpu")
        self._replicas: Dict[str, ReplicaProcess] = {}
        self._lock = sanitize.named_lock("chaos.ChaosController._lock")
        self._tmp = tempfile.mkdtemp(prefix="tpuchaos_")

    # -- lifecycle ------------------------------------------------------------

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate_all()
        return False

    def replicas(self) -> List[ReplicaProcess]:
        with self._lock:
            return list(self._replicas.values())

    def get(self, name: str) -> ReplicaProcess:
        with self._lock:
            return self._replicas[name]

    # -- spawn / respawn ------------------------------------------------------

    def _launch(self, name: str, service_ms: float, model_set: str,
                http_port: int = 0, grpc_port: int = 0) -> ReplicaProcess:
        address_file = os.path.join(self._tmp, f"{name}.json")
        if os.path.exists(address_file):
            os.unlink(address_file)
        cmd = [
            sys.executable, "-m", "tritonclient_tpu.fleet.serve",
            "--name", name,
            "--model-set", model_set,
            "--service-ms", str(service_ms),
            "--http-port", str(http_port),
            "--grpc-port", str(grpc_port),
            "--address-file", address_file,
        ]
        proc = subprocess.Popen(
            cmd, cwd=_REPO_ROOT, env=self._env,
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        )
        deadline = time.monotonic() + self.spawn_timeout_s
        doc = None
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"replica '{name}' exited rc={proc.returncode} "
                    "before publishing its addresses"
                )
            if os.path.exists(address_file):
                with open(address_file) as f:
                    doc = json.load(f)
                break
            # Sync spawn poll (controller threads only, never a loop).
            time.sleep(0.02)  # tpulint: disable=TPU001
        if doc is None:
            proc.kill()
            raise TimeoutError(f"replica '{name}' did not publish addresses")
        return ReplicaProcess(
            name, proc, doc["http"], doc["grpc"], service_ms, model_set
        )

    def spawn(self, name: str, service_ms: float = 5.0,
              model_set: str = "fleet") -> ReplicaProcess:
        replica = self._launch(name, service_ms, model_set)
        with self._lock:
            self._replicas[name] = replica
        return replica

    def restart(self, name: str,
                wait_ready_s: float = 30.0) -> ReplicaProcess:
        """Respawn a dead replica on the SAME ports it held before (so
        membership identifies it as the ejected replica rejoining)."""
        old = self.get(name)
        if old.alive():
            raise RuntimeError(f"replica '{name}' is still alive")
        old.proc.wait()
        http_port = int(old.http_address.rsplit(":", 1)[1])
        grpc_port = int(old.grpc_address.rsplit(":", 1)[1])
        fresh = self._launch(
            name, old.service_ms, old.model_set,
            http_port=http_port, grpc_port=grpc_port,
        )
        fresh.kills, fresh.stops = old.kills, old.stops
        with self._lock:
            self._replicas[name] = fresh
        self.wait_ready(name, timeout_s=wait_ready_s)
        return fresh

    def wait_ready(self, name: str, timeout_s: float = 30.0):
        from tritonclient_tpu.fleet._replica import http_call
        from tritonclient_tpu.protocol._literals import EP_HEALTH_READY

        replica = self.get(name)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                status, _ = http_call(
                    replica.http_address, "GET", EP_HEALTH_READY,
                    timeout_s=2.0,
                )
                if status == 200:
                    return
            except OSError:
                pass
            time.sleep(0.05)  # tpulint: disable=TPU001 (sync readiness poll)
        raise TimeoutError(f"replica '{name}' not ready in {timeout_s}s")

    # -- faults ---------------------------------------------------------------

    def sigkill(self, name: str):
        """SIGKILL the replica (recorded as a chaos injection at site
        ``replica.<name>``)."""
        replica = self.get(name)
        replica.kills += 1
        chaos.note_injection(f"replica.{name}", chaos.FAULT_SIGKILL)
        replica.proc.send_signal(signal.SIGKILL)
        replica.proc.wait(timeout=10)

    def sigstop(self, name: str):
        """Wedge the replica (alive but not scheduling — the slow/hung
        failure mode health probes must distinguish from dead)."""
        replica = self.get(name)
        replica.stops += 1
        chaos.note_injection(f"replica.{name}", chaos.FAULT_SIGSTOP)
        replica.proc.send_signal(signal.SIGSTOP)

    def sigcont(self, name: str):
        self.get(name).proc.send_signal(signal.SIGCONT)

    def terminate_all(self):
        with self._lock:
            replicas = list(self._replicas.values())
            self._replicas.clear()
        for replica in replicas:
            if replica.alive():
                replica.proc.send_signal(signal.SIGCONT)  # unwedge first
                replica.proc.terminate()
        for replica in replicas:
            try:
                replica.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                replica.proc.kill()
                replica.proc.wait(timeout=10)

"""The tpuchaos schedule DSL: which fault fires at which site, when.

A plan is a ``;``-separated list of rules::

    http.response=reset@nth=3; http.connect=refused@p=0.05;
    fleet.exchange.response=latency@ms=40@every=7@until=2.5

Each rule is ``<site>=<fault>`` followed by ``@key=value`` triggers:

=========  =================================================================
key        meaning
=========  =================================================================
``p``      fire with this probability per call (seeded RNG — deterministic)
``nth``    fire on exactly the Nth call to the site (1-based)
``every``  fire on every Nth call (1-based phase: call N, 2N, ...)
``after``  only fire at/after this many seconds since enable()
``until``  only fire strictly before this many seconds since enable()
``ms``     fault parameter: injected latency in milliseconds
``max``    stop after this many injections from this rule
=========  =================================================================

With no ``p``/``nth``/``every`` trigger the rule fires on EVERY call in
its time window. Fault names are validated here (:data:`FAULTS`) so a
typo fails at parse time, not silently never-fires. Site names are free
identifiers — the choke points in clients/router/shm spell theirs as
module constants; :func:`tritonclient_tpu.chaos.fire` matches by exact
site, with a rule site of ``*`` matching every choke point.

Determinism: every probabilistic decision draws from a per-rule
``random.Random`` seeded from ``(plan seed, rule index)``, and counters
are per-rule — the same seed + plan + call sequence injects the same
faults, which is what lets CI assert byte-identical chaos reports
across runs.
"""

import random
from typing import List, Optional

#: Fault kinds the injector can enact. Process-level faults
#: (``sigkill``/``sigstop``) are enacted by the ChaosController against
#: replica subprocesses it owns; everything else is enacted in-process
#: at a choke point.
FAULT_REFUSED = "refused"        # ConnectionRefusedError at connect
FAULT_RESET = "reset"            # ConnectionResetError (peer RST / mid-response FIN)
FAULT_PARTIAL = "partial"        # BrokenPipeError after a partial write
FAULT_TIMEOUT = "timeout"        # socket.timeout (slow/partial I/O bound hit)
FAULT_LATENCY = "latency"        # sleep ``ms`` then continue (no error)
FAULT_UNAVAILABLE = "unavailable"  # gRPC UNAVAILABLE (channel/stream breakage)
FAULT_ENOMEM = "enomem"          # OSError(ENOMEM) — shm mmap/register failure
FAULT_SIGKILL = "sigkill"        # controller: SIGKILL the target replica
FAULT_SIGSTOP = "sigstop"        # controller: SIGSTOP (wedge) the target replica

FAULTS = frozenset({
    FAULT_REFUSED,
    FAULT_RESET,
    FAULT_PARTIAL,
    FAULT_TIMEOUT,
    FAULT_LATENCY,
    FAULT_UNAVAILABLE,
    FAULT_ENOMEM,
    FAULT_SIGKILL,
    FAULT_SIGSTOP,
})


class PlanError(ValueError):
    """A plan string that does not parse (bad fault, bad trigger key)."""


class Rule:
    """One parsed plan rule plus its runtime trigger state."""

    __slots__ = (
        "site", "fault", "p", "nth", "every", "after_s", "until_s",
        "ms", "max_count", "index", "_rng", "calls", "injections",
    )

    def __init__(self, site: str, fault: str, index: int = 0,
                 p: Optional[float] = None, nth: Optional[int] = None,
                 every: Optional[int] = None,
                 after_s: Optional[float] = None,
                 until_s: Optional[float] = None,
                 ms: float = 0.0, max_count: Optional[int] = None):
        if fault not in FAULTS:
            raise PlanError(
                f"unknown fault '{fault}' (have: {', '.join(sorted(FAULTS))})"
            )
        self.site = site
        self.fault = fault
        self.index = index
        self.p = p
        self.nth = nth
        self.every = every
        self.after_s = after_s
        self.until_s = until_s
        self.ms = ms
        self.max_count = max_count
        self._rng: Optional[random.Random] = None
        self.calls = 0
        self.injections = 0

    def seed(self, plan_seed: int):
        """(Re)seed this rule's RNG and reset counters — called by
        ``Plan.seed`` at enable time so a plan object can be reused."""
        self._rng = random.Random((plan_seed << 8) ^ self.index)
        self.calls = 0
        self.injections = 0

    def matches(self, site: str) -> bool:
        return self.site == "*" or self.site == site

    def decide(self, elapsed_s: float) -> bool:
        """One call at a matching site: count it, and say whether this
        rule fires. Counters advance even outside the time window so
        ``nth``/``every`` stay call-indexed, not window-indexed."""
        self.calls += 1
        if self.max_count is not None and self.injections >= self.max_count:
            return False
        if self.after_s is not None and elapsed_s < self.after_s:
            return False
        if self.until_s is not None and elapsed_s >= self.until_s:
            return False
        if self.nth is not None:
            fire = self.calls == self.nth
        elif self.every is not None:
            fire = self.calls % self.every == 0
        elif self.p is not None:
            if self._rng is None:
                self.seed(0)
            fire = self._rng.random() < self.p
        else:
            fire = True
        if fire:
            self.injections += 1
        return fire

    def spec(self) -> str:
        parts = [f"{self.site}={self.fault}"]
        for key, value in (
            ("p", self.p), ("nth", self.nth), ("every", self.every),
            ("after", self.after_s), ("until", self.until_s),
            ("ms", self.ms or None), ("max", self.max_count),
        ):
            if value is not None:
                parts.append(f"{key}={value:g}" if isinstance(value, float)
                             else f"{key}={value}")
        return "@".join(parts)


_INT_KEYS = {"nth", "every", "max"}
_FLOAT_KEYS = {"p", "after", "until", "ms"}


def parse_plan(text: str) -> List[Rule]:
    """Parse a plan string into rules (empty string = no rules)."""
    rules: List[Rule] = []
    for chunk in (text or "").split(";"):
        chunk = chunk.strip()
        if not chunk:
            continue
        head, *mods = chunk.split("@")
        site, sep, fault = head.partition("=")
        if not sep or not site.strip() or not fault.strip():
            raise PlanError(f"rule '{chunk}' is not '<site>=<fault>[@k=v]'")
        kwargs = {}
        for mod in mods:
            key, sep, value = mod.partition("=")
            key = key.strip()
            if not sep:
                raise PlanError(f"trigger '{mod}' is not 'key=value'")
            try:
                if key in _INT_KEYS:
                    parsed = int(value)
                elif key in _FLOAT_KEYS:
                    parsed = float(value)
                else:
                    raise PlanError(
                        f"unknown trigger key '{key}' in '{chunk}'"
                    )
            except ValueError:
                raise PlanError(
                    f"trigger '{mod}': value does not parse"
                ) from None
            kwargs[{"after": "after_s", "until": "until_s",
                    "max": "max_count"}.get(key, key)] = parsed
        rules.append(Rule(site.strip(), fault.strip(),
                          index=len(rules), **kwargs))
    return rules


class Plan:
    """A parsed plan: rules + the seed that makes it deterministic."""

    def __init__(self, text: str = "", seed: int = 0):
        self.text = text or ""
        self.seed_value = int(seed)
        self.rules = parse_plan(self.text)
        self.reseed()

    def reseed(self):
        for rule in self.rules:
            rule.seed(self.seed_value)

    def for_site(self, site: str) -> List[Rule]:
        return [r for r in self.rules if r.matches(site)]

    def process_rules(self) -> List[Rule]:
        """Rules enacted by the ChaosController (sigkill/sigstop) rather
        than an in-process choke point; their site names the replica."""
        return [
            r for r in self.rules
            if r.fault in (FAULT_SIGKILL, FAULT_SIGSTOP)
        ]

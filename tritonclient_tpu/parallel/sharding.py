"""Rule-based parameter sharding.

Models publish partition rules as ``[(path_regex, PartitionSpec), ...]``;
`tree_shardings` resolves them against a parameter pytree so the train/infer
steps can `jax.device_put` / annotate with `NamedSharding`s and let GSPMD
insert the collectives (the scaling-book recipe: pick a mesh, annotate,
let XLA do the rest).
"""

import re
from typing import List, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Rules = Sequence[Tuple[str, P]]


def _path_str(path) -> str:
    parts = []
    for entry in path:
        if hasattr(entry, "key"):
            parts.append(str(entry.key))
        elif hasattr(entry, "idx"):
            parts.append(str(entry.idx))
        else:  # pragma: no cover
            parts.append(str(entry))
    return "/".join(parts)


def _filter_spec(spec: P, mesh: Mesh) -> P:
    """Drop mesh axes of size 1 or absent from the mesh (no-op shardings)."""

    def keep(axis):
        if axis is None:
            return None
        if isinstance(axis, (tuple, list)):
            kept = tuple(a for a in axis if mesh.shape.get(a, 1) > 1)
            return kept if kept else None
        return axis if mesh.shape.get(axis, 1) > 1 else None

    return P(*(keep(a) for a in spec))


def spec_for_path(path: str, rules: Rules, default: P = P()) -> P:
    """First rule whose regex matches (re.search) the '/'-joined path wins."""
    for pattern, spec in rules:
        if re.search(pattern, path):
            return spec
    return default


def tree_shardings(mesh: Mesh, tree, rules: Rules, default: P = P()):
    """A pytree of NamedShardings matching ``tree``'s structure."""

    def resolve(path, leaf):
        spec = spec_for_path(_path_str(path), rules, default)
        return NamedSharding(mesh, _filter_spec(spec, mesh))

    return jax.tree_util.tree_map_with_path(resolve, tree)


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, _filter_spec(P(*spec), mesh))


def shard_tree(mesh: Mesh, tree, rules: Rules, default: P = P()):
    """device_put every leaf according to its matched rule."""
    return jax.device_put(tree, tree_shardings(mesh, tree, rules, default))


def init_sharded(mesh: Mesh, init_fn, rules: Rules, *args,
                 default: P = P()):
    """Materialize ``init_fn(*args)``'s tree DIRECTLY into its rule-
    assigned shardings (jit + out_shardings).

    Staging the full unsharded tree on one device and then device_put-ing
    it (eager init + ``shard_tree``) OOMs exactly the model sizes a mesh
    exists for; under jit the leaves are created sharded from the start.
    JAX's PRNG is deterministic under jit, so results are value-identical
    to the eager path (asserted by the engine/serving parity tests).
    """
    abstract = jax.eval_shape(init_fn, *args)
    return jax.jit(
        init_fn,
        out_shardings=tree_shardings(mesh, abstract, rules, default=default),
    )(*args)

"""TPU-native parallelism: device meshes, sharding rules, sequence parallelism.

The reference repo has no multi-device code (SURVEY.md §2.5) — its
"distributed backend" is the client↔server wire plane. For the TPU-native
framework, scale-out is first-class: models shard over a
``jax.sharding.Mesh`` (dp/fsdp/tp/sp axes), XLA GSPMD inserts collectives
from `NamedSharding` annotations, and long sequences run either ring
attention (`ppermute` over the sp axis) or Ulysses all-to-all attention,
both inside a partial-manual `jax.shard_map`.
"""

from tritonclient_tpu.parallel.mesh import AXIS_ORDER, auto_mesh, build_mesh
from tritonclient_tpu.parallel.multihost import (
    hybrid_mesh,
    initialize,
    process_local_batch,
)
from tritonclient_tpu.parallel.overlap import (
    calibrate_collective_us,
    make_row_parallel_proj,
    row_parallel_proj,
)
from tritonclient_tpu.parallel.ring_attention import ring_attention
from tritonclient_tpu.parallel.sharding import (
    named_sharding,
    shard_tree,
    spec_for_path,
    tree_shardings,
)
from tritonclient_tpu.parallel.ulysses import ulysses_attention

__all__ = [
    "AXIS_ORDER",
    "auto_mesh",
    "build_mesh",
    "calibrate_collective_us",
    "hybrid_mesh",
    "make_row_parallel_proj",
    "row_parallel_proj",
    "initialize",
    "named_sharding",
    "process_local_batch",
    "ring_attention",
    "shard_tree",
    "spec_for_path",
    "tree_shardings",
    "ulysses_attention",
]

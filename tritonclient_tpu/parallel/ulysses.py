"""Ulysses (all-to-all) sequence parallelism: the ring-attention alternative.

Where ring attention rotates K/V chunks around the sp axis (sp_size
ppermute hops, each overlappable with compute), Ulysses re-lays the
problem out with two all-to-alls: heads scatter across the sp axis while
the sequence gathers, every device runs *full-sequence* attention over its
head subset, and the inverse all-to-all restores sequence sharding. Two
collectives total, both riding ICI, independent of sequence length — the
better trade when num_heads >= sp_size and the sequence fits one chip's
HBM after the head split; ring attention wins when it does not.

The reference has no analog (client SDK, SURVEY.md §2.5); this is the
second leg of the long-context plane, with the same signature and
sharding contract as ring_attention so callers can switch per workload.
"""

import math
from typing import Optional

import jax
from jax import lax
from jax.sharding import Mesh

from tritonclient_tpu import _stepscope
from tritonclient_tpu.ops.attention import dot_product_attention
from tritonclient_tpu.parallel.ring_attention import sequence_shard_map


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    sp_axis: str = "sp",
    causal: bool = False,
    scale: Optional[float] = None,
    impl: str = "reference",
) -> jax.Array:
    """Attention over [B, L, H, D] tensors whose L dim is sharded on sp_axis.

    Requires H divisible by the sp axis size (each device owns H/sp heads
    during the compute phase). Other mesh axes (dp on B) stay automatic
    under GSPMD. With sp size 1 this degrades to plain attention.
    ``impl='flash'`` runs the full-sequence compute phase through the fused
    Pallas kernel (forward and backward) instead of the materializing einsum.
    """
    if impl not in ("reference", "flash"):
        raise ValueError("impl must be 'reference' or 'flash'")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if impl == "flash":
        from tritonclient_tpu.ops.flash_attention import flash_attention

        attn = lambda a, b, c: flash_attention(a, b, c, causal=causal,
                                               scale=scale)
    else:
        attn = lambda a, b, c: dot_product_attention(a, b, c, causal=causal,
                                                     scale=scale)
    sp_size = mesh.shape.get(sp_axis, 1)
    if sp_size == 1:
        return attn(q, k, v)
    num_heads = q.shape[2]
    if num_heads % sp_size != 0:
        raise ValueError(
            f"ulysses_attention needs heads ({num_heads}) divisible by the "
            f"'{sp_axis}' axis size ({sp_size}); use ring_attention otherwise"
        )

    def body(q_loc, k_loc, v_loc):
        # [B, L/sp, H, D] -> [B, L, H/sp, D]: scatter heads, gather sequence.
        def to_heads(x):
            # stepscope collective note: fires at trace time, charging
            # the step that triggered compilation.
            _stepscope.note_collective(
                "all_to_all", nbytes=int(x.size) * x.dtype.itemsize
            )
            return lax.all_to_all(
                x, sp_axis, split_axis=2, concat_axis=1, tiled=True
            )

        qh, kh, vh = to_heads(q_loc), to_heads(k_loc), to_heads(v_loc)
        out = attn(qh, kh, vh)
        # [B, L, H/sp, D] -> [B, L/sp, H, D]: gather heads, scatter sequence.
        _stepscope.note_collective(
            "all_to_all", nbytes=int(out.size) * out.dtype.itemsize
        )
        return lax.all_to_all(
            out, sp_axis, split_axis=1, concat_axis=2, tiled=True
        )

    return sequence_shard_map(body, mesh, sp_axis)(q, k, v)

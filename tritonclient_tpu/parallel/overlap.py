"""Compute/collective overlap for tensor-parallel projections.

The gpt PARTITION_RULES row-shard the attention output projection (``wo``)
and the FFN down projection (``w_out``) on the tp axis, which forces one
all-reduce per projection: ``y = psum(x_local @ w_local)``. Under plain
GSPMD that psum is a single launch whose full ``[n, d_out]`` payload sits
on the step critical path between the two matmuls of adjacent blocks.

``row_parallel_proj`` restructures the projection the way
Triton-distributed tiles it (arxiv 2504.19442): split the *output* dim
into C chunks and issue ``matmul(chunk i) → psum(chunk i) → matmul(chunk
i+1) → …`` inside a partial-manual ``jax.shard_map`` region. Because each
chunk's all-reduce is issued before the next chunk's matmul, XLA's async
collectives (all-reduce start/done pairs on TPU) can run the wire transfer
of chunk *i* under the MXU work of chunk *i+1* — only the trailing chunk's
collective is structurally exposed. Chunking the output dim (not the
contraction dim) keeps total all-reduce bytes identical to the unchunked
projection and keeps per-element accumulation order unchanged, so decode
token streams are unaffected.

The stepscope side: ``_stepscope.expected_tp_collectives(n_layers, tp,
overlap_chunks)`` counts the extra launches and
``_stepscope.expected_overlap_split`` says how many of them hide; the
engine charges calibrated exposed/hidden µs per step from those counts
(see ``GenerationEngine``). ``calibrate_collective_us`` measures the
per-launch all-reduce cost once on the live mesh.
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tritonclient_tpu import _stepscope


def _partial_shard_map(f, mesh: Mesh, in_specs, out_specs, manual_axis: str):
    """Partial-manual shard_map (only ``manual_axis`` manual, other mesh
    axes stay under GSPMD) across the jax API generations: the top-level
    ``jax.shard_map`` (``axis_names``/``check_vma``) when present, else
    the ``jax.experimental`` form (``auto``/``check_rep``)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            axis_names={manual_axis}, check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    auto = frozenset(mesh.axis_names) - {manual_axis}
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False, auto=auto,
    )


def pick_chunks(d_out: int, tp: int, chunks: int) -> int:
    """Clamp a requested chunk count to what the geometry supports: each
    chunk must be a whole slice of the output dim. Returns 1 (no
    chunking) when tp is trivial or nothing divides."""
    if tp <= 1 or chunks <= 1:
        return 1
    chunks = int(chunks)
    while chunks > 1 and d_out % chunks != 0:
        chunks -= 1
    return max(chunks, 1)


# tpulint: hot-path
def row_parallel_proj(x, w, b, *, mesh: Mesh, axis: str = "tp",
                      chunks: int = 2, note: bool = True):
    """``x @ w + b`` with ``w`` row-sharded on ``axis``, issued as
    ``chunks`` matmul+psum pairs so the all-reduce on chunk *i* can
    execute under the matmul on chunk *i+1*.

    ``x`` is ``[n, d_in]`` with ``d_in`` sharded on ``axis`` (the
    activation produced by the preceding column-parallel matmul), ``w`` is
    ``[d_in, d_out]`` sharded on dim 0, ``b`` is replicated. The result is
    replicated. ``note=False`` skips the trace-time stepscope notes for
    callers (the engine) that charge structural per-step counts instead.
    """
    tp = mesh.shape.get(axis, 1)
    d_out = w.shape[-1]
    n_chunks = pick_chunks(d_out, tp, chunks)
    if n_chunks <= 1 and tp <= 1:
        return x @ w + b

    csz = d_out // n_chunks

    def body(xl, wl, bl):
        parts = []
        for c in range(n_chunks):
            part = xl @ lax.slice_in_dim(wl, c * csz, (c + 1) * csz, axis=1)
            if note:
                _stepscope.note_collective(
                    "psum", nbytes=int(part.size) * part.dtype.itemsize
                )
            # Issued before the next chunk's matmul: on TPU the async
            # all-reduce runs under it; only the last chunk is exposed.
            parts.append(lax.psum(part, axis))
        out = parts[0] if n_chunks == 1 else jnp.concatenate(parts, axis=-1)
        return out + bl

    return _partial_shard_map(
        body, mesh,
        in_specs=(P(None, axis), P(axis, None), P(None)),
        out_specs=P(None, None),
        manual_axis=axis,
    )(x, w, b)


def make_row_parallel_proj(mesh: Mesh, axis: str = "tp", chunks: int = 2,
                           note: bool = True):
    """Bind ``row_parallel_proj`` to a mesh as the ``proj_fn(x, w, b)``
    closure the gpt decode layer accepts."""

    def proj(x, w, b):
        return row_parallel_proj(x, w, b, mesh=mesh, axis=axis,
                                 chunks=chunks, note=note)

    return proj


# Run-once calibration (the engine caches the result): the jit build is
# per-mesh by design and the block_until_ready calls ARE the measurement.
# tpulint: disable=TPU010
def calibrate_collective_us(mesh: Mesh, shape, dtype=jnp.float32,
                            axis: str = "tp", reps: int = 20) -> float:
    """Median wall µs of one all-reduce of ``shape``/``dtype`` over the
    mesh's ``axis`` — the per-launch cost the engine multiplies by the
    structural counts of ``expected_overlap_split``. Returns 0.0 when the
    axis is trivial or the measurement fails (attribution degrades to
    counts-only, never breaks serving)."""
    if mesh.shape.get(axis, 1) <= 1:
        return 0.0
    try:
        fn = jax.jit(_partial_shard_map(
            lambda t: lax.psum(t, axis),
            mesh,
            in_specs=P(None),
            out_specs=P(None),
            manual_axis=axis,
        ))
        probe = jnp.zeros(shape, dtype)
        jax.block_until_ready(fn(probe))  # compile outside the clock
        samples = []
        for _ in range(max(int(reps), 3)):
            t0 = time.perf_counter_ns()
            jax.block_until_ready(fn(probe))
            samples.append((time.perf_counter_ns() - t0) / 1000.0)
        samples.sort()
        return samples[len(samples) // 2]
    except Exception:
        return 0.0


def overlap_chunks_from_env(default: int = 2) -> int:
    """Requested chunk count for the engine's overlap projections
    (``TPU_ENGINE_OVERLAP_CHUNKS``), before geometry clamping."""
    import os

    try:
        return max(int(os.environ.get("TPU_ENGINE_OVERLAP_CHUNKS",
                                      str(default))), 1)
    except ValueError:
        return default


def overlap_enabled_from_env(default: bool = True) -> bool:
    """``TPU_ENGINE_OVERLAP`` gate (default on; the projection only
    engages when the mesh actually has a tp axis > 1)."""
    import os

    raw = os.environ.get("TPU_ENGINE_OVERLAP", "").strip().lower()
    if raw in ("", None):
        return default
    return raw not in ("0", "off", "false", "no")

"""Multi-host (DCN-spanning) meshes and distributed runtime setup.

The reference's multi-node story is NCCL/MPI wired by the launcher; the
TPU-native equivalent is JAX's distributed runtime plus a hybrid mesh:
axes that cross hosts (dp, pp) ride DCN, axes within a slice (fsdp, sp,
tp) ride ICI. The scaling-book recipe made concrete:

  initialize()                          # once per process, from env or args
  mesh = hybrid_mesh(dcn={"dp": 2}, ici={"fsdp": 2, "tp": 4})
  batch = process_local_batch(mesh, global_shape, local_arrays, spec)

Everything degrades to single-process: initialize() is a no-op when no
coordinator is configured, and hybrid_mesh with dcn product 1 is a plain
build_mesh.
"""

import math
import os
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from tritonclient_tpu.parallel.mesh import order_axes

# Axes whose collectives tolerate DCN latency (gradient syncs, pipeline
# hops); everything else belongs on ICI within a slice.
DCN_FRIENDLY_AXES = ("dp", "pp")


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Bring up the JAX distributed runtime (idempotent, env-aware).

    Arguments default from JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
    JAX_PROCESS_ID (the knobs a launcher sets, playing the role of the
    reference's MPI environment); unset count/id stay None so JAX's
    cluster auto-detection (Cloud TPU, Slurm) still works. Returns True
    when the multi-process runtime is (or already was) initialized, False
    for the single-process no-op.
    """
    if jax.distributed.is_initialized():
        return True
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    if coordinator_address is None:
        return False
    if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["JAX_NUM_PROCESSES"])
    if process_id is None and "JAX_PROCESS_ID" in os.environ:
        process_id = int(os.environ["JAX_PROCESS_ID"])
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def hybrid_mesh(
    dcn: Dict[str, int],
    ici: Dict[str, int],
    devices: Optional[Sequence] = None,
) -> Mesh:
    """A mesh whose ``dcn`` axes span hosts and ``ici`` axes stay in-slice.

    ``dcn`` axes are laid out outermost and must be DCN-friendly; ``ici``
    axes are innermost. On real multi-process TPU the device grid comes
    from ``mesh_utils.create_hybrid_device_mesh`` (DCN-outermost AND
    ICI-torus-adjacent); single-process (including the 8-virtual-device
    CPU tests) an id-ordered reshape gives the same logical layout.
    """
    for name in dcn:
        if name not in DCN_FRIENDLY_AXES:
            raise ValueError(
                f"axis '{name}' must not cross DCN (latency-sensitive "
                f"collectives); DCN axes are {DCN_FRIENDLY_AXES}"
            )
    overlap = set(dcn) & set(ici)
    if overlap:
        raise ValueError(f"axes {sorted(overlap)} appear in both dcn and ici")
    devices = list(devices if devices is not None else jax.devices())
    dcn_total = math.prod(dcn.values()) if dcn else 1
    ici_total = math.prod(ici.values()) if ici else 1
    if dcn_total * ici_total != len(devices):
        raise ValueError(
            f"dcn {dict(dcn)} x ici {dict(ici)} needs "
            f"{dcn_total * ici_total} devices, have {len(devices)}"
        )
    multiprocess = jax.process_count() > 1
    if multiprocess:
        # The whole point of the split: ici axes must fit inside one
        # process's devices, dcn axes must match the process count.
        if ici_total != jax.local_device_count():
            raise ValueError(
                f"ici axes {dict(ici)} (product {ici_total}) must equal the "
                f"per-process device count {jax.local_device_count()}; a "
                "larger product would put latency-sensitive collectives on "
                "DCN"
            )
        if dcn_total != jax.process_count():
            raise ValueError(
                f"dcn axes {dict(dcn)} (product {dcn_total}) must equal the "
                f"process count {jax.process_count()}"
            )

    dcn_names = order_axes(dcn)
    ici_names = order_axes(ici)
    names = [*dcn_names, *ici_names]
    shape = [dcn[n] for n in dcn_names] + [ici[n] for n in ici_names]
    if multiprocess:
        from jax.experimental import mesh_utils

        # Physical-topology-aware layout: DCN axes map to process granules,
        # ICI axes to torus-adjacent devices within each granule. Both shape
        # arguments must carry one entry per logical axis, in the same order
        # (dcn axes first, size 1 on the ICI side, and vice versa) — the
        # result then already has the logical shape, so no reshape that
        # would interleave granules. Granules are processes (we validated
        # dcn_total against process_count above), which also keeps
        # single-slice multi-host topologies working.
        grid = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=[1] * len(dcn_names) + [ici[n] for n in ici_names],
            dcn_mesh_shape=[dcn[n] for n in dcn_names] + [1] * len(ici_names),
            devices=devices,
            process_is_granule=True,
        )
    else:
        grid = np.asarray(devices, dtype=object).reshape(shape)
    return Mesh(grid, tuple(names))


def process_local_batch(
    mesh: Mesh,
    global_shape: Sequence[int],
    local_arrays,
    spec: PartitionSpec,
) -> jax.Array:
    """Assemble a global jax.Array from this process's local shard(s).

    The multi-host data-loading contract: every process feeds only the
    rows its own devices hold (one array, or a list of per-device shards
    concatenated on the leading axis), and the result behaves as one
    global array under ``spec``. Single-process this is just device_put
    with the sharding (which is also how the CPU tests cover it).
    """
    sharding = NamedSharding(mesh, spec)
    if isinstance(local_arrays, (list, tuple)):
        local = np.concatenate([np.asarray(a) for a in local_arrays], axis=0)
    else:
        local = np.asarray(local_arrays)
    if jax.process_count() == 1:
        if tuple(local.shape) != tuple(global_shape):
            raise ValueError(
                f"single-process local data shape {local.shape} != global "
                f"shape {tuple(global_shape)}"
            )
        return jax.device_put(local, sharding)
    return jax.make_array_from_process_local_data(sharding, local, global_shape)

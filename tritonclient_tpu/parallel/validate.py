"""Mesh-serving validation flow shared by tests and the driver dry-run.

Serves a MESH-SHARDED BERT (params by partition rules, ring attention on
sp) through the full gRPC + mesh-spanning-shm-region stack and checks the
pooled output against the single-device model — the long-context serving
story end to end: tokens arrive sharded, the output parks back sharded,
nothing congregates on one chip (SURVEY §5.7/§5.8).
"""

from typing import Optional

import numpy as np


def serve_sharded_bert_roundtrip(mesh, seq_len: int = 64,
                                 rtol: float = 2e-4, atol: float = 2e-4,
                                 prefix: str = "msv") -> None:
    """Raises on any serving error or numeric mismatch."""
    import jax
    from jax.sharding import PartitionSpec as P

    import tritonclient_tpu.grpc as grpcclient
    import tritonclient_tpu.utils.tpu_shared_memory as tpushm
    from tritonclient_tpu.models import bert
    from tritonclient_tpu.server import InferenceServer

    cfg = bert.bert_tiny(seq_len=seq_len)
    sharded = bert.BertBaseModel(cfg=cfg, mesh=mesh)
    reference = bert.BertBaseModel(cfg=cfg)
    dp = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
    sp = mesh.shape.get("sp", 1)
    b, l = 2 * dp, min(max(8 * sp, 16), seq_len // sp * sp)
    x = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (b, l)
    ).astype(np.int32)
    ref = np.asarray(reference._fwd(reference._params, x))

    client: Optional[object] = None
    in_region = out_region = None
    with InferenceServer(models=[sharded], http=False) as server:
        try:
            client = grpcclient.InferenceServerClient(server.grpc_address)
            # Region layouts match the model's data sharding: batch on
            # dp(/fsdp), sequence on sp (input); batch only (output).
            in_region = tpushm.create_sharded_memory_region(
                f"{prefix}_in", x.nbytes, mesh,
                partition_spec=P(("dp",), "sp"),
            )
            out_bytes = b * cfg.d_model * 4
            out_region = tpushm.create_sharded_memory_region(
                f"{prefix}_out", out_bytes, mesh,
                partition_spec=P(("dp",), None),
            )
            client.register_tpu_shared_memory(
                f"{prefix}_in", tpushm.get_raw_handle(in_region), 0, x.nbytes
            )
            client.register_tpu_shared_memory(
                f"{prefix}_out", tpushm.get_raw_handle(out_region), 0,
                out_bytes,
            )
            # Park the tokens SHARDED over the mesh.
            tpushm.set_shared_memory_region_from_dlpack(
                in_region, [jax.device_put(x, in_region.sharding)]
            )
            inp = grpcclient.InferInput("INPUT_IDS", [b, l], "INT32")
            inp.set_shared_memory(f"{prefix}_in", x.nbytes, 0)
            out = grpcclient.InferRequestedOutput("POOLED_OUTPUT")
            out.set_shared_memory(f"{prefix}_out", out_bytes, 0)
            client.infer("bert_base", [inp], outputs=[out])
            # The parked output stays a sharded device array until read.
            parked = out_region._parked[0]
            assert hasattr(parked, "sharding"), type(parked)
            got = tpushm.get_contents_as_numpy(
                out_region, "FP32", (b, cfg.d_model), 0
            )
        finally:
            if client is not None:
                # Unregister before destroy: tearing down a region the
                # server still maps would leave a dangling registry entry
                # (TPU006 destroy-while-registered).
                try:
                    client.unregister_tpu_shared_memory()
                except Exception:
                    pass  # server may already be down; destroy regardless
            for region in (in_region, out_region):
                if region is not None:
                    tpushm.destroy_shared_memory_region(region)
            if client is not None:
                client.close()
    np.testing.assert_allclose(got, ref, rtol=rtol, atol=atol)

"""Ring attention: sequence-parallel attention over a mesh axis.

Each device holds a sequence chunk of Q/K/V; K/V blocks rotate around the
ring via `lax.ppermute` while a flash-style online softmax accumulates the
output, so attention over the full sequence never materializes on one chip
and the sp-axis collectives ride ICI. Runs inside a partial-manual
`jax.shard_map` (only the sp axis is manual; dp/tp stay under GSPMD).

The reference has no analog (client SDK, SURVEY.md §2.5); this is the
long-context plane the TPU framework needs for sequence lengths beyond one
chip's HBM.
"""

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tritonclient_tpu import _stepscope


def _noted_ppermute(x, axis_name, perm):
    """lax.ppermute + a stepscope collective note. The note fires at JAX
    trace time (once per compiled call site, on the thread whose step
    triggered compilation) — cheap attribution, not an execution count."""
    _stepscope.note_collective(
        "ppermute", nbytes=int(x.size) * x.dtype.itemsize
    )
    return lax.ppermute(x, axis_name, perm)


_NEG_BIG = -0.7 * float(jnp.finfo(jnp.float32).max)


def sequence_shard_map(body, mesh: Mesh, sp_axis: str):
    """Partial-manual shard_map over the sp axis for [B, L, H, D] q/k/v.

    Shared scaffolding of the sequence-parallel attention variants: only
    the sp axis is manual; dp/tp stay under GSPMD.
    """
    spec = P(None, sp_axis, None, None)
    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        axis_names={sp_axis},
        check_vma=False,
    )


def _ring_body_flash(q, k, v, *, axis_name: str, axis_size: int,
                     causal: bool, scale: float):
    """Flash variant: each hop runs the fused Pallas kernel on the local
    Q chunk against the visiting K/V chunk (``return_lse=True``), and the
    per-hop partials combine with the standard two-way logsumexp merge.
    Gradients flow through the kernel's LSE cotangent path, ppermute, and
    the combine, so ring-flash is differentiable end to end.
    """
    from tritonclient_tpu.ops.flash_attention import flash_attention

    my_idx = lax.axis_index(axis_name)
    b, lc, h, d = q.shape
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def full_hop(k_cur, v_cur):
        return flash_attention(q, k_cur, v_cur, causal=False, scale=scale,
                               return_lse=True)

    def diag_hop(k_cur, v_cur):
        # j == my_idx: the visiting chunk is this device's own K/V, so the
        # in-chunk causal mask is exactly the aligned q_pos >= k_pos mask.
        return flash_attention(q, k_cur, v_cur, causal=True, scale=scale,
                               return_lse=True)

    def skip_hop(k_cur, v_cur):
        # Entirely above the diagonal: weight exp(_NEG_BIG) == 0 in the merge.
        return (jnp.zeros_like(q), jnp.full((b, lc, h), _NEG_BIG,
                                            jnp.float32))

    def step(carry, i):
        o_acc, lse_acc, k_cur, v_cur = carry
        # After i hops each device holds the chunk that started (my_idx - i).
        j = (my_idx - i) % axis_size
        if causal:
            idx = jnp.where(j < my_idx, 0, jnp.where(j == my_idx, 1, 2))
            o_j, lse_j = lax.switch(idx, [full_hop, diag_hop, skip_hop],
                                    k_cur, v_cur)
        else:
            o_j, lse_j = full_hop(k_cur, v_cur)
        m = jnp.maximum(lse_acc, lse_j)
        w_acc = jnp.exp(lse_acc - m)
        w_j = jnp.exp(lse_j - m)
        denom = w_acc + w_j
        o_acc = (o_acc * w_acc[..., None]
                 + o_j.astype(jnp.float32) * w_j[..., None]) / denom[..., None]
        lse_acc = m + jnp.log(denom)
        k_next = _noted_ppermute(k_cur, axis_name, perm)
        v_next = _noted_ppermute(v_cur, axis_name, perm)
        return (o_acc, lse_acc, k_next, v_next), None

    o0 = jnp.zeros((b, lc, h, d), jnp.float32)
    lse0 = jnp.full((b, lc, h), _NEG_BIG, jnp.float32)
    (o, _, _, _), _ = lax.scan(step, (o0, lse0, k, v),
                               jnp.arange(axis_size))
    return o.astype(q.dtype)


def _ring_body(q, k, v, *, axis_name: str, axis_size: int, causal: bool,
               scale: float):
    """Manual-mode body: q/k/v are the local [B, Lc, H, D] chunks."""
    my_idx = lax.axis_index(axis_name)
    b, lq, h, d = q.shape
    lk = k.shape[1]
    qf = q.astype(jnp.float32) * scale

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def step(carry, i):
        o, m, l, k_cur, v_cur = carry
        # After i hops each device holds the chunk that started (my_idx - i).
        j = (my_idx - i) % axis_size
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_cur.astype(jnp.float32))
        if causal:
            q_pos = my_idx * lq + jnp.arange(lq)
            k_pos = j * lk + jnp.arange(lk)
            keep = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(keep[None, None], s, _NEG_BIG)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        if causal:
            p = jnp.where(keep[None, None], p, 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32)
        )
        k_next = _noted_ppermute(k_cur, axis_name, perm)
        v_next = _noted_ppermute(v_cur, axis_name, perm)
        return (o_new, m_new, l_new, k_next, v_next), None

    o0 = jnp.zeros((b, h, lq, d), jnp.float32)
    m0 = jnp.full((b, h, lq), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((b, h, lq), jnp.float32)
    (o, m, l, _, _), _ = lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(axis_size)
    )
    l = jnp.where(l == 0.0, 1.0, l)
    out = (o / l[..., None]).astype(q.dtype)
    return jnp.transpose(out, (0, 2, 1, 3))  # [B, Lq, H, D]


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    sp_axis: str = "sp",
    causal: bool = False,
    scale: Optional[float] = None,
    impl: str = "reference",
) -> jax.Array:
    """Attention over [B, L, H, D] tensors whose L dim is sharded on sp_axis.

    Other mesh axes (dp on B, tp on H) stay automatic — GSPMD shards them as
    annotated by the caller. With sp size 1 this degrades to plain attention.
    ``impl='flash'`` runs the fused Pallas kernel per hop (online softmax
    inside the chunk, logsumexp merge across chunks) instead of the
    materializing per-chunk einsum — the combination for long context, where
    neither the full sequence nor a chunk's score matrix fits HBM.
    """
    if impl not in ("reference", "flash"):
        raise ValueError("impl must be 'reference' or 'flash'")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    sp_size = mesh.shape.get(sp_axis, 1)
    if sp_size == 1:
        if impl == "flash":
            from tritonclient_tpu.ops.flash_attention import flash_attention

            return flash_attention(q, k, v, causal=causal, scale=scale)
        from tritonclient_tpu.ops.attention import dot_product_attention

        return dot_product_attention(q, k, v, causal=causal, scale=scale)
    body = functools.partial(
        _ring_body_flash if impl == "flash" else _ring_body,
        axis_name=sp_axis,
        axis_size=sp_size,
        causal=causal,
        scale=scale,
    )
    return sequence_shard_map(body, mesh, sp_axis)(q, k, v)

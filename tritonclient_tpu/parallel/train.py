"""Sharded training step for the BERT flagship.

Demonstrates the full multi-chip path the driver dry-runs: params laid out
by Megatron TP rules (+fsdp when the axis exists), batch on dp, sequence on
sp with ring attention, optimizer states sharded like their params, one
`jax.jit` train step with donated carries. GSPMD inserts every collective.
"""

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from tritonclient_tpu.models import bert
from tritonclient_tpu.parallel.ring_attention import ring_attention
from tritonclient_tpu.parallel.sharding import (
    named_sharding,
    shard_tree,
    tree_shardings,
)


def make_mlm_train_step(cfg: bert.BertConfig, mesh, learning_rate: float = 1e-4,
                        sequence_parallel_impl: str = "ring",
                        attention_impl: str = "reference"):
    """Returns (init_state, train_step).

    init_state(key) -> (params, opt_state), sharded over ``mesh``.
    train_step(params, opt_state, batch) -> (params, opt_state, loss); batch
    is {'tokens': [B, L] i32, 'labels': [B, L] i32} with B divisible by dp
    and L by sp. ``sequence_parallel_impl`` picks the sp-axis attention:
    'ring' (ppermute pipeline, any head count) or 'ulysses' (two
    all-to-alls, heads divisible by sp — see parallel/ulysses.py for the
    trade-off). ``attention_impl='flash'`` routes the per-device attention
    compute (inside ring hops / the Ulysses head phase, or single-device
    when sp=1) through the fused Pallas kernel, forward and backward.
    """
    if sequence_parallel_impl not in ("ring", "ulysses"):
        raise ValueError("sequence_parallel_impl must be 'ring' or 'ulysses'")
    if attention_impl not in ("reference", "flash"):
        raise ValueError("attention_impl must be 'reference' or 'flash'")
    optimizer = optax.adamw(learning_rate)
    rules = bert.PARTITION_RULES
    act_sharding = named_sharding(mesh, ("dp", "fsdp"), "sp", None)

    attention_fn = None
    if mesh.shape.get("sp", 1) > 1:
        if sequence_parallel_impl == "ring":
            attention_fn = functools.partial(ring_attention, mesh=mesh,
                                             impl=attention_impl)
        else:
            from tritonclient_tpu.parallel.ulysses import ulysses_attention

            attention_fn = functools.partial(ulysses_attention, mesh=mesh,
                                             impl=attention_impl)
    elif attention_impl == "flash":
        from tritonclient_tpu.ops.flash_attention import flash_attention

        attention_fn = functools.partial(flash_attention, causal=False)

    def loss_fn(params, batch):
        return bert.mlm_loss(
            params,
            batch,
            cfg,
            attention_fn=attention_fn,
            activation_spec=act_sharding,
        )

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    def init_state(key: jax.Array):
        params = bert.init_params(key, cfg)
        params = shard_tree(mesh, params, rules)
        opt_state = optimizer.init(params)
        # Optimizer moments mirror the param tree one level down, so the same
        # path rules resolve (spec_for_path uses re.search); scalars -> P().
        opt_state = jax.device_put(
            opt_state, tree_shardings(mesh, opt_state, rules, default=P())
        )
        return params, opt_state

    def make_batch(key: jax.Array, batch: int, seq: int) -> Dict:
        tok_key, lab_key = jax.random.split(key)
        data_sharding = named_sharding(mesh, ("dp", "fsdp"), "sp")
        tokens = jax.random.randint(tok_key, (batch, seq), 0, cfg.vocab_size,
                                    jnp.int32)
        labels = jax.random.randint(lab_key, (batch, seq), 0, cfg.vocab_size,
                                    jnp.int32)
        return {
            "tokens": jax.device_put(tokens, data_sharding),
            "labels": jax.device_put(labels, data_sharding),
        }

    return init_state, train_step, make_batch

"""Device-mesh construction.

Axis conventions (subset used as needed):
  dp    data parallel (batch)
  fsdp  fully-sharded data parallel (params sharded over the batch axis)
  pp    pipeline parallel (stages)
  sp    sequence/context parallel (ring attention over this axis)
  tp    tensor parallel (Megatron-style within layers)
  ep    expert parallel (MoE experts)

Shardings are laid out so the fast-moving axes (tp, sp) map to adjacent
devices — on real TPU slices those collectives then ride ICI, with dp/pp
outermost (DCN-friendly), per the scaling-book recipe.
"""

import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_ORDER = ("dp", "fsdp", "pp", "ep", "sp", "tp")


def order_axes(axes) -> list:
    """Axis names sorted by AXIS_ORDER (unknown names keep insertion order
    after the known ones) — the one place the ordering policy lives."""
    return sorted(
        axes,
        key=lambda n: AXIS_ORDER.index(n) if n in AXIS_ORDER else len(AXIS_ORDER),
    )


def build_mesh(
    axes: Dict[str, int],
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a Mesh from ``{axis_name: size}``; one size may be -1 (inferred).

    Axes are ordered by AXIS_ORDER (unknown names keep insertion order after
    the known ones) so tp/sp are innermost over adjacent devices.
    """
    devices = list(devices if devices is not None else jax.devices())
    sizes = {k: int(v) for k, v in axes.items()}
    wildcards = [k for k, v in sizes.items() if v == -1]
    if len(wildcards) > 1:
        raise ValueError(f"at most one axis may be -1, got {wildcards}")
    known = math.prod(v for v in sizes.values() if v != -1)
    if wildcards:
        if known == 0 or len(devices) % known:
            raise ValueError(
                f"cannot infer axis '{wildcards[0]}': {len(devices)} devices "
                f"not divisible by {known}"
            )
        sizes[wildcards[0]] = len(devices) // known
    total = math.prod(sizes.values())
    if total != len(devices):
        raise ValueError(
            f"mesh axes {sizes} require {total} devices, have {len(devices)}"
        )
    names = order_axes(sizes)
    grid = np.asarray(devices, dtype=object).reshape([sizes[n] for n in names])
    return Mesh(grid, tuple(names))


def auto_mesh(
    devices: Optional[Sequence] = None,
    prefer: Sequence[str] = ("dp", "tp"),
) -> Mesh:
    """Balanced factorization of the device count over ``prefer`` axes.

    The last axis in ``prefer`` gets the largest factor (innermost ⇒ ICI).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if len(prefer) == 1:
        return build_mesh({prefer[0]: n}, devices)
    # Split n = outer * inner with inner the largest divisor <= sqrt-balanced.
    inner = 1
    for d in range(int(math.isqrt(n)), 0, -1):
        if n % d == 0:
            inner = max(inner, n // d if n // d <= n else d)
            break
    outer = n // inner
    axes = {prefer[0]: outer, prefer[-1]: inner}
    for name in prefer[1:-1]:
        axes[name] = 1
    return build_mesh(axes, devices)

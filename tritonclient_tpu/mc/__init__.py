"""tpumc: deterministic schedule-space model checking.

The lint→witness ladder (tpulint → tpusan → tpuchaos) catches
concurrency bugs on schedules that *happen to occur*; tpumc is the rung
that *enumerates* schedules. It reuses the sanitizer's
``named_lock``/``named_rlock``/``named_condition`` factories as
schedule-control points: while a :class:`~tritonclient_tpu.mc._sched.
SchedulerController` is installed, those factories return virtual,
controller-owned primitives, a cooperative scheduler serializes the
model's threads, and the :class:`Explorer` enumerates interleavings
under a CHESS-style bounded-preemption budget (default 2) with
sleep-set/DPOR-lite pruning keyed on lock/field-access footprints.

Detected per schedule: deadlock (TPU007), lost wakeup (TPU011),
empty-lockset races over adopted ``note_field_access`` sites (TPU009),
harness-invariant violations (TPUMC1), and thread exceptions (TPUMC2).
Every finding embeds a replayable trace — ``{harness, seed,
preemption_budget, decisions}`` — that reproduces the schedule (and the
finding JSON) byte-identically, and findings ride the shared
``analysis/_sarif.py`` machinery into code scanning.

Harness models for the four scheduling cores live in
:mod:`tritonclient_tpu.mc._harnesses` (registry: :data:`HARNESSES`);
``scripts/tpumc.py`` is the CLI, ``run_static_checks.sh --modelcheck``
the CI entry point.

Worked example::

    from tritonclient_tpu import mc

    result = mc.run_harness("demo_lost_wakeup")
    trace = result.findings[0]["trace"]         # {seed, decisions, ...}
    replayed = mc.Explorer(
        mc.HARNESSES["demo_lost_wakeup"], name="demo_lost_wakeup"
    ).replay(trace)
    assert mc.findings_json(replayed) == mc.findings_json(result)
"""

from tritonclient_tpu.mc._explore import (
    ExploreResult,
    Explorer,
    Model,
    RULES_META,
    findings_json,
)
from tritonclient_tpu.mc._harnesses import (
    DEFAULT_HARNESSES,
    HARNESSES,
    SCHEDULE_BUDGETS,
    HarnessUnavailable,
    run_harness,
)
from tritonclient_tpu.mc._sched import McError, SchedulerController

__all__ = [
    "DEFAULT_HARNESSES",
    "ExploreResult",
    "Explorer",
    "HARNESSES",
    "HarnessUnavailable",
    "McError",
    "Model",
    "RULES_META",
    "SCHEDULE_BUDGETS",
    "SchedulerController",
    "findings_json",
    "run_harness",
]

"""Cooperative scheduler: the schedule-control half of tpumc.

The sanitizer's ``named_lock``/``named_rlock``/``named_condition``
factories are the repo's concurrency instrumentation points; while a
:class:`SchedulerController` is installed (``sanitize.set_schedule_
controller``), those factories hand back *schedule-controlled*
primitives instead of ``threading`` ones. Every visible operation —
lock acquire/release, cv wait/notify, an adopted ``note_field_access``
site — becomes a schedule point: the executing thread publishes the
operation it is about to perform and parks; the controller (driven by
``_explore.Explorer``) decides which thread runs next. Exactly one test
thread executes at any instant, so lock/condition state can be *virtual*
(owned by the controller, no real ``threading`` primitives under test):
enabledness, blocking, and wakeups are controller decisions, which is
what makes every interleaving reachable and every run replayable from a
decision list.

Threads park on per-thread gate events; the real GIL never interleaves
two test threads between schedule points. Code constructed or inspected
*outside* a registered test thread (model construction before the run,
invariant checks after it) uses the same primitives through a
single-threaded immediate path.
"""

import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

# tpulint: disable-file=TPU009 - controller state is serialized by
# construction: exactly one thread runs between go/ready Event handoffs,
# so no two accesses to the bookkeeping dicts ever overlap.

_MC_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(os.path.dirname(_MC_DIR))
_SAN_DIR = os.path.join(os.path.dirname(_MC_DIR), "sanitize")

#: Wall-clock bound on one thread's progress between two schedule
#: points. Tripping it means the code under test blocked on something
#: the controller does not manage (a real lock, a blocking queue get) —
#: a harness bug, surfaced as :class:`McError`, never silently hung.
STUCK_LIMIT_S = 30.0


class McError(RuntimeError):
    """Harness/controller protocol violation (not a model-checking
    finding): an uncontrolled thread touched a controlled primitive
    mid-run, or a thread blocked outside the controller's knowledge."""


class McAborted(BaseException):
    """Raised inside test threads to unwind them at teardown.

    Derives from ``BaseException`` so ``except Exception`` blocks in the
    code under test cannot swallow the unwind.
    """


def _call_site() -> Tuple[str, int]:
    """(repo-relative path, line) of the innermost frame outside the mc
    and sanitize packages — the project-code site an operation report
    should point at (mirrors ``sanitize._project_site``, but cheap: no
    stack formatting, just a frame walk)."""
    f = sys._getframe(1)
    fallback = None
    while f is not None:
        fn = f.f_code.co_filename
        # _harnesses.py is model code, not framework code: the demo
        # harnesses' seeded bugs live there and findings should point
        # at them.
        if fn.endswith("_harnesses.py") or not (
                fn.startswith(_MC_DIR) or fn.startswith(_SAN_DIR)):
            if fallback is None:
                fallback = f
            if fn.startswith(_REPO_ROOT + os.sep):
                path = os.path.relpath(fn, _REPO_ROOT).replace(os.sep, "/")
                return path, f.f_lineno
        f = f.f_back
    if fallback is not None:
        return fallback.f_code.co_filename, fallback.f_lineno
    return "<unknown>", 1


class Op:
    """One pending visible operation, published at a schedule point."""

    __slots__ = ("kind", "lock", "timeout", "n", "owner_id", "field",
                 "write", "label", "path", "line")

    def __init__(self, kind: str, lock=None, timeout=None, n: int = 0,
                 owner_id: int = 0, field: str = "", write: bool = False,
                 label: str = ""):
        self.kind = kind
        self.lock = lock
        self.timeout = timeout
        self.n = n
        self.owner_id = owner_id
        self.field = field
        self.write = write
        self.label = label
        self.path, self.line = _call_site()

    def footprint(self):
        """Hashable resource token set for the DPOR-lite dependence
        check. Lock-shaped ops key on the lock *instance*; field ops on
        (owner, field, write). A thread's "start" op conflicts with
        everything: where a thread begins relative to the others is
        always a real scheduling choice."""
        if self.kind == "start":
            return (("*", 0),)
        if self.lock is not None:
            return (("L", id(self.lock)),)
        if self.field:
            return (("F", self.owner_id, self.field, self.write),)
        return ()

    def describe(self) -> str:
        name = self.lock._name if self.lock is not None else None
        if self.kind in ("acquire", "acquire_timed", "try_acquire"):
            return f"acquiring lock '{name}'"
        if self.kind == "release":
            return f"releasing lock '{name}'"
        if self.kind == "wait_sleep":
            return f"entering wait on '{name}'"
        if self.kind == "wait_wake":
            how = "untimed" if self.timeout is None else "timed"
            return f"in {how} cv wait on '{name}'"
        if self.kind == "notify":
            return f"notifying '{name}'"
        if self.kind == "field":
            return f"accessing field '{self.label}'"
        return self.kind


def _dependent(fp_a, fp_b) -> bool:
    """Two operations conflict when they touch the same lock instance,
    or the same (owner, field) with at least one write. Everything else
    commutes — the sleep-set/DPOR-lite pruning ground."""
    for a in fp_a:
        for b in fp_b:
            if a[0] == "*" or b[0] == "*":
                return True
            if a[0] == "L" and b[0] == "L" and a[1] == b[1]:
                return True
            if (a[0] == "F" and b[0] == "F" and a[1:3] == b[1:3]
                    and (a[3] or b[3])):
                return True
    return False


class McLock:
    """Virtual schedule-controlled Lock/RLock (ownership lives on the
    controller's thread states, never a real ``threading`` primitive)."""

    _is_tpumc_controlled = True

    def __init__(self, ctl: "SchedulerController", name: str,
                 reentrant: bool):
        self._ctl = ctl
        self._name = name
        self._reentrant = reentrant
        self.owner: Optional[int] = None  # tid, or -1 for the immediate path
        self.count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if not blocking:
            return self._ctl.sched_point(Op("try_acquire", lock=self))
        if timeout is not None and timeout > 0:
            return self._ctl.sched_point(
                Op("acquire_timed", lock=self, timeout=timeout)
            )
        self._ctl.sched_point(Op("acquire", lock=self))
        return True

    def release(self):
        self._ctl.sched_point(Op("release", lock=self))

    def locked(self) -> bool:
        return self.owner is not None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False

    def __repr__(self):
        return f"McLock({self._name!r})"


class McCondition:
    """Virtual schedule-controlled Condition over an :class:`McLock`.

    ``wait`` is two schedule points: the always-enabled sleep step
    (release the lock, join the waiter queue) and the wake step (enabled
    once notified — or once the controller fires the timeout — and the
    lock is free again). The gap between them contains no user code.
    """

    _is_tpumc_controlled = True
    _reentrant = True

    def __init__(self, ctl: "SchedulerController", name: str):
        self._ctl = ctl
        self._name = name
        self._lock = McLock(ctl, name, reentrant=True)
        self.waiters: List[int] = []  # tids, FIFO

    @property
    def owner(self):
        return self._lock.owner

    def acquire(self, *args):
        return self._lock.acquire(*args)

    def release(self):
        self._lock.release()

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self._lock.release()
        return False

    def _require_owner(self, verb: str):
        ts = self._ctl.current()
        tid = ts.tid if ts is not None else -1
        if self._lock.owner != tid:
            raise RuntimeError(f"cannot {verb} on un-acquired lock")

    def wait(self, timeout=None):
        self._require_owner("wait")
        self._ctl.sched_point(Op("wait_sleep", lock=self, timeout=timeout))
        return self._ctl.sched_point(
            Op("wait_wake", lock=self, timeout=timeout)
        )

    def wait_for(self, predicate, timeout=None):
        result = predicate()
        while not result:
            got = self.wait(timeout)
            result = predicate()
            if not got:
                break
        return result

    def notify(self, n: int = 1):
        self._require_owner("notify")
        self._ctl.sched_point(Op("notify", lock=self, n=n))

    def notify_all(self):
        self.notify(n=1 << 30)

    def __repr__(self):
        return f"McCondition({self._name!r})"


class _TState:
    """One controlled thread: gate events + virtual blocking state."""

    __slots__ = ("tid", "name", "fn", "thread", "go", "ready", "pending",
                 "status", "exc", "op_result", "wakeable", "timeout_fired",
                 "saved_count", "held")

    def __init__(self, tid: int, name: str, fn):
        self.tid = tid
        self.name = name
        self.fn = fn
        self.thread: Optional[threading.Thread] = None
        self.go = threading.Event()
        self.ready = threading.Event()
        self.pending: Optional[Op] = None
        self.status = "new"  # new | parked | done
        self.exc: Optional[BaseException] = None
        self.op_result = None
        self.wakeable = False      # notified (or timeout fired) in a cv wait
        self.timeout_fired = False
        self.saved_count = 0       # lock recursion restored after the wait
        self.held: List[McLock] = []  # acquisition order (diagnostics/races)


class _FieldAccess:
    __slots__ = ("tid", "write", "locks", "path", "line")

    def __init__(self, tid, write, locks, path, line):
        self.tid = tid
        self.write = write
        self.locks = locks  # frozenset of held lock names
        self.path = path
        self.line = line


class SchedulerController:
    """Virtual lock/cv state + the park/grant protocol for one run."""

    def __init__(self):
        self.threads: List[_TState] = []
        self._by_ident: Dict[int, _TState] = {}
        self._aborting = False
        self._started = False
        #: (owner_id, field) -> (label, [_FieldAccess]) — the Eraser-lite
        #: table the race check intersects locksets over.
        self.accesses: Dict[Tuple[int, str], Tuple[str, List[_FieldAccess]]] = {}

    # -- factory surface consumed by sanitize ------------------------------- #

    def make_lock(self, name: str, reentrant: bool) -> McLock:
        return McLock(self, name, reentrant)

    def make_condition(self, name: str) -> McCondition:
        return McCondition(self, name)

    def field_access(self, owner, field: str, write: bool = True,
                     label: Optional[str] = None):
        ts = self.current()
        if ts is None:
            return  # setup/check phase: single-threaded, not a race site
        self.sched_point(Op(
            "field", owner_id=id(owner), field=field, write=write,
            label=label or f"{type(owner).__name__}.{field}",
        ))

    # -- thread protocol ----------------------------------------------------- #

    def current(self) -> Optional[_TState]:
        return self._by_ident.get(threading.get_ident())

    def sched_point(self, op: Op):
        ts = self.current()
        if ts is None:
            return self._immediate(op)
        if self._aborting:
            raise McAborted()
        ts.pending = op
        ts.ready.set()
        ts.go.wait()
        ts.go.clear()
        if self._aborting:
            raise McAborted()
        return ts.op_result

    def _immediate(self, op: Op):
        """Single-threaded execution for unregistered threads (model
        construction before the run, invariant checks after it)."""
        if self._started and any(t.status != "done" for t in self.threads):
            raise McError(
                "an uncontrolled thread reached a controlled primitive "
                "mid-run — harness models must prevent the code under "
                "test from spawning its own threads"
            )
        lock = op.lock
        if op.kind in ("acquire", "acquire_timed", "try_acquire"):
            base = lock._lock if isinstance(lock, McCondition) else lock
            if base.owner not in (None, -1):
                raise McError(
                    f"lock '{base._name}' still held by a finished test "
                    "thread at invariant time (lock leak)"
                )
            if base.owner == -1 and not base._reentrant:
                raise McError(
                    f"non-reentrant lock '{base._name}' re-acquired on "
                    "the immediate path"
                )
            base.owner = -1
            base.count += 1
            return True
        if op.kind == "release":
            base = lock._lock if isinstance(lock, McCondition) else lock
            base.count -= 1
            if base.count <= 0:
                base.owner, base.count = None, 0
            return None
        if op.kind == "notify":
            for tid in list(op.lock.waiters[:op.n]):
                self._threads_by_tid()[tid].wakeable = True
                op.lock.waiters.remove(tid)
            return None
        if op.kind in ("wait_sleep", "wait_wake"):
            raise McError("cv wait outside a controlled test thread")
        return None  # field/start: nothing to do single-threaded

    def _main(self, ts: _TState):
        self._by_ident[threading.get_ident()] = ts
        try:
            self.sched_point(Op("start"))
            ts.fn()
        except McAborted:
            pass
        except BaseException as e:  # noqa: BLE001 — becomes a finding
            ts.exc = e
        finally:
            ts.status = "done"
            ts.pending = None
            ts.ready.set()

    def start(self, thread_fns: List[Tuple[str, object]]):
        """Spawn and park every test thread (each stops at its "start"
        schedule point before ``fn`` runs). Spawn order assigns tids —
        the stable identity decision lists are written in."""
        for name, fn in thread_fns:
            ts = _TState(len(self.threads), name, fn)
            self.threads.append(ts)
            ts.thread = threading.Thread(
                target=self._main, args=(ts,), daemon=True,
                name=f"tpumc-{name}",
            )
            ts.thread.start()
            if not ts.ready.wait(timeout=STUCK_LIMIT_S):
                raise McError(f"test thread '{name}' never parked")
            ts.ready.clear()
            ts.status = "parked"
        self._started = True

    def _threads_by_tid(self):
        return {t.tid: t for t in self.threads}

    # -- scheduling queries --------------------------------------------------- #

    def live(self) -> List[_TState]:
        return [t for t in self.threads if t.status != "done"]

    def is_enabled(self, ts: _TState) -> bool:
        op = ts.pending
        if op is None or ts.status == "done":
            return False
        if op.kind in ("start", "release", "notify", "field", "wait_sleep",
                       "try_acquire"):
            return True
        lock = op.lock._lock if isinstance(op.lock, McCondition) else op.lock
        if op.kind == "acquire":
            return lock.owner is None or (
                lock.owner == ts.tid and lock._reentrant
            )
        if op.kind == "acquire_timed":
            return lock.owner is None or lock.owner == ts.tid \
                and lock._reentrant or ts.timeout_fired
        if op.kind == "wait_wake":
            return (ts.wakeable or ts.timeout_fired) and lock.owner is None
        raise McError(f"unknown op kind {op.kind!r}")

    def enabled_tids(self) -> List[int]:
        return [t.tid for t in self.live() if self.is_enabled(t)]

    def fire_timeout(self) -> bool:
        """Model the earliest pending timeout firing: called only when no
        thread is enabled, so timed waits behave as 'the timeout fires
        once nothing else can make progress' — the fair schedule for
        real-code harnesses whose every wait carries a timeout."""
        eligible = []
        for ts in self.live():
            op = ts.pending
            if op is None or ts.timeout_fired:
                continue
            if op.kind == "wait_wake" and not ts.wakeable \
                    and op.timeout is not None:
                eligible.append((op.timeout, ts.tid, ts))
            elif op.kind == "acquire_timed":
                lock = op.lock
                if lock.owner is not None and lock.owner != ts.tid:
                    eligible.append((op.timeout, ts.tid, ts))
        if not eligible:
            return False
        eligible.sort(key=lambda e: (e[0], e[1]))
        ts = eligible[0][2]
        ts.timeout_fired = True
        if ts.pending.kind == "wait_wake":
            cv = ts.pending.lock
            if ts.tid in cv.waiters:
                cv.waiters.remove(ts.tid)
        return True

    # -- stepping ------------------------------------------------------------- #

    def step(self, tid: int):
        """Apply ``tid``'s pending op to the virtual state, let the
        thread run to its next schedule point, and re-park it."""
        ts = self._threads_by_tid()[tid]
        if not self.is_enabled(ts):
            raise McError(f"stepping disabled thread {ts.name!r}")
        self._apply(ts)
        ts.pending = None
        ts.go.set()
        if not ts.ready.wait(timeout=STUCK_LIMIT_S):
            self.abort()
            raise McError(
                f"test thread '{ts.name}' blocked outside the controller "
                "(uncontrolled primitive?) — model-checked code must only "
                "block through sanitize.named_* primitives"
            )
        ts.ready.clear()

    def _apply(self, ts: _TState):
        op = ts.pending
        kind = op.kind
        if kind in ("start", "field"):
            if kind == "field":
                key = (op.owner_id, op.field)
                label, entries = self.accesses.setdefault(
                    key, (op.label, [])
                )
                entries.append(_FieldAccess(
                    ts.tid, op.write,
                    frozenset(l._name for l in ts.held),
                    op.path, op.line,
                ))
            return
        cv = op.lock if isinstance(op.lock, McCondition) else None
        lock = cv._lock if cv is not None else op.lock
        if kind == "acquire":
            lock.owner = ts.tid
            lock.count += 1
            if lock.count == 1:
                ts.held.append(lock)
            ts.op_result = True
        elif kind == "try_acquire":
            if lock.owner is None:
                lock.owner = ts.tid
                lock.count = 1
                ts.held.append(lock)
                ts.op_result = True
            else:
                ts.op_result = False
        elif kind == "acquire_timed":
            if lock.owner is None or (lock.owner == ts.tid
                                      and lock._reentrant):
                lock.owner = ts.tid
                lock.count += 1
                if lock.count == 1:
                    ts.held.append(lock)
                ts.op_result = True
            else:
                ts.timeout_fired = False
                ts.op_result = False
        elif kind == "release":
            if lock.owner != ts.tid:
                raise McError(
                    f"thread '{ts.name}' released lock '{lock._name}' it "
                    "does not hold"
                )
            lock.count -= 1
            if lock.count == 0:
                lock.owner = None
                ts.held.remove(lock)
        elif kind == "wait_sleep":
            ts.saved_count = lock.count
            lock.owner, lock.count = None, 0
            ts.held.remove(lock)
            ts.wakeable = False
            ts.timeout_fired = False
            cv.waiters.append(ts.tid)
        elif kind == "wait_wake":
            lock.owner = ts.tid
            lock.count = ts.saved_count
            ts.held.append(lock)
            ts.op_result = not ts.timeout_fired
            ts.wakeable = False
            ts.timeout_fired = False
        elif kind == "notify":
            by_tid = self._threads_by_tid()
            for tid in list(cv.waiters[:op.n]):
                by_tid[tid].wakeable = True
                cv.waiters.remove(tid)
        else:
            raise McError(f"unknown op kind {kind!r}")

    # -- teardown ------------------------------------------------------------- #

    def abort(self):
        self._aborting = True
        for ts in self.threads:
            ts.go.set()
        for ts in self.threads:
            if ts.thread is not None:
                ts.thread.join(timeout=5.0)

    # -- post-run analysis ---------------------------------------------------- #

    def race_candidates(self):
        """[(label, write_access, other_access)] for fields touched by
        >= 2 threads with >= 1 write and an EMPTY intersected lockset —
        the Eraser check over a fully explored schedule (pairs the
        static TPU009 rule and tpusan's runtime lockset witness)."""
        out = []
        for (_oid, _field), (label, entries) in sorted(
            self.accesses.items(), key=lambda kv: kv[1][0]
        ):
            tids = {e.tid for e in entries}
            if len(tids) < 2 or not any(e.write for e in entries):
                continue
            lockset = None
            for e in entries:
                lockset = e.locks if lockset is None else lockset & e.locks
            if lockset:
                continue
            writer = next(e for e in entries if e.write)
            other = next(e for e in entries if e.tid != writer.tid)
            out.append((label, writer, other))
        return out

"""Checkable harness models over the four real scheduling cores.

Each builder returns a fresh :class:`~tritonclient_tpu.mc.Model` whose
threads drive the *real* code paths — ``_DynamicBatcher.submit``/
``_sweep_shed``/``_take_batch``/completion-wakeup, ``GenerationEngine``
admission/slot-free/cancel, ``BlockPool``/``PrefixCache`` alloc/free/
prefix-release, ``AdmissionController`` bucket/cap/pressure-shed — not
re-modeled logic. The driver threads replace only the surrounding
*infrastructure* the checker cannot control (the daemon dispatcher /
engine / delivery threads the cores spawn internally), re-issuing the
same calls those threads make, in the same order, against the same
state. Invariants assert the cross-schedule contracts: no response
lost, no slot or KV page leaked, shed counters reconcile, FIFO
preserved for no-deadline traffic.

These models are the safety net for the ROADMAP item-1 scheduler
extraction: they constrain observable behavior only through public
state, so they re-run unchanged against a unified scheduler.

Two ``demo_*`` fixtures (a lost wakeup and an AB-BA deadlock) carry
seeded bugs — they are the worked examples in README/tests and are
excluded from the default "run every harness" set.
"""

import threading
import types
from typing import Callable, Dict

from tritonclient_tpu import sanitize
from tritonclient_tpu.mc._explore import Explorer, ExploreResult, Model


class HarnessUnavailable(RuntimeError):
    """The harness's subject cannot be imported here (e.g. no jax)."""


class _AliveThread:
    """Quacks like a live ``threading.Thread``: pre-seeded into the
    engine/distributor thread slots so the real ``submit`` paths do not
    spawn uncontrolled daemon threads mid-run (the harness's controlled
    threads stand in for them)."""

    @staticmethod
    def is_alive() -> bool:
        return True

    @staticmethod
    def join(timeout=None):
        return None


# --------------------------------------------------------------------------- #
# batcher: submit / _sweep_shed / _take_batch / completion-wakeup             #
# --------------------------------------------------------------------------- #


def build_batcher() -> Model:
    from tritonclient_tpu.protocol._literals import SHED_REASON_CANCELLED
    from tritonclient_tpu.server._core import (
        CoreRequest,
        CoreTensor,
        _DynamicBatcher,
        _ModelStats,
    )

    m = Model("batcher")
    core = types.SimpleNamespace(
        _lock=sanitize.named_lock("InferenceCore._lock")
    )
    batcher = _DynamicBatcher(core)
    batcher._n_dispatchers = 0  # the model's dispatcher thread stands in
    model = types.SimpleNamespace(name="mc-batcher")
    stats = _ModelStats()

    def req(rid: str, cancelled: bool = False) -> CoreRequest:
        ev = threading.Event()
        if cancelled:
            ev.set()
        return CoreRequest(
            model_name="mc-batcher", id=rid,
            inputs=[CoreTensor(name="x", datatype="FP32", shape=[1, 4])],
            cancel_event=ev,
        )

    state = {
        "slots": [],        # (rid, slot) in per-thread submit order
        "completed": [],    # rids in completion order
        "swept": 0,
        "subs_done": 0,
    }

    def submitter_fifo():
        # Two same-signature submissions from ONE thread: their queue
        # order is their submit order, the FIFO contract under test.
        for rid in ("a1", "a2"):
            state["slots"].append((rid, batcher.submit(model, req(rid),
                                                       stats, cap=8)))
        state["subs_done"] += 1

    def submitter_cancelled():
        # Cancelled before the dispatcher can take it: the sweep must
        # shed it and the shed counter must reconcile.
        state["slots"].append(("c1", batcher.submit(
            model, req("c1", cancelled=True), stats, cap=8
        )))
        state["subs_done"] += 1

    def dispatcher():
        # The take half of _DynamicBatcher._run, minus the model
        # execution: sweep + take under the cv, finalize/complete
        # outside it, completion bookkeeping + wakeup back under it.
        while True:
            with batcher._cv:
                shed = batcher._sweep_shed()
                batch = batcher._take_batch() if batcher._queue else None
                if batch:
                    batcher._dispatching += 1
            if shed:
                batcher._finalize_shed(shed)
                state["swept"] += len(shed)
            for slot in batch or ():
                slot.response = f"resp-{slot.request.id}"
                slot.done = True
                slot.event.set()
                state["completed"].append(slot.request.id)
            if batch:
                with batcher._cv:
                    batcher._dispatching -= 1
                    batcher._cv.notify_all()
            answered = len(state["completed"]) + state["swept"]
            if state["subs_done"] == 2 and answered >= len(state["slots"]):
                return
            if not batch and not shed:
                with batcher._cv:
                    batcher._cv.wait(timeout=0.01)

    m.thread("submit-fifo", submitter_fifo)
    m.thread("submit-cancel", submitter_cancelled)
    m.thread("dispatcher", dispatcher)

    def no_response_lost():
        for rid, slot in state["slots"]:
            assert slot.done, f"slot {rid} never answered"
            assert (slot.response is None) != (slot.error is None), \
                f"slot {rid} must carry exactly one of response/error"
        return True

    def fifo_preserved():
        order = [r for r in state["completed"] if r in ("a1", "a2")]
        assert order == sorted(order), \
            f"no-deadline FIFO violated: completion order {order}"
        return True

    def shed_reconciles():
        assert sum(stats.shed_counts.values()) == state["swept"], (
            f"shed counters {stats.shed_counts} != swept {state['swept']}"
        )
        assert stats.shed_counts[SHED_REASON_CANCELLED] == 1
        return True

    def queue_drained():
        assert not batcher._queue, "slots left in the batcher queue"
        assert batcher._deadline_queued == 0
        assert batcher._dispatching == 0
        return True

    m.invariant("no response lost", no_response_lost)
    m.invariant("no-deadline FIFO preserved", fifo_preserved)
    m.invariant("shed counters reconcile", shed_reconciles)
    m.invariant("queue drained", queue_drained)
    return m


# --------------------------------------------------------------------------- #
# gpt engine: admission / slot-free / cancel                                  #
# --------------------------------------------------------------------------- #


def build_gpt_engine() -> Model:
    try:
        import numpy as np

        from tritonclient_tpu.models.gpt import gpt_tiny
        from tritonclient_tpu.models.gpt_engine import GenerationEngine
    except Exception as e:  # noqa: BLE001 — jax/numpy absent or broken
        raise HarnessUnavailable(f"gpt engine unavailable: {e}") from e

    m = Model("gpt_engine")
    # One usable KV page (n_blocks=2 = scratch + 1) and two slots: the
    # second admission MUST take the pool-exhausted head-of-line path
    # (engine._pending) and retry when the first request's page frees.
    eng = GenerationEngine(gpt_tiny(max_len=8), params={}, max_slots=2,
                           block_size=4, n_blocks=2, prefill_chunk=4)
    eng._thread = _AliveThread()        # harness thread runs the loop
    eng._dist._thread = _AliveThread()  # harness thread delivers
    eng.shutdown = lambda: None         # atexit must not touch mc locks

    state = {"reqs": {}, "subs": 0, "cancel_drained": False}
    prompt = np.zeros((1, 3), np.int32)

    def submitter(name: str):
        def run():
            state["reqs"][name] = eng.submit(prompt, max_new=1)
            state["subs"] += 1
        return run

    def delivered(req) -> bool:
        return req.remaining == 0

    def engine_loop():
        # The scheduling spine of GenerationEngine._run_loop — cancel
        # sweep, free processing, admission — without the decode/prefill
        # dispatches (no compute runs under the checker).
        for _ in range(40):
            with eng._cv:
                done = (eng._admit.empty() and eng._dist.free_q.empty()
                        and eng._pending is None
                        and all(r is None for r in eng._slot_req)
                        and state["subs"] == 2)
                if done:
                    break
                # Actionable now? A queued admission, a returned slot,
                # or a head-of-line retry with pages available. Anything
                # else (decode in flight, pool exhausted) parks on the
                # cv until a submit/completion wakeup, as _run_loop does.
                work = (not eng._admit.empty()
                        or not eng._dist.free_q.empty()
                        or (eng._pending is not None
                            and eng._pool.free_count > 0))
                if not work:
                    # Longer than the distributor's wait: the checker
                    # fires the EARLIEST timeout when every thread is
                    # blocked, and a slot awaiting delivery is the
                    # distributor's progress to make, not ours.
                    eng._cv.wait(timeout=5.0)
                    continue
            eng._release_cancelled()
            eng._process_frees()
            eng._admit_requests()
            # _advance_prefills' terminal bookkeeping: prefill chunks
            # complete instantly under the checker (its compute
            # dispatches are the one part of the loop not modeled).
            for slot in list(eng._prefilling):
                del eng._prefilling[slot]
            with eng._cv:
                eng._cv.notify_all()  # loop-top wakeup, as _run_loop does
        # Deterministic epilogue on the same thread: a request cancelled
        # while queued must be drained through the abandoned path.
        req_c = eng.submit(prompt, max_new=1)
        req_c.cancelled = True
        eng._admit_requests()
        state["reqs"]["c"] = req_c
        state["cancel_drained"] = req_c.out.get_nowait() is None

    def distributor():
        # The completion tail of _Distributor._deliver: final token out,
        # terminator queued, slot handed back on free_q, engine woken.
        done = set()
        while len(done) < 2:
            progressed = False
            for slot, req in enumerate(list(eng._slot_req)):
                if req is None or id(req) in done:
                    continue
                if slot in eng._prefilling:
                    continue  # tokens only flow once the prefill is done
                req.remaining = 0
                req.out.put(None)
                eng._dist.free_q.put((slot, req))
                with eng._cv:
                    eng._cv.notify_all()
                done.add(id(req))
                progressed = True
            if not progressed:
                with eng._cv:
                    eng._cv.wait(timeout=2.0)

    m.thread("submit-a", submitter("a"))
    m.thread("submit-b", submitter("b"))
    m.thread("engine-loop", engine_loop)
    m.thread("distributor", distributor)

    def no_page_leaked():
        # Everything freed: only the scratch page stays referenced.
        assert eng._pool.used_count == 1, (
            f"KV pages leaked: used_count {eng._pool.used_count} != 1 "
            "(scratch)"
        )
        assert eng._pool.free_count == 1
        return True

    def no_slot_leaked():
        assert all(r is None for r in eng._slot_req), "slot left occupied"
        assert eng._pending is None
        assert eng._admit.empty()
        assert eng._dist.free_q.empty()
        assert not eng._prefilling
        return True

    def every_request_terminated():
        for name in ("a", "b"):
            req = state["reqs"][name]
            assert delivered(req), f"request {name} never delivered"
        assert state["cancel_drained"], \
            "cancelled request never drained through the abandoned path"
        return True

    m.invariant("no KV page leaked", no_page_leaked)
    m.invariant("no slot leaked", no_slot_leaked)
    m.invariant("every request terminated", every_request_terminated)
    return m


# --------------------------------------------------------------------------- #
# kvcache: BlockPool alloc/free + PrefixCache register/release/evict          #
# --------------------------------------------------------------------------- #


def build_kvcache() -> Model:
    from tritonclient_tpu._kvcache import BlockPool, PrefixCache

    m = Model("kvcache")
    n_blocks = 4
    pool = BlockPool(n_blocks, block_size=1)
    prefix = PrefixCache(pool)
    H1 = 0x1234

    def producer():
        # Prefill path: allocate, publish one block under its chain
        # hash, release both (registered -> evictable LRU, unregistered
        # -> free list).
        b1 = pool.try_alloc()
        b2 = pool.try_alloc()
        if b1 is not None:  # the consumer may have drained the pool
            prefix.register(H1, b1)
            prefix.release_block(b1)
        if b2 is not None:
            prefix.release_block(b2)

    def consumer():
        # Prefix-hit path racing the producer: a hit refs the shared
        # block; a miss drains the pool and reclaims through evict_lru.
        bid = prefix.match(H1)
        if bid is not None:
            prefix.release_block(bid)
        taken = []
        while True:
            got = pool.try_alloc()
            if got is None:
                break
            taken.append(got)
        evicted = prefix.evict_lru()
        if evicted is not None:
            taken.append(evicted)
        for got in taken:
            prefix.release_block(got)

    m.thread("producer", producer)
    m.thread("consumer", consumer)

    def conservation():
        # Every block in exactly one of: free list, evictable LRU,
        # refcount > 0.
        free = pool.free_count
        used = pool.used_count
        evictable = prefix.evictable_count
        assert free + used + evictable == n_blocks, (
            f"block conservation violated: free {free} + used {used} + "
            f"evictable {evictable} != {n_blocks}"
        )
        assert used == 0, f"pages leaked: {used} blocks still referenced"
        return True

    m.invariant("no page leaked (free/evictable/ref partition)",
                conservation)
    return m


# --------------------------------------------------------------------------- #
# fleet admission: token bucket / concurrency cap / pressure shed             #
# --------------------------------------------------------------------------- #


def build_fleet_admission() -> Model:
    from tritonclient_tpu.fleet._admission import (
        AdmissionController,
        TenantQuota,
    )
    from tritonclient_tpu.protocol._literals import QUOTA_REASON_PRESSURE

    m = Model("fleet_admission")
    # Frozen clock: the token bucket never refills mid-run, so every
    # schedule sees the same arithmetic.
    ctl = AdmissionController(
        {
            "t": TenantQuota(rate=1.0, burst=2.0, max_outstanding=1),
            "be": TenantQuota(rate=0.0, priority="low"),
        },
        clock=lambda: 100.0,
    )
    state = {"attempts": 0, "admitted": 0, "rejected": 0, "pressure": 0}

    def paid_client():
        # admit/release pair under the concurrency cap: racing the
        # other paid client, exactly one of the overlapping admits may
        # see the cap.
        for _ in range(2):
            state["attempts"] += 1
            reason = ctl.admit("t")
            if reason is None:
                state["admitted"] += 1
                ctl.release("t")
            else:
                state["rejected"] += 1

    def best_effort_client():
        # Pressure shed: low-priority traffic under fleet pressure is
        # always rejected; without pressure it rides the unlimited rate.
        state["attempts"] += 1
        reason = ctl.admit("be", under_pressure=True)
        assert reason == QUOTA_REASON_PRESSURE
        state["rejected"] += 1
        state["pressure"] += 1
        state["attempts"] += 1
        reason = ctl.admit("be")
        if reason is None:
            state["admitted"] += 1
            ctl.release("be")
        else:
            state["rejected"] += 1

    m.thread("tenant-t-0", paid_client)
    m.thread("tenant-t-1", paid_client)
    m.thread("tenant-be", best_effort_client)

    def counters_reconcile():
        counts = ctl.rejection_counts()
        total_rejected = sum(
            n for reasons in counts.values() for n in reasons.values()
        )
        assert state["admitted"] + state["rejected"] == state["attempts"]
        assert total_rejected == state["rejected"], (
            f"rejection counters {counts} != observed {state['rejected']}"
        )
        assert counts["be"][QUOTA_REASON_PRESSURE] == state["pressure"]
        return True

    def nothing_outstanding():
        status = ctl.status()
        assert status["outstanding"] == {}, (
            f"outstanding not reconciled: {status['outstanding']}"
        )
        return True

    m.invariant("admit/reject counters reconcile", counters_reconcile)
    m.invariant("no outstanding leaked", nothing_outstanding)
    return m


# --------------------------------------------------------------------------- #
# seeded-bug demos (worked examples; excluded from the default set)           #
# --------------------------------------------------------------------------- #


def build_demo_lost_wakeup() -> Model:
    """The classic missed-signal bug: the consumer checks the flag
    OUTSIDE the cv's lock, so the producer's set+notify can both land
    between the check and the wait — and the untimed wait then sleeps
    forever. tpumc reports TPU011 with the exact schedule; the static
    TPU011 rule flags the same shape as wait-outside-predicate-loop."""
    m = Model("demo-lost-wakeup")
    cv = sanitize.named_condition("demo.cv")
    box = {"ready": False}

    def producer():
        box["ready"] = True
        sanitize.note_field_access(box, "ready", write=True,
                                   label="demo.ready")
        with cv:
            cv.notify_all()

    def consumer():
        sanitize.note_field_access(box, "ready", write=False,
                                   label="demo.ready")
        if not box["ready"]:  # BUG: check not repeated under the lock
            with cv:
                cv.wait()

    m.thread("producer", producer)
    m.thread("consumer", consumer)
    return m


def build_demo_deadlock() -> Model:
    """AB-BA lock-order inversion: one preemption inside the first
    critical section reaches the cyclic-wait state."""
    m = Model("demo-deadlock")
    la = sanitize.named_lock("demo.lock_a")
    lb = sanitize.named_lock("demo.lock_b")

    def forward():
        with la:
            with lb:
                pass

    def backward():
        with lb:
            with la:
                pass

    m.thread("forward", forward)
    m.thread("backward", backward)
    return m


# --------------------------------------------------------------------------- #
# registry                                                                    #
# --------------------------------------------------------------------------- #

#: name -> builder. ``demo_*`` entries carry seeded bugs and are
#: excluded from :data:`DEFAULT_HARNESSES`.
HARNESSES: Dict[str, Callable[[], Model]] = {
    "batcher": build_batcher,
    "gpt_engine": build_gpt_engine,
    "kvcache": build_kvcache,
    "fleet_admission": build_fleet_admission,
    "demo_lost_wakeup": build_demo_lost_wakeup,
    "demo_deadlock": build_demo_deadlock,
}

DEFAULT_HARNESSES = ("batcher", "gpt_engine", "kvcache", "fleet_admission")

#: Per-harness exploration budgets (schedules): the gpt engine rebuilds
#: real device-state vectors per schedule, so its cap is tighter.
SCHEDULE_BUDGETS: Dict[str, int] = {
    "batcher": 1500,
    "gpt_engine": 400,
    "kvcache": 1500,
    "fleet_admission": 1500,
    "demo_lost_wakeup": 200,
    "demo_deadlock": 200,
}


def run_harness(name: str, preemption_budget: int = 2,
                max_schedules: int = 0, deadline_s: float = 60.0,
                seed: int = 0, prune: str = "dpor") -> ExploreResult:
    """Explore one registered harness under its default budgets."""
    if name not in HARNESSES:
        raise KeyError(
            f"unknown harness {name!r} (have: {', '.join(sorted(HARNESSES))})"
        )
    explorer = Explorer(
        HARNESSES[name], name=name, preemption_budget=preemption_budget,
        max_schedules=max_schedules or SCHEDULE_BUDGETS.get(name, 1000),
        deadline_s=deadline_s, seed=seed, prune=prune,
    )
    return explorer.explore()

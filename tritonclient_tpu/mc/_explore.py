"""Schedule-space exploration: CHESS-style bounded preemption + DPOR-lite.

One *schedule* is a full serialized execution of a harness model,
identified by its decision list (the tid chosen at every schedule
point). The explorer runs depth-first over decision prefixes: each
completed run proposes branches — at every step, every *other* enabled
thread — and a branch survives only if

* taking it keeps the path's preemption count within the budget
  (a switch away from a still-enabled thread is a preemption; switches
  forced by blocking are free — the CHESS insight that most bugs hide
  within very few preemptions), and
* it is a *backtrack point*: some operation executed later in the run
  by another thread is dependent on the operation executed at that step
  (same lock, or same field with at least one write) and that thread
  was enabled there — DPOR-lite pruning: when no future operation
  conflicts, the orders commute, so the swapped schedule is equivalent
  to one already explored. (Branching on the future *executed*
  conflict, not the alternative's currently-pending op, is what lets a
  notify that happens three ops into another thread's future pull that
  thread's whole critical section ahead of a wait.)

Detection per run:

* deadlock (TPU007) — no thread enabled, no timeout can fire, and some
  thread is blocked on a lock;
* lost wakeup (TPU011) — every stuck thread sits in an untimed cv wait
  no reachable notify can release;
* invariant violation (TPUMC1) and thread exception (TPUMC2) — checked
  after clean completion / surfaced from the thread body;
* empty-lockset race (TPU009) — the Eraser intersection over adopted
  ``note_field_access`` sites, evaluated on completed schedules.

Every finding embeds a replayable trace: ``{harness, seed,
preemption_budget, decisions}``. Replaying forces the full decision
list, so the failing schedule — and the finding records derived from it
— reproduce byte-identically.
"""

import json
import time
from typing import Callable, Dict, List, Optional, Tuple

from tritonclient_tpu import sanitize
from tritonclient_tpu.mc._sched import (
    McError,
    SchedulerController,
    _dependent,
)

#: SARIF driver metadata. TPU007/TPU009/TPU011 reuse the static rules'
#: ids (same merge contract as tpusan); TPUMC1/TPUMC2 are model-checker
#: native.
RULES_META = [
    {
        "id": "TPU007",
        "name": "lock-order",
        "shortDescription": {
            "text": "deadlock reached by schedule-space exploration"
        },
    },
    {
        "id": "TPU009",
        "name": "guarded-by",
        "shortDescription": {
            "text": "empty lockset on a cross-thread field access reached "
            "by schedule-space exploration"
        },
    },
    {
        "id": "TPU011",
        "name": "condvar-discipline",
        "shortDescription": {
            "text": "lost wakeup: a cv wait no reachable notify can "
            "release"
        },
    },
    {
        "id": "TPUMC1",
        "name": "mc-invariant",
        "shortDescription": {
            "text": "harness invariant violated on an explored schedule"
        },
    },
    {
        "id": "TPUMC2",
        "name": "mc-exception",
        "shortDescription": {
            "text": "unhandled exception in a model thread on an "
            "explored schedule"
        },
    },
]


class Model:
    """One harness: named test threads over real code + end-state
    invariants. Built fresh for every schedule (the builder runs with
    the controller installed, so every ``sanitize.named_*`` primitive
    the code under test constructs is schedule-controlled)."""

    def __init__(self, name: str):
        self.name = name
        self.threads: List[Tuple[str, Callable[[], None]]] = []
        self.invariants: List[Tuple[str, Callable[[], object]]] = []

    def thread(self, name: str, fn: Callable[[], None]):
        self.threads.append((name, fn))

    def invariant(self, desc: str, fn: Callable[[], object]):
        """``fn`` runs after a schedule completes cleanly; a False
        return or any exception (AssertionError included) is a TPUMC1
        finding on that schedule."""
        self.invariants.append((desc, fn))


class _Record:
    """One explored step: what was chosen, what else was possible."""

    __slots__ = ("chosen", "enabled", "footprints", "preemptive")

    def __init__(self, chosen, enabled, footprints, preemptive):
        self.chosen = chosen
        self.enabled = enabled          # sorted tids
        self.footprints = footprints    # tid -> footprint tuple
        self.preemptive = preemptive    # this step switched off a runnable thread


class _RunOutcome:
    __slots__ = ("schedule", "trace", "findings", "steps")

    def __init__(self, schedule, trace, findings):
        self.schedule = schedule
        self.trace = trace
        self.findings = findings
        self.steps = len(schedule)


class ExploreResult:
    """Aggregate over every explored schedule of one harness."""

    def __init__(self, harness: str, seed: int, preemption_budget: int):
        self.harness = harness
        self.seed = seed
        self.preemption_budget = preemption_budget
        self.findings: List[dict] = []
        self._fingerprints = set()
        self.schedules = 0
        self.infeasible = 0
        self.decision_points = 0
        self.pruned_independent = 0
        self.pruned_budget = 0
        self.elapsed_s = 0.0
        self.complete = False  # frontier exhausted within limits

    def add_finding(self, record: dict):
        if record["fingerprint"] not in self._fingerprints:
            self._fingerprints.add(record["fingerprint"])
            self.findings.append(record)

    def as_dict(self) -> dict:
        return {
            "tool": "tpumc",
            "harness": self.harness,
            "seed": self.seed,
            "preemption_budget": self.preemption_budget,
            "schedules": self.schedules,
            "infeasible": self.infeasible,
            "decision_points": self.decision_points,
            "pruned_independent": self.pruned_independent,
            "pruned_budget": self.pruned_budget,
            "elapsed_s": round(self.elapsed_s, 3),
            "complete": self.complete,
            "findings": self.findings,
        }

    def sarif(self) -> str:
        from tritonclient_tpu.analysis._engine import Finding
        from tritonclient_tpu.analysis._sarif import render_sarif

        found = [
            Finding(r["rule"], r["path"], r["line"], r["col"], r["message"])
            for r in self.findings
        ]
        return render_sarif(found, RULES_META, tool_name="tpumc")


class Explorer:
    """Enumerate one harness model's schedule space.

    ``build`` returns a fresh :class:`Model` per call. ``seed`` is
    recorded into every trace (and seeds nothing today — exploration is
    deterministic DFS — but traces carry it so a future randomized
    strategy replays through the same door).
    """

    def __init__(self, build: Callable[[], Model], name: Optional[str] = None,
                 preemption_budget: int = 2, max_schedules: int = 2000,
                 max_steps: int = 2000, deadline_s: Optional[float] = None,
                 seed: int = 0, prune: str = "dpor"):
        self._build = build
        self.name = name or getattr(build, "__name__", "model")
        self.preemption_budget = preemption_budget
        self.max_schedules = max_schedules
        self.max_steps = max_steps
        self.deadline_s = deadline_s
        self.seed = seed
        if prune not in ("dpor", "naive"):
            raise ValueError(f"unknown pruning mode {prune!r}")
        self.prune = prune  # "naive" keeps independent branches (PERF A/B)

    # -- single schedule ------------------------------------------------------ #

    def _trace_dict(self, schedule: List[int]) -> dict:
        return {
            "harness": self.name,
            "seed": self.seed,
            "preemption_budget": self.preemption_budget,
            "decisions": list(schedule),
        }

    def _finding(self, rule: str, path: str, line: int, message: str,
                 schedule: List[int]) -> dict:
        return {
            "rule": rule,
            "path": path,
            "line": int(line),
            "col": 0,
            "message": message,
            "fingerprint": f"{rule}::{path}::{message}",
            "harness": self.name,
            "trace": self._trace_dict(schedule),
        }

    def _stuck_findings(self, ctl: SchedulerController,
                        schedule: List[int]) -> List[dict]:
        stuck = [t for t in ctl.live() if t.pending is not None]
        if not stuck:
            return []
        stuck.sort(key=lambda t: t.tid)
        all_waiting = all(
            t.pending.kind == "wait_wake" and t.pending.timeout is None
            and not t.wakeable
            for t in stuck
        )
        parts = [
            f"thread '{t.name}' {t.pending.describe()} at "
            f"{t.pending.path}:{t.pending.line}"
            for t in stuck
        ]
        lead = stuck[0].pending
        if all_waiting:
            message = (
                "lost wakeup: no reachable notify can release "
                + "; ".join(parts)
            )
            rule = "TPU011"
        else:
            message = "schedule-space deadlock: " + "; ".join(parts)
            rule = "TPU007"
        return [self._finding(rule, lead.path, lead.line, message, schedule)]

    def _execute(self, forced: List[int]) -> Optional[_RunOutcome]:
        ctl = SchedulerController()
        prev = sanitize.set_schedule_controller(ctl)
        try:
            model = self._build()
            ctl.start(model.threads)
            trace: List[_Record] = []
            schedule: List[int] = []
            findings: List[dict] = []
            step = 0
            while ctl.live():
                enabled = sorted(ctl.enabled_tids())
                if not enabled:
                    if ctl.fire_timeout():
                        continue
                    findings = self._stuck_findings(ctl, schedule)
                    break
                if step < len(forced):
                    choice = forced[step]
                    if choice not in enabled:
                        return None  # infeasible divergence
                else:
                    prev_tid = schedule[-1] if schedule else None
                    choice = prev_tid if prev_tid in enabled else enabled[0]
                by_tid = {t.tid: t for t in ctl.threads}
                footprints = {
                    tid: by_tid[tid].pending.footprint() for tid in enabled
                }
                preemptive = bool(
                    schedule and choice != schedule[-1]
                    and schedule[-1] in enabled
                )
                trace.append(_Record(choice, enabled, footprints, preemptive))
                schedule.append(choice)
                ctl.step(choice)
                step += 1
                if step > self.max_steps:
                    raise McError(
                        f"harness '{self.name}' exceeded {self.max_steps} "
                        "schedule points in one run — unbounded loop in a "
                        "model thread?"
                    )
            if not findings:
                for ts in ctl.threads:
                    if ts.exc is not None:
                        op_site = f"thread '{ts.name}'"
                        findings.append(self._finding(
                            "TPUMC2", f"mc/{self.name}", 1,
                            f"unhandled {type(ts.exc).__name__} in "
                            f"{op_site}: {ts.exc}",
                            schedule,
                        ))
            if not findings:
                for label, writer, other in ctl.race_candidates():
                    findings.append(self._finding(
                        "TPU009", writer.path, writer.line,
                        f"empty lockset on field '{label}': written at "
                        f"{writer.path}:{writer.line} and accessed at "
                        f"{other.path}:{other.line} by another thread "
                        "with no common lock on any explored schedule "
                        "point",
                        schedule,
                    ))
                for desc, fn in model.invariants:
                    try:
                        ok = fn()
                    except BaseException as e:  # noqa: BLE001 — finding
                        findings.append(self._finding(
                            "TPUMC1", f"mc/{self.name}", 1,
                            f"invariant '{desc}' raised "
                            f"{type(e).__name__}: {e}",
                            schedule,
                        ))
                        continue
                    if ok is False:
                        findings.append(self._finding(
                            "TPUMC1", f"mc/{self.name}", 1,
                            f"invariant '{desc}' violated",
                            schedule,
                        ))
            return _RunOutcome(schedule, trace, findings)
        finally:
            ctl.abort()
            sanitize.set_schedule_controller(prev)

    # -- exploration ---------------------------------------------------------- #

    def _preemptions_with_branch(self, trace: List[_Record], i: int,
                                 alt: int) -> int:
        count = sum(1 for rec in trace[:i] if rec.preemptive)
        if i > 0 and alt != trace[i - 1].chosen \
                and trace[i - 1].chosen in trace[i].enabled:
            count += 1
        return count

    def explore(self) -> ExploreResult:
        result = ExploreResult(self.name, self.seed, self.preemption_budget)
        t0 = time.monotonic()
        frontier: List[List[int]] = [[]]
        seen = {()}
        truncated = False
        while frontier:
            if result.schedules >= self.max_schedules:
                truncated = True
                break
            if self.deadline_s is not None \
                    and time.monotonic() - t0 > self.deadline_s:
                truncated = True
                break
            prefix = frontier.pop()
            outcome = self._execute(prefix)
            result.schedules += 1
            if outcome is None:
                result.infeasible += 1
                continue
            for record in outcome.findings:
                result.add_finding(record)
            trace = outcome.trace
            for i in range(len(trace) - 1, len(prefix) - 1, -1):
                rec = trace[i]
                others = [a for a in rec.enabled if a != rec.chosen]
                result.decision_points += len(others)
                if self.prune == "dpor":
                    # Backtrack set: threads whose later *executed* op
                    # conflicts with the op executed here. Everything
                    # else commutes past step i.
                    alts = set()
                    chosen_fp = rec.footprints[rec.chosen]
                    for j in range(i + 1, len(trace)):
                        later = trace[j]
                        if later.chosen == rec.chosen \
                                or later.chosen not in rec.enabled:
                            continue
                        if _dependent(later.footprints[later.chosen],
                                      chosen_fp):
                            alts.add(later.chosen)
                    result.pruned_independent += len(others) - len(alts)
                else:
                    alts = others
                for alt in sorted(alts):
                    if self._preemptions_with_branch(
                        trace, i, alt
                    ) > self.preemption_budget:
                        result.pruned_budget += 1
                        continue
                    branch = tuple(outcome.schedule[:i]) + (alt,)
                    if branch not in seen:
                        seen.add(branch)
                        frontier.append(list(branch))
        result.elapsed_s = time.monotonic() - t0
        result.complete = not frontier and not truncated
        return result

    def replay(self, trace: dict) -> ExploreResult:
        """Re-run one recorded schedule. The decision list pins every
        choice, so the run — and any finding records it produces —
        reproduce byte-identically."""
        result = ExploreResult(self.name, trace.get("seed", self.seed),
                               trace.get("preemption_budget",
                                         self.preemption_budget))
        t0 = time.monotonic()
        outcome = self._execute(list(trace["decisions"]))
        result.schedules = 1
        if outcome is None:
            result.infeasible = 1
        else:
            for record in outcome.findings:
                result.add_finding(record)
        result.elapsed_s = time.monotonic() - t0
        result.complete = True
        return result


def findings_json(result: ExploreResult) -> str:
    """Canonical JSON for byte-identical replay comparison."""
    return json.dumps(result.findings, indent=2, sort_keys=True)

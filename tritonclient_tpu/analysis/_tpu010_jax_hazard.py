"""TPU010: JAX hot-path hazard detection.

The stepscope numbers that motivate this rule: at tp=2 the decode loop
spends 354.8 ms in host dispatch against 5.3 ms of device time — the
regime where one hidden device→host sync or one silent retrace erases
the entire compute/collective-overlap win. This rule makes those
hazards lint errors *on the hot paths only*, so cold setup/debug code
stays free to coerce arrays however it likes.

**Hot regions** are declared, not guessed: annotate a function with
``# tpulint: hot-path`` on (or immediately above) its ``def`` line, and
everything reachable from it in the project call graph is hot. The
in-tree roots are the engines' decode/step loops, the distributor
delivery loop, the overlap helpers, and the shm upload path.

Flagged inside hot regions (``_callgraph.py`` records the candidates via
local device-taint dataflow — results of ``jax.*``/``jnp.*``/``lax.*``
calls, jitted-callable results, ``jax.Array``-annotated parameters):

* **host syncs** — ``np.asarray``/``np.array``/``float``/``int``/
  ``bool``/``.item()``/``.tolist()`` on a device value, and
  ``jax.device_get``;
* **bool syncs** — ``if``/``while`` branching on a device value
  (identity checks ``is None`` excluded: metadata never transfers);
* **blocking syncs** — ``block_until_ready`` in a dispatch path;
* **retrace triggers** — ``jax.jit``/``jax.pmap`` constructed inside a
  hot function body (a fresh callable retraces per call; construction
  under a cache-miss guard like ``if key not in cache:`` is recognized
  as the memoized-build idiom and skipped), and jitted callables with
  ``static_argnums``/``static_argnames`` invoked with a loop-varying
  argument (every distinct value recompiles).

Deliberate sync points — the single designed readback per decode step,
idle-only warmup barriers — suppress with ``# tpulint: disable=TPU010``
and a justification, which doubles as documentation of where the
device→host boundary intentionally sits.
"""

from typing import List, Optional, Sequence

from tritonclient_tpu.analysis import _callgraph
from tritonclient_tpu.analysis._engine import FileContext, Finding, Rule


class JaxHazardRule(Rule):
    id = "TPU010"
    name = "jax-hot-path"
    description = (
        "device->host sync or retrace trigger on a `# tpulint: hot-path` "
        "reachable function (dispatch-bound decode loops cannot afford "
        "either)"
    )

    def check_project(self, ctxs: Sequence[FileContext]) -> List[Finding]:
        if not ctxs:
            return []
        graph = _callgraph.get_callgraph(ctxs)
        linted = {ctx.path for ctx in ctxs}
        findings: List[Finding] = []
        for key in sorted(graph.functions):
            fn = graph.functions[key]
            if fn.path not in linted:
                continue
            root = graph.hot_root(key)
            if root is None:
                continue
            via = "" if root == key else f", hot via `{root}`"
            for hz in fn.hazards:
                msg = _message(hz, via)
                if msg is None:
                    continue
                findings.append(Finding(
                    JaxHazardRule.id, fn.path, hz.line, hz.col, msg))
        findings.sort(key=lambda f: (f.path, f.line, f.col))
        return findings


def _message(hz, via: str) -> Optional[str]:
    loop = " inside a loop" if hz.in_loop else ""
    if hz.kind == "host-sync":
        return (f"device->host sync in hot path{loop}: {hz.detail}"
                f"{via}")
    if hz.kind == "bool-sync":
        return f"{hz.detail} in hot path{loop}{via}"
    if hz.kind == "block-sync":
        return (f"{hz.detail} in hot path{loop} — stalls the dispatch "
                f"pipeline{via}")
    if hz.kind == "jit-in-body":
        if hz.guarded:
            return None  # cache-miss-guarded build: compiles once
        return f"retrace trigger in hot path{loop}: {hz.detail}{via}"
    if hz.kind == "static-drift":
        return f"retrace trigger in hot path: {hz.detail}{via}"
    return None

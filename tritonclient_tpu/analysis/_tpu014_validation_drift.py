"""TPU014: validation drift between protocol planes.

The HTTP and gRPC front-ends parse the same KServe v2 surface, so the
set of request fields each plane validates must match — a field range-
checked on one plane but trusted on the other is an open door that the
"validated" plane's tests will never catch. This rule diffs the
per-field sanitizer sets of the two server planes the way TPU008 diffs
protocol literals:

* **plane drift** — a field validated on one server plane
  (``server/_http.py`` / ``server/_grpc.py``) and *referenced* on the
  other, but never validated there. The finding lands on the trusting
  plane's reference line.
* **client-only validation** — a field validated in a client library
  (``http/``, ``grpc/``) that a server plane references but neither
  server plane validates: the server is trusting clients to police
  their own input.

"Validated" means a ``validate_*`` call from ``protocol/_validate.py``
whose target field is known — either statically
(``validate_shape``→shape) or from the field-name literal passed to
``validate_int``. "Referenced" means the plane touches the wire key:
the ``KEY_*`` literal constant, a matching string literal, or a
matching attribute read. Content-Length is special-cased: the gRPC
equivalent of the HTTP body cap is ``grpc.max_receive_message_length``,
so a plane referencing that option counts as validating
``content_length``.

Deliberate asymmetries suppress with ``# tpulint: disable=TPU014`` on
the reference line, with a comment saying which plane covers the field
and how.
"""

import ast
from typing import Dict, List, Optional, Sequence

from tritonclient_tpu.analysis._engine import FileContext, Finding, Rule

#: validator name -> canonical field(s) it launders.
_VALIDATOR_FIELDS = {
    "validate_shape": ("shape",),
    "validate_dtype": ("datatype",),
    "validate_shm_window": ("shared_memory_offset",
                            "shared_memory_byte_size"),
    "validate_content_length": ("content_length",),
    "validate_data_length": ("data_length",),
}

#: Wire-key constant name -> canonical field.
_KEY_FIELDS = {
    "KEY_SHM_OFFSET": "shared_memory_offset",
    "KEY_SHM_BYTE_SIZE": "shared_memory_byte_size",
    "KEY_BINARY_DATA_SIZE": "binary_data_size",
    "KEY_CLASSIFICATION": "classification",
}

#: Attribute / string-literal spellings -> canonical field.
_NAME_FIELDS = {
    "shape": "shape",
    "datatype": "datatype",
    "shm_offset": "shared_memory_offset",
    "shared_memory_offset": "shared_memory_offset",
    "shm_byte_size": "shared_memory_byte_size",
    "shared_memory_byte_size": "shared_memory_byte_size",
    "binary_data_size": "binary_data_size",
    "classification": "classification",
    "device_id": "device_id",
    "content_length": "content_length",
}

_HTTP_SUFFIX = "server/_http.py"
_GRPC_SUFFIX = "server/_grpc.py"
_CLIENT_SEGMENTS = ("/http/", "/grpc/")


def _norm(name: str) -> str:
    return name.strip().lower().replace("-", "_")


class _PlaneFacts:
    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.validated: Dict[str, int] = {}   # field -> first line
        self.referenced: Dict[str, int] = {}  # field -> first line
        self._walk(ctx.tree)

    def _note(self, table: Dict[str, int], field: str, line: int):
        table.setdefault(field, line)

    def _walk(self, tree):
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                self._call(node)
            elif isinstance(node, ast.Name):
                field = _KEY_FIELDS.get(node.id)
                if field:
                    self._note(self.referenced, field, node.lineno)
            elif isinstance(node, ast.Attribute):
                field = _NAME_FIELDS.get(node.attr)
                if field:
                    self._note(self.referenced, field, node.lineno)
            elif isinstance(node, ast.Constant) and isinstance(node.value,
                                                               str):
                if self.ctx.is_docstring(node):
                    continue
                if node.value == "grpc.max_receive_message_length":
                    self._note(self.validated, "content_length", node.lineno)
                    continue
                field = _NAME_FIELDS.get(_norm(node.value))
                if field:
                    self._note(self.referenced, field, node.lineno)

    def _call(self, call: ast.Call):
        func = call.func
        name = (func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else "")
        if not name.startswith("validate_"):
            return
        for field in _VALIDATOR_FIELDS.get(name, ()):
            self._note(self.validated, field, call.lineno)
        if name == "validate_int":
            field_arg = None
            if len(call.args) >= 2:
                field_arg = call.args[1]
            else:
                for kw in call.keywords:
                    if kw.arg == "field":
                        field_arg = kw.value
            if isinstance(field_arg, ast.Constant) and isinstance(
                field_arg.value, str
            ):
                self._note(self.validated, _norm(field_arg.value),
                           call.lineno)
            elif isinstance(field_arg, ast.Name):
                # The field name is a KEY_* wire-key constant (the
                # TPU003-clean spelling).
                field = _KEY_FIELDS.get(field_arg.id)
                if field:
                    self._note(self.validated, field, call.lineno)


class ValidationDriftRule(Rule):
    id = "TPU014"
    name = "validation-drift"
    description = (
        "request field validated on one protocol plane but referenced "
        "unvalidated on the other, or validated only client-side"
    )

    def check_project(self, ctxs: Sequence[FileContext]) -> List[Finding]:
        http = _find_plane(ctxs, _HTTP_SUFFIX)
        grpc = _find_plane(ctxs, _GRPC_SUFFIX)
        findings: List[Finding] = []
        if http is not None and grpc is not None:
            findings += self._diff(http, "HTTP", grpc, "gRPC")
            findings += self._diff(grpc, "gRPC", http, "HTTP")
        # Client-side-only validation: a client library validates a
        # field the server planes reference but never validate.
        servers = [p for p in (http, grpc) if p is not None]
        if servers:
            findings += self._client_only(ctxs, servers)
        return findings

    def _diff(self, src: _PlaneFacts, src_name: str,
              dst: _PlaneFacts, dst_name: str) -> List[Finding]:
        out: List[Finding] = []
        for field, src_line in sorted(src.validated.items()):
            if field in dst.validated or field not in dst.referenced:
                continue
            line = dst.referenced[field]
            if dst.ctx.is_suppressed(self.id, line):
                continue
            out.append(Finding(
                self.id, dst.ctx.path, line, 0,
                f"field '{field}' is validated on the {src_name} plane "
                f"but the {dst_name} plane references it without a "
                f"validate_* call: the planes have drifted — route both "
                f"through protocol/_validate.py",
            ))
        return out

    def _client_only(self, ctxs: Sequence[FileContext],
                     servers: List[_PlaneFacts]) -> List[Finding]:
        client_validated: Dict[str, str] = {}  # field -> client path
        for ctx in ctxs:
            path = "/" + ctx.path.replace("\\", "/").lstrip("/")
            if not any(seg in path for seg in _CLIENT_SEGMENTS):
                continue
            if "/server/" in path or _is_test_path(ctx.path):
                continue
            facts = _PlaneFacts(ctx)
            for field in facts.validated:
                client_validated.setdefault(field, ctx.path)
        out: List[Finding] = []
        server_validated = set()
        for plane in servers:
            server_validated |= set(plane.validated)
        for field, client_path in sorted(client_validated.items()):
            if field in server_validated:
                continue
            for plane in servers:
                if field not in plane.referenced:
                    continue
                line = plane.referenced[field]
                if plane.ctx.is_suppressed(self.id, line):
                    continue
                out.append(Finding(
                    self.id, plane.ctx.path, line, 0,
                    f"field '{field}' is validated only in the client "
                    f"({client_path}); the server references it without "
                    f"a validate_* call and must not trust clients to "
                    f"police their own input",
                ))
        return out


def _find_plane(ctxs: Sequence[FileContext],
                suffix: str) -> Optional[_PlaneFacts]:
    for ctx in ctxs:
        if ctx.path.replace("\\", "/").endswith(suffix):
            return _PlaneFacts(ctx)
    return None


def _is_test_path(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    return "tests" in parts or parts[-1].startswith("test_")

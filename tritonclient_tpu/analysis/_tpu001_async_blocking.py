"""TPU001: blocking calls on async paths.

Two legs:

* Inside an ``async def`` body (stopping at nested sync ``def``s, which run
  on executor threads): calls that block the event loop — ``time.sleep``,
  sync socket / ``http.client`` / ``urllib`` / ``subprocess`` work, file
  I/O via ``open``, and sync gRPC channel construction. ``async with`` /
  ``async for`` bodies and nested ``async def``s are async context like any
  other; a blocking call *bound* through ``functools.partial`` and invoked
  on the async path flags at the invocation (handing the partial to an
  executor is fine — it is never called on the loop there).
* Anywhere: ``time.sleep``. An in-process serving stack runs event loops in
  the same interpreter, so a sleep in sync code is one refactor away from
  stalling an aio transport; deliberately-sync call sites (perf_analyzer
  warmup windows, delay-simulation models) carry
  ``# tpulint: disable=TPU001`` with a justification.
"""

import ast
from typing import Dict, List

from tritonclient_tpu.analysis._engine import FileContext, Finding, Rule

_BLOCKING_EXACT = {
    "time.sleep",
    "open",
    "io.open",
    "os.system",
    "os.popen",
    "os.wait",
    "os.waitpid",
    "grpc.insecure_channel",
    "grpc.secure_channel",
    "socket.create_connection",
    "socket.getaddrinfo",
    "socket.gethostbyname",
    "socket.socket",
}
_BLOCKING_PREFIXES = (
    "http.client.",
    "urllib.request.",
    "requests.",
    "subprocess.",
)


class AsyncBlockingRule(Rule):
    id = "TPU001"
    name = "async-blocking"
    description = (
        "blocking call (time.sleep, sync socket/HTTP/subprocess, file I/O, "
        "sync gRPC) inside an async def, or time.sleep anywhere"
    )

    def check_file(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        self._visit(ctx, ctx.tree, in_async=False, findings=findings,
                    partials={})
        return findings

    def _visit(self, ctx, node, in_async, findings, partials):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.AsyncFunctionDef):
                self._visit(ctx, child, True, findings, dict(partials))
            elif isinstance(child, ast.FunctionDef):
                # Sync defs nested in async functions run off-loop
                # (executors, callbacks): the async context does not extend
                # into them.
                self._visit(ctx, child, False, findings, dict(partials))
            else:
                if isinstance(child, ast.Assign):
                    self._track_partial(ctx, child, partials)
                if isinstance(child, ast.Call):
                    self._check_call(ctx, child, in_async, findings, partials)
                self._visit(ctx, child, in_async, findings, partials)

    def _track_partial(self, ctx, assign: ast.Assign, partials: Dict[str, str]):
        """``name = functools.partial(<blocking>, ...)`` binds the blocking
        call under a new name; record it so invocations flag."""
        bound = self._partial_target(ctx, assign.value)
        for tgt in assign.targets:
            if isinstance(tgt, ast.Name):
                if bound is not None:
                    partials[tgt.id] = bound
                else:
                    partials.pop(tgt.id, None)

    def _partial_target(self, ctx, value) -> "str | None":
        if not isinstance(value, ast.Call):
            return None
        name = ctx.canonical_call_name(value.func)
        if name not in ("functools.partial", "partial") or not value.args:
            return None
        inner = ctx.canonical_call_name(value.args[0])
        if inner is None:
            return None
        if (
            inner == "time.sleep"
            or inner in _BLOCKING_EXACT
            or inner.startswith(_BLOCKING_PREFIXES)
        ):
            return inner
        return None

    def _check_call(self, ctx, call, in_async, findings, partials):
        # Direct invocation of a partial binding a blocking call, or an
        # immediately-invoked `functools.partial(blocking, ...)()`.
        bound = None
        if isinstance(call.func, ast.Name) and call.func.id in partials:
            bound = partials[call.func.id]
        elif isinstance(call.func, ast.Call):
            bound = self._partial_target(ctx, call.func)
        if bound is not None and in_async:
            findings.append(
                Finding(
                    self.id, ctx.path, call.lineno, call.col_offset,
                    f"call invokes a functools.partial binding blocking "
                    f"`{bound}` inside an async def; route it through an "
                    "executor or an aio equivalent",
                )
            )
            return
        name = ctx.canonical_call_name(call.func)
        if name is None:
            return
        if name == "time.sleep":
            if in_async:
                msg = (
                    "time.sleep inside an async def blocks the event loop; "
                    "use `await asyncio.sleep(...)`"
                )
            else:
                msg = (
                    "time.sleep stalls any event loop sharing this "
                    "interpreter when reached from aio paths; use "
                    "`await asyncio.sleep` on async paths or suppress "
                    "deliberately-sync call sites"
                )
            findings.append(
                Finding(self.id, ctx.path, call.lineno, call.col_offset, msg)
            )
            return
        if not in_async:
            return
        if name in _BLOCKING_EXACT or name.startswith(_BLOCKING_PREFIXES):
            findings.append(
                Finding(
                    self.id,
                    ctx.path,
                    call.lineno,
                    call.col_offset,
                    f"blocking call `{name}` inside an async def; route it "
                    "through an executor or an aio equivalent",
                )
            )

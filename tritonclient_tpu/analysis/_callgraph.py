"""Whole-program call-graph + thread-escape substrate for tpulint v3.

TPU009 (guarded-by race detection) and TPU010 (JAX hot-path hazards) are
interprocedural: both need to know who calls whom, which functions run on
which threads, and which locks are held *at entry* to a function — facts
no single ``FileContext`` carries. This module builds that substrate once
per lint run and shares it between the two rules:

* **Per-file summaries** (``summarize_file``) — declarations (classes,
  lock attributes, instance-attribute types, jitted attributes, mutable
  module globals) plus per-function facts: resolved call edges with the
  lexically-held lockset at each call site, ``self.*``/typed-receiver
  attribute accesses (read/write, held locks), thread spawn sites
  (``threading.Thread(target=...)``, executor ``submit``/``map``,
  ``run_in_executor``, ``threading.Timer``), JAX hazard candidates
  (device→host syncs, ``block_until_ready``, jit-in-body, jit static-arg
  drift), and the ``# tpulint: hot-path`` annotation.
* **Graph assembly** (``CallGraph``) — thread roots from spawn targets,
  per-root reachability, "which threads can run this function" sets
  (``main`` plus one identity per spawn target), and a decreasing
  fixpoint for held-at-entry locksets:
  ``entry(f) = ∩ over call sites (held(site) ∪ entry(caller))``, with
  public functions and spawn targets pinned to the empty set (anyone may
  call them lock-free). An access's *effective* lockset is its lexical
  locks ∪ ``entry`` of its function — the interprocedural step that keeps
  ``fleet/_policy.py``-style "caller holds the router lock" helpers from
  being false positives.
* **A sha1-keyed JSON cache** — summaries are serializable; the cache
  stores per-file declarations and function facts keyed by source sha1,
  with function facts additionally guarded by a digest over the *merged*
  project declarations (cross-file resolution inputs). ``--changed``
  re-summarizes only edited files and rebuilds the graph from cache,
  keeping the pre-commit path under two seconds.

Summaries are best-effort static facts, deliberately conservative in the
same places TPU007 is: dynamic call targets that cannot be resolved drop
out of the graph (no edge) rather than guessing.
"""

import ast
import hashlib
import json
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tritonclient_tpu.analysis import _shapes, _taint
from tritonclient_tpu.analysis._engine import (
    FileContext,
    discover_files,
)

#: Lock factories (mirrors TPU007): values are the declaration kind.
_LOCK_FACTORIES = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
    "asyncio.Lock": "Lock",
    "asyncio.Condition": "Condition",
    "tritonclient_tpu.sanitize.named_lock": "Lock",
    "tritonclient_tpu.sanitize.named_rlock": "RLock",
    "tritonclient_tpu.sanitize.named_condition": "Condition",
}

#: Constructors whose instances synchronize internally — attributes of
#: these types never need a guarding lock and are exempt from TPU009.
_SELF_SYNC_FACTORIES = {
    "queue.Queue",
    "queue.SimpleQueue",
    "queue.LifoQueue",
    "queue.PriorityQueue",
    "threading.Event",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
    "threading.Barrier",
    "threading.local",
    "concurrent.futures.ThreadPoolExecutor",
    "concurrent.futures.ProcessPoolExecutor",
}

#: Container-mutating method names (write through a method call) —
#: mirrors TPU002's convention.
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "remove", "pop",
    "popleft", "popitem", "clear", "update", "setdefault", "add",
    "discard", "sort",
}

#: Methods whose writes are construction/teardown, not shared-state races.
_INIT_METHODS = {"__init__", "__post_init__", "__del__", "__enter__"}

#: jax.Array attribute reads that touch metadata only — never force a
#: device→host transfer (shape/dtype introspection is host-side).
_DEVICE_METADATA_ATTRS = {
    "shape", "dtype", "ndim", "size", "nbytes", "sharding", "at",
    "weak_type", "itemsize",
}

#: Call prefixes whose results live on device (taint sources).
_DEVICE_CALL_PREFIXES = ("jax.", "jax.numpy.", "jax.lax.", "jax.random.")

#: Host-coercion callables that force a device→host sync on jax.Array
#: arguments.
_HOST_COERCERS = {"numpy.asarray", "numpy.array", "float", "int", "bool"}

#: Device-array methods that force a sync.
_SYNC_METHODS = {"item", "tolist", "__array__"}

_HOT_RE = re.compile(r"#\s*tpulint:\s*hot-path\b")

#: Condition-variable methods recorded as cv sites (TPU011 substrate).
_CV_METHODS = {"wait", "wait_for", "notify", "notify_all"}

#: Methods on self-synchronizing objects that carry a wakeup-visible
#: state change (queue put, event set/clear, semaphore release) — they
#: count as predicate writes for the notify-discipline check.
_SIGNAL_METHODS = {"put", "put_nowait", "set", "clear", "release"}

CACHE_VERSION = 7  # v7: per-function shape/sharding/donation facts (TPU015-TPU017)


def modkey_for(path: str) -> str:
    """File stem used in function/lock keys (``__init__.py`` maps to its
    package directory name) — identical to TPU007's convention."""
    stem = os.path.basename(path)
    if stem == "__init__.py":
        stem = os.path.basename(os.path.dirname(path)) or stem
    return stem[:-3] if stem.endswith(".py") else stem


# ---------------------------------------------------------------------------
# summary records (JSON-native: plain dicts/lists, light wrapper classes)
# ---------------------------------------------------------------------------


class Access:
    """One read/write of a shared attribute.

    ``owner`` is a class name or a module key (module globals); ``locks``
    is the lexically-held lockset at the access site.
    """

    __slots__ = ("owner", "attr", "write", "locks", "line", "col", "in_init")

    def __init__(self, owner, attr, write, locks, line, col, in_init):
        self.owner = owner
        self.attr = attr
        self.write = write
        self.locks = tuple(locks)
        self.line = line
        self.col = col
        self.in_init = in_init

    def to_json(self):
        return [self.owner, self.attr, int(self.write), list(self.locks),
                self.line, self.col, int(self.in_init)]

    @classmethod
    def from_json(cls, row):
        return cls(row[0], row[1], bool(row[2]), row[3], row[4], row[5],
                   bool(row[6]))


class Hazard:
    """One JAX hazard candidate (classified by TPU010 if the function is
    hot-reachable). ``kind`` ∈ host-sync | bool-sync | block-sync |
    jit-in-body | static-drift; ``guarded`` marks cache-miss-guarded jit
    construction (``if key not in cache: jit(...)``) which is benign."""

    __slots__ = ("kind", "detail", "line", "col", "in_loop", "guarded")

    def __init__(self, kind, detail, line, col, in_loop, guarded=False):
        self.kind = kind
        self.detail = detail
        self.line = line
        self.col = col
        self.in_loop = in_loop
        self.guarded = guarded

    def to_json(self):
        return [self.kind, self.detail, self.line, self.col,
                int(self.in_loop), int(self.guarded)]

    @classmethod
    def from_json(cls, row):
        return cls(row[0], row[1], row[2], row[3], bool(row[4]), bool(row[5]))


class CvSite:
    """One condition-variable operation (TPU011's substrate).

    ``cv`` is the resolved lock key of a declared Condition; ``kind`` ∈
    wait | wait_for | notify | notify_all. ``preds`` are the ``self.*``
    attribute names the site's predicate mentions (the enclosing
    ``while``/``if`` test for a wait, the lambda body for a wait_for).
    ``locks`` is the lexically-held lockset at the site.
    """

    __slots__ = ("kind", "cv", "line", "col", "timed", "in_loop",
                 "result_used", "preds", "locks")

    def __init__(self, kind, cv, line, col, timed, in_loop, result_used,
                 preds, locks):
        self.kind = kind
        self.cv = cv
        self.line = line
        self.col = col
        self.timed = timed
        self.in_loop = in_loop
        self.result_used = result_used
        self.preds = tuple(preds)
        self.locks = tuple(locks)

    def to_json(self):
        return [self.kind, self.cv, self.line, self.col, int(self.timed),
                int(self.in_loop), int(self.result_used),
                list(self.preds), list(self.locks)]

    @classmethod
    def from_json(cls, row):
        return cls(row[0], row[1], row[2], row[3], bool(row[4]),
                   bool(row[5]), bool(row[6]), row[7], row[8])


class FunctionSummary:
    __slots__ = ("key", "path", "line", "cls", "name", "public", "hot",
                 "is_spawn_site", "calls", "accesses", "spawns", "hazards",
                 "cvsites", "signals", "taint", "shapes")

    def __init__(self, key, path, line, cls_name, name, public, hot):
        self.key = key
        self.path = path
        self.line = line
        self.cls = cls_name
        self.name = name
        self.public = public
        self.hot = hot
        # [(callee_key, (held locks...), line)]
        self.calls: List[Tuple[str, Tuple[str, ...], int]] = []
        self.accesses: List[Access] = []
        # [(target_key or None, kind, line)]
        self.spawns: List[Tuple[Optional[str], str, int]] = []
        self.hazards: List[Hazard] = []
        self.cvsites: List[CvSite] = []
        # [(attr, method, line)] — _SIGNAL_METHODS calls on attributes
        # (queue put / event set): wakeup-visible state changes.
        self.signals: List[Tuple[str, str, int]] = []
        # Per-function taint facts (TPU013); None when the function has
        # no parameters, sinks, or forwarded taint worth recording.
        self.taint = None
        # Per-function shape/sharding/donation facts (TPU015-TPU017);
        # None when the function has nothing worth recording.
        self.shapes = None

    def to_json(self):
        return {
            "key": self.key, "path": self.path, "line": self.line,
            "cls": self.cls, "name": self.name,
            "public": int(self.public), "hot": int(self.hot),
            "calls": [[c, list(h), ln] for c, h, ln in self.calls],
            "accesses": [a.to_json() for a in self.accesses],
            "spawns": [[t, k, ln] for t, k, ln in self.spawns],
            "hazards": [h.to_json() for h in self.hazards],
            "cvsites": [s.to_json() for s in self.cvsites],
            "signals": [[a, m, ln] for a, m, ln in self.signals],
            "taint": self.taint.to_json() if self.taint else None,
            "shapes": self.shapes.to_json() if self.shapes else None,
        }

    @classmethod
    def from_json(cls, d):
        fn = cls(d["key"], d["path"], d["line"], d["cls"], d["name"],
                 bool(d["public"]), bool(d["hot"]))
        fn.calls = [(c, tuple(h), ln) for c, h, ln in d["calls"]]
        fn.accesses = [Access.from_json(r) for r in d["accesses"]]
        fn.spawns = [(t, k, ln) for t, k, ln in d["spawns"]]
        fn.hazards = [Hazard.from_json(r) for r in d["hazards"]]
        fn.cvsites = [CvSite.from_json(r) for r in d.get("cvsites", [])]
        fn.signals = [(a, m, ln) for a, m, ln in d.get("signals", [])]
        raw_taint = d.get("taint")
        if raw_taint:
            fn.taint = _taint.FunctionTaint.from_json(raw_taint)
        raw_shapes = d.get("shapes")
        if raw_shapes:
            fn.shapes = _shapes.FunctionShapes.from_json(raw_shapes)
        return fn


# ---------------------------------------------------------------------------
# pass 1: declarations (file-local, cacheable by source sha alone)
# ---------------------------------------------------------------------------


def extract_decls(ctx: FileContext) -> dict:
    """Declaration facts other files' summaries may depend on."""
    modkey = modkey_for(ctx.path)
    decls = {
        "modkey": modkey,
        "classes": [],
        "class_locks": {},    # cls -> {attr: lock key}
        "lock_kinds": {},     # lock key -> Lock|RLock|Condition
        "attr_types": {},     # cls -> {attr: class name}
        "attr_elem_types": {},  # cls -> {attr: element class of container}
        "class_methods": {},  # cls -> [method names]
        "exempt_attrs": {},   # cls -> [attr] (self-synchronizing types)
        "jit_attrs": {},      # cls -> {attr: has_static_args}
        "return_types": {},   # fn key -> [class name, is_element_of_list]
        "module_globals": [],  # mutable module-level names
    }
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign):
            kind = _lock_factory_kind(ctx, node.value)
            mutable = _is_mutable_literal(ctx, node.value)
            for tgt in node.targets:
                if not isinstance(tgt, ast.Name):
                    continue
                if kind:
                    decls["lock_kinds"][f"{modkey}:{tgt.id}"] = kind
                elif mutable:
                    decls["module_globals"].append(tgt.id)
    for cls in [n for n in ast.walk(ctx.tree) if isinstance(n, ast.ClassDef)]:
        decls["classes"].append(cls.name)
        locks = decls["class_locks"].setdefault(cls.name, {})
        types = decls["attr_types"].setdefault(cls.name, {})
        elem_types = decls["attr_elem_types"].setdefault(cls.name, {})
        exempt = decls["exempt_attrs"].setdefault(cls.name, [])
        jits = decls["jit_attrs"].setdefault(cls.name, {})
        methods = decls["class_methods"].setdefault(cls.name, [])
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            methods.append(meth.name)
            ret = _annotation_class(meth.returns)
            if ret:
                decls["return_types"][f"{cls.name}.{meth.name}"] = list(ret)
            ptypes = _param_types(meth)
            for node in ast.walk(meth):
                if (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Name)
                        and node.value.id in ptypes):
                    for tgt in node.targets:
                        if _is_self_attr(tgt):
                            types[tgt.attr] = ptypes[node.value.id]
        for node in ast.walk(cls):
            if isinstance(node, ast.AnnAssign) and _is_self_attr(
                    node.target):
                got = _annotation_class(node.annotation)
                if got:
                    if got[1]:
                        elem_types[node.target.attr] = got[0]
                    else:
                        types[node.target.attr] = got[0]
                continue
            if not isinstance(node, ast.Assign):
                continue
            kind = _lock_factory_kind(ctx, node.value)
            selfsync = _call_name_in(ctx, node.value, _SELF_SYNC_FACTORIES)
            jit = _jit_factory(ctx, node.value)
            ctor = _ctor_class(ctx, node.value)
            for tgt in node.targets:
                if _is_self_attr(tgt):
                    if kind:
                        key = f"{cls.name}.{tgt.attr}"
                        locks[tgt.attr] = key
                        decls["lock_kinds"][key] = kind
                    elif selfsync:
                        exempt.append(tgt.attr)
                    elif jit is not None:
                        jits[tgt.attr] = jit
                    elif ctor:
                        types[tgt.attr] = ctor
                elif isinstance(tgt, ast.Subscript) and ctor:
                    base = tgt.value
                    if _is_self_attr(base):
                        types[base.attr] = ctor
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Global):
                    for name in sub.names:
                        if name not in decls["module_globals"]:
                            decls["module_globals"].append(name)
            if not isinstance(_parent_class(ctx, node), ast.ClassDef):
                ret = _annotation_class(node.returns)
                if ret:
                    decls["return_types"][f"{modkey}:{node.name}"] = list(ret)
    return decls


def _parent_class(ctx, node):
    cur = ctx.parents.get(node)
    while cur is not None and not isinstance(cur, ast.ClassDef):
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None
        cur = ctx.parents.get(cur)
    return cur


def _is_self_attr(node) -> bool:
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self")


def _lock_factory_kind(ctx, value) -> Optional[str]:
    for sub in ast.walk(value):
        if isinstance(sub, ast.Call):
            name = ctx.canonical_call_name(sub.func)
            if name in _LOCK_FACTORIES:
                return _LOCK_FACTORIES[name]
    return None


def _call_name_in(ctx, value, names: Set[str]) -> bool:
    for sub in ast.walk(value):
        if isinstance(sub, ast.Call):
            if ctx.canonical_call_name(sub.func) in names:
                return True
    return False


def _jit_factory(ctx, value) -> Optional[bool]:
    """True/False (= has static args) when ``value`` builds a jitted
    callable (``jax.jit(...)``, possibly through functools.partial)."""
    for sub in ast.walk(value):
        if isinstance(sub, ast.Call):
            name = ctx.canonical_call_name(sub.func)
            if name in ("jax.jit", "jax.pmap"):
                static = any(
                    kw.arg in ("static_argnums", "static_argnames")
                    for kw in sub.keywords if kw.arg
                )
                return static
    return None


def _ctor_class(ctx, value) -> Optional[str]:
    for sub in ast.walk(value):
        if isinstance(sub, ast.Call):
            name = ctx.canonical_call_name(sub.func)
            if name is None:
                continue
            tail = name.rsplit(".", 1)[-1]
            if tail and tail[0].isupper():
                return tail
    return None


def _is_mutable_literal(ctx, value) -> bool:
    if isinstance(value, (ast.Dict, ast.List, ast.Set)):
        return True
    if isinstance(value, ast.Call):
        name = ctx.canonical_call_name(value.func)
        return name in (
            "dict", "list", "set", "collections.OrderedDict",
            "collections.defaultdict", "collections.deque",
        )
    return False


def _param_types(func) -> Dict[str, str]:
    out = {}
    args = (list(func.args.posonlyargs) + list(func.args.args)
            + list(func.args.kwonlyargs))
    for arg in args:
        got = _annotation_class(arg.annotation)
        if got:
            out[arg.arg] = got[0]
    return out


def _annotation_class(ann) -> Optional[Tuple[str, bool]]:
    """(class name, is_list_element) from an annotation node."""
    if ann is None:
        return None
    if isinstance(ann, ast.Subscript):
        # List[Replica] / Optional[Replica] / Sequence["Replica"] /
        # Dict[str, Replica] (the *value* type is what iteration over
        # ``.values()`` yields, the overwhelmingly common access shape).
        base = ann.value
        container = base.attr if isinstance(base, ast.Attribute) else (
            base.id if isinstance(base, ast.Name) else "")
        inner = ann.slice
        if container in ("Dict", "dict", "Mapping", "MutableMapping",
                         "DefaultDict", "OrderedDict") and isinstance(
                inner, ast.Tuple) and len(inner.elts) == 2:
            got = _annotation_class(inner.elts[1])
            return (got[0], True) if got else None
        got = _annotation_class(inner)
        if got:
            is_list = container in ("List", "list", "Sequence", "Iterable",
                                    "Tuple", "tuple", "Iterator")
            return (got[0], is_list or got[1])
        return None
    if isinstance(ann, ast.Name):
        name = ann.id
    elif isinstance(ann, ast.Attribute):
        name = ann.attr
    elif isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        name = ann.value.rsplit(".", 1)[-1].rstrip("]")
    else:
        return None
    if name and name[0].isupper():
        return (name, False)
    return None


def _is_device_annotation(ann) -> bool:
    """Parameter annotated as a device array (jax.Array / jnp.ndarray)."""
    if isinstance(ann, ast.Attribute) and ann.attr in ("Array", "ndarray"):
        return True
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.rsplit(".", 1)[-1] in ("Array", "ndarray")
    return False


# ---------------------------------------------------------------------------
# pass 2: per-function facts (needs merged declarations)
# ---------------------------------------------------------------------------


class _Decls:
    """Merged project declarations, indexed for resolution."""

    def __init__(self, per_file: Dict[str, dict]):
        self.known_classes: Set[str] = set()
        self.class_locks: Dict[str, Dict[str, str]] = {}
        self.lock_kinds: Dict[str, str] = {}
        self.attr_types: Dict[str, Dict[str, str]] = {}
        self.attr_elem_types: Dict[str, Dict[str, str]] = {}
        self.class_methods: Dict[str, Set[str]] = {}
        self.exempt_attrs: Dict[str, Set[str]] = {}
        self.jit_attrs: Dict[str, Dict[str, bool]] = {}
        self.return_types: Dict[str, Tuple[str, bool]] = {}
        self.module_globals: Dict[str, Set[str]] = {}
        for decls in per_file.values():
            self.known_classes.update(decls["classes"])
            for cls, locks in decls["class_locks"].items():
                self.class_locks.setdefault(cls, {}).update(locks)
            self.lock_kinds.update(decls["lock_kinds"])
            for cls, types in decls["attr_types"].items():
                self.attr_types.setdefault(cls, {}).update(types)
            for cls, types in decls.get("attr_elem_types", {}).items():
                self.attr_elem_types.setdefault(cls, {}).update(types)
            for cls, meths in decls.get("class_methods", {}).items():
                self.class_methods.setdefault(cls, set()).update(meths)
            for cls, attrs in decls["exempt_attrs"].items():
                self.exempt_attrs.setdefault(cls, set()).update(attrs)
            for cls, jits in decls["jit_attrs"].items():
                self.jit_attrs.setdefault(cls, {}).update(jits)
            for key, val in decls["return_types"].items():
                self.return_types[key] = (val[0], bool(val[1]))
            self.module_globals[decls["modkey"]] = set(
                decls["module_globals"])

    def digest(self, per_file: Dict[str, dict]) -> str:
        blob = json.dumps(per_file, sort_keys=True).encode()
        return hashlib.sha1(blob).hexdigest()


class _FnWalker:
    """Statement walker for one top-level function: tracks held locks,
    device-array taint, loop variables, and cache-guard depth; emits a
    FunctionSummary per function (nested defs get their own, keyed
    ``<parent>.<locals>.<name>``, with an empty held stack — their bodies
    run later, on whatever thread invokes them)."""

    def __init__(self, ctx: FileContext, decls: _Decls, modkey: str,
                 hot_lines: Set[int]):
        self.ctx = ctx
        self.decls = decls
        self.modkey = modkey
        self.hot_lines = hot_lines
        self.out: List[FunctionSummary] = []

    # -- entry ---------------------------------------------------------------

    def walk_function(self, node, cls_name: Optional[str], key: str,
                      nested_in: Optional[str] = None):
        public = not node.name.startswith("_") and nested_in is None
        fn = FunctionSummary(
            key, self.ctx.path, node.lineno, cls_name, node.name, public,
            self._is_hot(node),
        )
        self.out.append(fn)
        state = {
            "fn": fn,
            "cls": cls_name,
            "held": [],
            "var_types": _param_types(node),
            "list_elem": {},     # var -> element class (list-typed vars)
            "tainted": {a.arg for a in (
                list(node.args.posonlyargs) + list(node.args.args)
                + list(node.args.kwonlyargs))
                if _is_device_annotation(a.annotation)},
            "local_jits": {},    # name -> has_static_args
            "local_defs": {},    # name -> nested function key
            "loop_vars": set(),
            "loop_depth": 0,
            # A memoization decorator (functools.lru_cache / cache) makes
            # the whole body a build-once region: jit construction inside
            # it compiles once per distinct argument, not per call.
            "guard_depth": 1 if _is_memoized(node) else 0,
            "in_init": node.name in _INIT_METHODS,
        }
        # Objects constructed in this function are thread-local until
        # published; accesses through them are not shared-state accesses.
        state["fresh_vars"] = set()
        # Scope handling for module globals: a name assigned locally
        # without a `global` declaration shadows the module global — its
        # accesses are local, not shared state.
        declared_global = {
            n for g in ast.walk(node) if isinstance(g, ast.Global)
            for n in g.names
        }
        state["shadowed"] = {
            n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)
        } - declared_global
        # Pre-scan for sibling nested defs so forward refs (spawn before
        # def, as in `Thread(target=loop)` above `def loop():`) resolve.
        for stmt in ast.walk(node):
            if (isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and stmt is not node):
                state["local_defs"][stmt.name] = (
                    f"{key}.<locals>.{stmt.name}")
        self._walk_body(node.body, state)

    def _is_hot(self, node) -> bool:
        first = min([node.lineno] + [d.lineno for d in node.decorator_list])
        return bool(self.hot_lines & {first - 1, first, node.lineno})

    # -- statements ----------------------------------------------------------

    def _walk_body(self, stmts, state):
        for stmt in stmts:
            self._walk_stmt(stmt, state)

    def _walk_stmt(self, stmt, state):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            nested_key = state["local_defs"].get(
                stmt.name, f"{state['fn'].key}.<locals>.{stmt.name}")
            self.walk_function(stmt, state["cls"], nested_key,
                               nested_in=state["fn"].key)
            return
        if isinstance(stmt, ast.ClassDef):
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired = []
            for item in stmt.items:
                lock = self._resolve_lock_expr(item.context_expr, state)
                if lock is not None:
                    acquired.append(lock)
                else:
                    self._scan_expr(item.context_expr, state)
            state["held"].extend(acquired)
            self._walk_body(stmt.body, state)
            if acquired:
                del state["held"][-len(acquired):]
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._scan_expr(stmt.iter, state)
            elem = self._iter_element_class(stmt.iter, state)
            if elem and isinstance(stmt.target, ast.Name):
                state["var_types"][stmt.target.id] = elem
            if self._expr_tainted(stmt.iter, state) and isinstance(
                    stmt.target, ast.Name):
                state["tainted"].add(stmt.target.id)
            new_loop_vars = {
                n.id for n in ast.walk(stmt.target)
                if isinstance(n, ast.Name)
            }
            state["loop_vars"] |= new_loop_vars
            state["loop_depth"] += 1
            self._walk_body(stmt.body, state)
            self._walk_body(stmt.orelse, state)
            state["loop_depth"] -= 1
            return
        if isinstance(stmt, ast.While):
            self._branch_sync_check(stmt.test, state)
            self._scan_expr(stmt.test, state)
            state["loop_depth"] += 1
            self._walk_body(stmt.body, state)
            self._walk_body(stmt.orelse, state)
            state["loop_depth"] -= 1
            return
        if isinstance(stmt, ast.If):
            self._branch_sync_check(stmt.test, state)
            self._scan_expr(stmt.test, state)
            guard = _is_cache_guard(stmt.test)
            narrowed = self._isinstance_narrow(stmt.test)
            saved = dict(state["var_types"])
            state["var_types"].update(narrowed)
            if guard:
                state["guard_depth"] += 1
            self._walk_body(stmt.body, state)
            if guard:
                state["guard_depth"] -= 1
            state["var_types"] = saved
            self._walk_body(stmt.orelse, state)
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._handle_assign(stmt, state)
            return
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            self._scan_expr(stmt.value, state)
            return
        # Generic compound/simple statement.
        for field, value in ast.iter_fields(stmt):
            if field in ("body", "orelse", "finalbody", "handlers"):
                continue
            nodes = value if isinstance(value, list) else [value]
            for node in nodes:
                if isinstance(node, ast.AST):
                    self._scan_expr(node, state)
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if sub:
                self._walk_body(sub, state)
        for handler in getattr(stmt, "handlers", []) or []:
            self._walk_body(handler.body, state)

    def _handle_assign(self, stmt, state):
        value = stmt.value
        if value is not None:
            self._scan_expr(value, state)
        targets = (stmt.targets if isinstance(stmt, ast.Assign)
                   else [stmt.target])
        tainted = value is not None and self._expr_tainted(value, state)
        ctor = _ctor_class(self.ctx, value) if value is not None else None
        ret = self._call_return_class(value, state) if value is not None \
            else None
        jit = _jit_factory(self.ctx, value) if value is not None else None
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                if tainted:
                    state["tainted"].add(tgt.id)
                else:
                    state["tainted"].discard(tgt.id)
                if jit is not None:
                    state["local_jits"][tgt.id] = jit
                if ret is not None:
                    cls, is_list = ret
                    state["fresh_vars"].discard(tgt.id)
                    if is_list:
                        state["list_elem"][tgt.id] = cls
                        state["var_types"].pop(tgt.id, None)
                    else:
                        state["var_types"][tgt.id] = cls
                elif ctor:
                    state["var_types"][tgt.id] = ctor
                    if isinstance(value, ast.Call) and _ctor_class(
                            self.ctx, value) == ctor:
                        state["fresh_vars"].add(tgt.id)
            elif isinstance(tgt, ast.Tuple) and tainted:
                for el in tgt.elts:
                    if isinstance(el, ast.Name):
                        state["tainted"].add(el.id)
            # Attribute/subscript stores are accesses, picked up below.
            self._scan_expr(tgt, state)

    # -- expression scanning --------------------------------------------------

    def _scan_expr(self, expr, state):
        """Record calls, spawns, attribute accesses, and JAX hazards in
        one expression tree."""
        for node in ast.walk(expr):
            if isinstance(node, (ast.ListComp, ast.SetComp,
                                 ast.GeneratorExp, ast.DictComp)):
                # Bind generator targets before their uses are visited
                # (ast.walk is breadth-first, so the comprehension node
                # precedes its children) — `r._snapshot_locked() for r
                # in sorted(self._replicas.values())` resolves r.
                for gen in node.generators:
                    elem = self._iter_element_class(gen.iter, state)
                    if elem and isinstance(gen.target, ast.Name):
                        state["var_types"][gen.target.id] = elem
            elif isinstance(node, ast.Call):
                self._handle_call(node, state)
            elif isinstance(node, ast.Attribute):
                self._maybe_access(node, state)
            elif isinstance(node, ast.Name):
                self._maybe_global_access(node, state)

    def _handle_call(self, call, state):
        fn = state["fn"]
        name = self.ctx.canonical_call_name(call.func)
        # Thread spawn sites.
        spawn = self._spawn_target(call, name, state)
        if spawn is not None:
            fn.spawns.append(spawn + (call.lineno,))
        # Call edge.
        callee = self._resolve_callee(call, state)
        if callee is not None:
            fn.calls.append((callee, tuple(state["held"]), call.lineno))
        # Condition-variable sites and wakeup signals (TPU011).
        self._maybe_cvsite(call, state)
        # JAX hazards.
        self._call_hazards(call, name, state)

    # -- condition-variable sites (TPU011 substrate) --------------------------

    def _maybe_cvsite(self, call, state):
        func = call.func
        if not isinstance(func, ast.Attribute):
            return
        fn = state["fn"]
        # Wakeup-visible state changes: queue.put / event.set through
        # any receiver. These count as predicate writes for notify
        # checks, so broader recognition only makes TPU011 quieter.
        if func.attr in _SIGNAL_METHODS:
            recv = func.value
            if isinstance(recv, ast.Attribute):
                fn.signals.append((recv.attr, func.attr, call.lineno))
            elif isinstance(recv, ast.Name) and recv.id != "self":
                fn.signals.append((recv.id, func.attr, call.lineno))
        if func.attr not in _CV_METHODS:
            return
        # Only sites whose receiver resolves to a *declared Condition*
        # are cv sites — `slot.event.wait()` (an Event) stays out.
        cv = self._resolve_lock_expr(func.value, state)
        if cv is None or self.decls.lock_kinds.get(cv) != "Condition":
            return
        kind = func.attr
        timed = self._cv_timed(call, kind)
        result_used = not isinstance(
            self.ctx.parents.get(call), ast.Expr)
        preds = self._cv_preds(call, kind, state)
        fn.cvsites.append(CvSite(
            kind, cv, call.lineno, call.col_offset, timed,
            state["loop_depth"] > 0, result_used, preds,
            tuple(state["held"]),
        ))

    @staticmethod
    def _cv_timed(call, kind) -> bool:
        # wait(timeout) — positional 0; wait_for(pred, timeout) — pos 1.
        pos = 0 if kind == "wait" else 1
        timeout = None
        if len(call.args) > pos:
            timeout = call.args[pos]
        for kw in call.keywords:
            if kw.arg == "timeout":
                timeout = kw.value
        if timeout is None:
            return False
        return not (isinstance(timeout, ast.Constant)
                    and timeout.value is None)

    def _cv_preds(self, call, kind, state) -> Tuple[str, ...]:
        """``self.*`` attribute names the wait's predicate reads: the
        enclosing ``while``/``if`` test for a wait, the predicate
        callable for a wait_for."""
        preds = set()

        def collect(tree):
            for node in ast.walk(tree):
                if isinstance(node, ast.Attribute) and _is_self_attr(node):
                    cls = state["cls"]
                    if cls and node.attr in self.decls.class_locks.get(
                            cls, {}):
                        continue
                    preds.add(node.attr)

        if kind in ("wait_for",) and (call.args or call.keywords):
            pred_arg = call.args[0] if call.args else None
            for kw in call.keywords:
                if kw.arg == "predicate":
                    pred_arg = kw.value
            if pred_arg is not None:
                collect(pred_arg)
        if kind in ("wait", "wait_for"):
            node = call
            while node is not None:
                parent = self.ctx.parents.get(node)
                if isinstance(parent,
                              (ast.FunctionDef, ast.AsyncFunctionDef)):
                    break
                if isinstance(parent, ast.While) or (
                        isinstance(parent, ast.If)
                        and node in parent.body):
                    collect(parent.test)
                node = parent
        return tuple(sorted(preds))

    def _spawn_target(self, call, name, state) -> Optional[Tuple[Optional[str], str]]:
        def resolve(arg):
            # functools.partial(self._run, ...) unwraps to its first arg.
            if isinstance(arg, ast.Call):
                inner = self.ctx.canonical_call_name(arg.func)
                if inner == "functools.partial" and arg.args:
                    return resolve(arg.args[0])
                return None
            return self._callable_key(arg, state)

        if name in ("threading.Thread", "threading.Timer"):
            kind = name.split(".")[-1]
            for kw in call.keywords:
                if kw.arg in ("target", "function"):
                    return (resolve(kw.value), kind)
            if name == "threading.Timer" and len(call.args) >= 2:
                return (resolve(call.args[1]), kind)
            if call.args:
                return (resolve(call.args[0]), kind)
            return None
        func = call.func
        if isinstance(func, ast.Attribute):
            if func.attr == "submit" and call.args:
                return (resolve(call.args[0]), "submit")
            if func.attr == "run_in_executor" and len(call.args) >= 2:
                return (resolve(call.args[1]), "run_in_executor")
            if func.attr == "map" and call.args:
                recv = func.value
                recv_name = recv.id if isinstance(recv, ast.Name) else (
                    recv.attr if isinstance(recv, ast.Attribute) else "")
                if "executor" in recv_name.lower() or "pool" in \
                        recv_name.lower():
                    return (resolve(call.args[0]), "map")
        return None

    def _callable_key(self, node, state) -> Optional[str]:
        """Function key for a callable reference (not a call)."""
        if isinstance(node, ast.Attribute) and isinstance(
                node.value, ast.Name):
            base, attr = node.value.id, node.attr
            if base == "self" and state["cls"]:
                return f"{state['cls']}.{attr}"
            vtype = state["var_types"].get(base)
            if vtype:
                return f"{vtype}.{attr}"
            return None
        if isinstance(node, ast.Name):
            if node.id in state["local_defs"]:
                return state["local_defs"][node.id]
            target = self.ctx.aliases.get(node.id)
            if target:
                mod, _, tail = target.rpartition(".")
                modstem = mod.rsplit(".", 1)[-1] if mod else ""
                return f"{modstem}:{tail}" if modstem else None
            return f"{self.modkey}:{node.id}"
        return None

    def _resolve_callee(self, call, state) -> Optional[str]:
        func = call.func
        cls, var_types = state["cls"], state["var_types"]
        if isinstance(func, ast.Attribute) and isinstance(
                func.value, ast.Name):
            base, meth = func.value.id, func.attr
            if base == "self" and cls:
                return f"{cls}.{meth}"
            vtype = var_types.get(base)
            if vtype:
                return f"{vtype}.{meth}"
            target = self.ctx.aliases.get(base)
            if target:
                modstem = target.rsplit(".", 1)[-1]
                return f"{modstem}:{meth}"
            return None
        if isinstance(func, ast.Attribute):
            inner = func.value
            if _is_self_attr(inner) and cls:
                vtype = self.decls.attr_types.get(cls, {}).get(inner.attr)
                if vtype:
                    return f"{vtype}.{func.attr}"
            return None
        if isinstance(func, ast.Name):
            if func.id in state["local_defs"]:
                return state["local_defs"][func.id]
            target = self.ctx.aliases.get(func.id)
            if target:
                mod, _, name = target.rpartition(".")
                modstem = mod.rsplit(".", 1)[-1] if mod else ""
                return f"{modstem}:{name}" if modstem else None
            if func.id in self.decls.known_classes:
                return f"{func.id}.__init__"
            return f"{self.modkey}:{func.id}"
        return None

    def _call_return_class(self, value, state) -> Optional[Tuple[str, bool]]:
        if not isinstance(value, ast.Call):
            return None
        callee = self._resolve_callee(value, state)
        if callee is None:
            return None
        return self.decls.return_types.get(callee)

    def _iter_element_class(self, it, state) -> Optional[str]:
        if isinstance(it, ast.Name):
            return state["list_elem"].get(it.id)
        if isinstance(it, ast.Call):
            func = it.func
            # Order/shape-preserving builtins pass the element through.
            if isinstance(func, ast.Name) and func.id in (
                    "sorted", "list", "tuple", "reversed", "iter",
                    "set") and it.args:
                return self._iter_element_class(it.args[0], state)
            # dict-of-T iteration: self._replicas.values() where the
            # attr is annotated Dict[str, T].
            if isinstance(func, ast.Attribute) and func.attr == "values":
                recv = func.value
                if _is_self_attr(recv) and state["cls"]:
                    return self.decls.attr_elem_types.get(
                        state["cls"], {}).get(recv.attr)
            ret = self._call_return_class(it, state)
            if ret and ret[1]:
                return ret[0]
        if _is_self_attr(it) and state["cls"]:
            return self.decls.attr_elem_types.get(
                state["cls"], {}).get(it.attr)
        return None

    # -- attribute accesses ---------------------------------------------------

    def _maybe_access(self, node: ast.Attribute, state):
        owner = None
        if isinstance(node.value, ast.Name):
            base = node.value.id
            if base == "self" and state["cls"]:
                owner = state["cls"]
            elif base in state["fresh_vars"]:
                return  # locally constructed: thread-local until published
            else:
                owner = state["var_types"].get(base)
        elif _is_self_attr(node.value) and state["cls"]:
            owner = self.decls.attr_types.get(
                state["cls"], {}).get(node.value.attr)
        if owner is None:
            return
        attr = node.attr
        if attr in self.decls.class_locks.get(owner, {}):
            return  # lock attributes are the guards, not the guarded
        if attr in self.decls.exempt_attrs.get(owner, ()):
            return
        if attr in self.decls.jit_attrs.get(owner, {}):
            return  # compiled-callable handles: written once, then called
        if attr in self.decls.class_methods.get(owner, ()):
            return  # bound-method references (Thread targets, callbacks)
        # A plain method call on self/typed receiver is not a state access.
        parent = self.ctx.parents.get(node)
        if isinstance(parent, ast.Call) and parent.func is node:
            return
        write = self._is_write(node, state)
        fn = state["fn"]
        fn.accesses.append(Access(
            owner, attr, write, tuple(state["held"]),
            node.lineno, node.col_offset,
            state["in_init"] and owner == state["cls"],
        ))

    def _is_write(self, node: ast.Attribute, state) -> bool:
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            return True
        parent = self.ctx.parents.get(node)
        if isinstance(parent, ast.AugAssign) and parent.target is node:
            return True
        # self.X[k] = v / self.X[k] += v — subscript store through X.
        if isinstance(parent, ast.Subscript) and parent.value is node:
            if isinstance(parent.ctx, (ast.Store, ast.Del)):
                return True
            grand = self.ctx.parents.get(parent)
            if isinstance(grand, ast.AugAssign) and grand.target is parent:
                return True
        # self.X.append(v) — container mutator.
        if (isinstance(parent, ast.Attribute) and parent.value is node
                and parent.attr in _MUTATORS):
            grand = self.ctx.parents.get(parent)
            if isinstance(grand, ast.Call) and grand.func is parent:
                return True
        return False

    def _maybe_global_access(self, node: ast.Name, state):
        if node.id not in self.decls.module_globals.get(self.modkey, ()):
            return
        if node.id in state["shadowed"]:
            return
        write = isinstance(node.ctx, (ast.Store, ast.Del))
        if not write:
            parent = self.ctx.parents.get(node)
            if isinstance(parent, ast.AugAssign) and parent.target is node:
                write = True
            elif (isinstance(parent, ast.Subscript)
                  and parent.value is node
                  and isinstance(parent.ctx, (ast.Store, ast.Del))):
                write = True
            elif (isinstance(parent, ast.Attribute)
                  and parent.value is node and parent.attr in _MUTATORS):
                grand = self.ctx.parents.get(parent)
                write = isinstance(grand, ast.Call) and grand.func is parent
        state["fn"].accesses.append(Access(
            self.modkey, node.id, write, tuple(state["held"]),
            node.lineno, node.col_offset, False,
        ))

    # -- lock resolution (mirrors TPU007) ------------------------------------

    def _resolve_lock_expr(self, expr, state) -> Optional[str]:
        cls, var_types = state["cls"], state["var_types"]
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name):
            base, attr = expr.value.id, expr.attr
            if base == "self" and cls:
                key = self.decls.class_locks.get(cls, {}).get(attr)
                if key:
                    return key
            vtype = var_types.get(base)
            if vtype:
                return self.decls.class_locks.get(vtype, {}).get(attr)
            return None
        if (isinstance(expr, ast.Attribute) and _is_self_attr(expr.value)
                and cls):
            vtype = self.decls.attr_types.get(cls, {}).get(expr.value.attr)
            if vtype:
                return self.decls.class_locks.get(vtype, {}).get(expr.attr)
            return None
        if isinstance(expr, ast.Name):
            key = f"{self.modkey}:{expr.id}"
            if key in self.decls.lock_kinds:
                return key
            target = self.ctx.aliases.get(expr.id)
            if target:
                mod, _, name = target.rpartition(".")
                modstem = mod.rsplit(".", 1)[-1] if mod else ""
                key = f"{modstem}:{name}"
                if key in self.decls.lock_kinds:
                    return key
        return None

    def _isinstance_narrow(self, test) -> Dict[str, str]:
        if (isinstance(test, ast.Call)
                and isinstance(test.func, ast.Name)
                and test.func.id == "isinstance"
                and len(test.args) == 2
                and isinstance(test.args[0], ast.Name)):
            type_arg = test.args[1]
            if isinstance(type_arg, ast.Name):
                return {test.args[0].id: type_arg.id}
            if isinstance(type_arg, ast.Attribute):
                return {test.args[0].id: type_arg.attr}
        return {}

    # -- JAX hazards ----------------------------------------------------------

    def _expr_tainted(self, expr, state) -> bool:
        """Does this expression (transitively) hold a device array?"""
        if isinstance(expr, ast.Name):
            return expr.id in state["tainted"]
        if isinstance(expr, ast.Attribute):
            if expr.attr in _DEVICE_METADATA_ATTRS:
                return False  # metadata access never forces a transfer
            return self._expr_tainted(expr.value, state)
        if isinstance(expr, ast.Subscript):
            return self._expr_tainted(expr.value, state)
        if isinstance(expr, ast.BinOp):
            return (self._expr_tainted(expr.left, state)
                    or self._expr_tainted(expr.right, state))
        if isinstance(expr, ast.UnaryOp):
            return self._expr_tainted(expr.operand, state)
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self._expr_tainted(e, state) for e in expr.elts)
        if isinstance(expr, ast.IfExp):
            return (self._expr_tainted(expr.body, state)
                    or self._expr_tainted(expr.orelse, state))
        if isinstance(expr, ast.Call):
            name = self.ctx.canonical_call_name(expr.func)
            if name and name.startswith(_DEVICE_CALL_PREFIXES):
                return True
            func = expr.func
            if isinstance(func, ast.Attribute):
                # self._step(...) where _step = jax.jit(...)
                if (_is_self_attr(func) and state["cls"]
                        and func.attr in self.decls.jit_attrs.get(
                            state["cls"], {})):
                    return True
                # tainted.method(...) stays on device (sync methods are
                # sinks, handled in _call_hazards).
                if func.attr not in _SYNC_METHODS and self._expr_tainted(
                        func.value, state):
                    return True
            if isinstance(func, ast.Name) and func.id in state["local_jits"]:
                return True
        return False

    def _call_hazards(self, call, name, state):
        fn = state["fn"]
        in_loop = state["loop_depth"] > 0
        src = _expr_text(call.args[0]) if call.args else ""
        if name in _HOST_COERCERS and any(
                self._expr_tainted(a, state) for a in call.args):
            fn.hazards.append(Hazard(
                "host-sync",
                f"`{name.split('.')[-1] if '.' in name else name}({src})` "
                f"forces a device->host transfer",
                call.lineno, call.col_offset, in_loop))
            return
        if name == "jax.device_get" and call.args:
            fn.hazards.append(Hazard(
                "host-sync", f"`jax.device_get({src})` blocks on the device",
                call.lineno, call.col_offset, in_loop))
            return
        if name == "jax.block_until_ready" or (
                isinstance(call.func, ast.Attribute)
                and call.func.attr == "block_until_ready"):
            fn.hazards.append(Hazard(
                "block-sync", "`block_until_ready` blocks host dispatch",
                call.lineno, call.col_offset, in_loop))
            return
        if isinstance(call.func, ast.Attribute) and call.func.attr in \
                _SYNC_METHODS:
            if self._expr_tainted(call.func.value, state):
                fn.hazards.append(Hazard(
                    "host-sync",
                    f"`.{call.func.attr}()` on a device array forces a "
                    f"device->host transfer",
                    call.lineno, call.col_offset, in_loop))
            return
        if name in ("jax.jit", "jax.pmap"):
            fn.hazards.append(Hazard(
                "jit-in-body",
                "`jax.jit` constructed inside a function body — a fresh "
                "callable retraces on every call",
                call.lineno, call.col_offset, in_loop,
                guarded=state["guard_depth"] > 0))
            return
        # static-arg drift: jitted-with-static-args callable invoked with a
        # loop variable — every distinct value recompiles.
        static = None
        func = call.func
        if isinstance(func, ast.Name):
            static = state["local_jits"].get(func.id)
        elif _is_self_attr(func) and state["cls"]:
            static = self.decls.jit_attrs.get(state["cls"], {}).get(func.attr)
        if static and in_loop:
            drifting = [
                _expr_text(a) for a in call.args
                if isinstance(a, ast.Name) and a.id in state["loop_vars"]
            ]
            if drifting:
                fn.hazards.append(Hazard(
                    "static-drift",
                    f"jitted callable with static args invoked with "
                    f"loop-varying `{drifting[0]}` — retraces per value",
                    call.lineno, call.col_offset, True))

    def _branch_sync_check(self, test, state):
        # `if x is None:` / `x is y` are identity checks — no transfer.
        if isinstance(test, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops):
            return
        if self._expr_tainted(test, state):
            state["fn"].hazards.append(Hazard(
                "bool-sync",
                f"branching on device value `{_expr_text(test)}` forces a "
                f"device->host sync",
                test.lineno, test.col_offset, state["loop_depth"] > 0))


def _is_cache_guard(test) -> bool:
    """``if key not in cache:`` / ``if x is None:`` — the memoized-build
    idiom; jit construction under it compiles once, not per call."""
    for node in ast.walk(test):
        if isinstance(node, ast.Compare):
            for op in node.ops:
                if isinstance(op, (ast.In, ast.NotIn, ast.Is, ast.IsNot)):
                    return True
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            return True
    return False


_MEMO_DECORATORS = {"lru_cache", "cache", "cached_property"}


def _is_memoized(node) -> bool:
    """``@functools.lru_cache`` / ``@cache`` on the def — the function is
    a build-once factory, so jit construction in its body is guarded."""
    for dec in node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = target.attr if isinstance(target, ast.Attribute) else (
            target.id if isinstance(target, ast.Name) else "")
        if name in _MEMO_DECORATORS:
            return True
    return False


def _expr_text(node) -> str:
    try:
        text = ast.unparse(node)
    except Exception:  # pragma: no cover - unparse failure
        return "<expr>"
    return text if len(text) <= 40 else text[:37] + "..."


def summarize_file(ctx: FileContext, decls: _Decls) -> List[FunctionSummary]:
    """Function summaries for one file against merged declarations."""
    modkey = modkey_for(ctx.path)
    hot_lines = {
        i + 1 for i, line in enumerate(ctx.source.splitlines())
        if _HOT_RE.search(line)
    }
    walker = _FnWalker(ctx, decls, modkey, hot_lines)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if ctx.enclosing_function(node) is not None:
            continue  # nested defs are walked from their parent
        cls = ctx.enclosing_class(node)
        if cls is not None:
            key = f"{cls.name}.{node.name}"
            walker.walk_function(node, cls.name, key)
        else:
            walker.walk_function(node, None, f"{modkey}:{node.name}")
    taints = _taint.extract_file_taint(ctx, modkey)
    shapes = _shapes.extract_file_shapes(ctx, modkey)
    for fn in walker.out:
        rec = taints.get(fn.key)
        if rec is not None and (rec.params or rec.flows or rec.param_sinks
                                or rec.param_calls or rec.wire_calls):
            fn.taint = rec
        srec = shapes.get(fn.key)
        if srec is not None and not srec.empty():
            fn.shapes = srec
    return walker.out


# ---------------------------------------------------------------------------
# graph assembly
# ---------------------------------------------------------------------------

MAIN = "main"


class CallGraph:
    """Whole-program view rules query: functions, thread identities,
    held-at-entry locksets, hot-path reachability."""

    def __init__(self, functions: Dict[str, FunctionSummary], decls: _Decls):
        self.functions = functions
        self.decls = decls
        # callee -> [(caller key, frozenset(held))]
        self.callers: Dict[str, List[Tuple[str, frozenset]]] = {}
        # spawn target key -> (spawner key, kind)
        self.roots: Dict[str, Tuple[str, str]] = {}
        for fn in functions.values():
            for callee, held, _line in fn.calls:
                if callee in functions:
                    self.callers.setdefault(callee, []).append(
                        (fn.key, frozenset(held)))
            for target, kind, _line in fn.spawns:
                if target is not None and target in functions:
                    self.roots.setdefault(target, (fn.key, kind))
        self._thread_sets = self._compute_thread_sets()
        self._entry = self._compute_entry_locksets()
        self._hot = self._compute_hot()

    # -- reachability / threads ---------------------------------------------

    def _forward_reach(self, seeds: Set[str]) -> Set[str]:
        seen = set(seeds)
        stack = list(seeds)
        while stack:
            fn = self.functions.get(stack.pop())
            if fn is None:
                continue
            for callee, _held, _line in fn.calls:
                if callee in self.functions and callee not in seen:
                    seen.add(callee)
                    stack.append(callee)
        return seen

    def _compute_thread_sets(self) -> Dict[str, Set[str]]:
        """Function key -> set of thread identities that may run it
        (spawn-target keys, plus ``main`` for public entry points and
        their transitive callees)."""
        sets: Dict[str, Set[str]] = {k: set() for k in self.functions}
        for root in self.roots:
            for key in self._forward_reach({root}):
                sets[key].add(root)
        main_seeds = {
            key for key, fn in self.functions.items()
            if key not in self.roots and (
                fn.public or key not in self.callers)
        }
        for key in self._forward_reach(main_seeds):
            sets[key].add(MAIN)
        return sets

    def thread_set(self, key: str) -> Set[str]:
        return self._thread_sets.get(key, {MAIN})

    # -- held-at-entry fixpoint ----------------------------------------------

    def _compute_entry_locksets(self) -> Dict[str, frozenset]:
        """Decreasing fixpoint from ⊤: entry(f) is the lockset provably
        held at every entry to f. Public functions and spawn targets pin
        to ∅ (they may be entered lock-free)."""
        TOP = None
        entry: Dict[str, Optional[frozenset]] = {}
        for key, fn in self.functions.items():
            if fn.public or key in self.roots or key not in self.callers:
                entry[key] = frozenset()
            else:
                entry[key] = TOP
        changed = True
        while changed:
            changed = False
            for key, fn in self.functions.items():
                if entry[key] == frozenset():
                    continue
                contribs = []
                for caller, held in self.callers.get(key, ()):
                    up = entry.get(caller)
                    if up is TOP:
                        continue  # unresolved caller: skip this round
                    contribs.append(held | up)
                if not contribs:
                    continue
                # Inputs only shrink round over round (held sets are
                # fixed, caller entries decrease), so recomputing the
                # intersection from scratch is monotone and terminates.
                new = frozenset.intersection(*contribs)
                if entry[key] is TOP or new != entry[key]:
                    entry[key] = new
                    changed = True
        return {k: (v if v is not TOP else frozenset())
                for k, v in entry.items()}

    def entry_lockset(self, key: str) -> frozenset:
        return self._entry.get(key, frozenset())

    def effective_locks(self, fn_key: str, access: Access) -> frozenset:
        return frozenset(access.locks) | self.entry_lockset(fn_key)

    # -- hot paths ------------------------------------------------------------

    def _compute_hot(self) -> Dict[str, str]:
        """Function key -> the ``# tpulint: hot-path`` root it is
        reachable from (itself, when annotated directly)."""
        hot: Dict[str, str] = {}
        roots = sorted(k for k, fn in self.functions.items() if fn.hot)
        for root in roots:
            for key in self._forward_reach({root}):
                hot.setdefault(key, root)
        return hot

    def hot_root(self, key: str) -> Optional[str]:
        return self._hot.get(key)

    def self_spawning_classes(self) -> Set[str]:
        """Classes that start a thread on one of their own methods (or a
        closure inside one). For these, the spawned thread and the
        object's other callers provably share the *same instance* —
        the object-identity fact a static Eraser otherwise lacks."""
        owners: Set[str] = set()
        for target in self.roots:
            head = target.split(".", 1)[0]
            if ":" not in head:
                owners.add(head)
        return owners

    # -- witnesses ------------------------------------------------------------

    def witness_path(self, key: str, context: str) -> List[str]:
        """Shortest call path from a thread context's entry to ``key``
        (function keys only — line-free, so messages stay
        fingerprint-stable across unrelated edits)."""
        if context == MAIN:
            seeds = {
                k for k, fn in self.functions.items()
                if k not in self.roots and (fn.public or k not in
                                            self.callers)
            }
        else:
            seeds = {context}
        prev: Dict[str, Optional[str]] = {s: None for s in seeds}
        queue = sorted(seeds)
        while queue:
            cur = queue.pop(0)
            if cur == key:
                path = []
                node: Optional[str] = cur
                while node is not None:
                    path.append(node)
                    node = prev[node]
                return list(reversed(path))
            fn = self.functions.get(cur)
            if fn is None:
                continue
            for callee in sorted({c for c, _h, _l in fn.calls}):
                if callee in self.functions and callee not in prev:
                    prev[callee] = cur
                    queue.append(callee)
        return [key]

    def describe_context(self, context: str) -> str:
        if context == MAIN:
            return "main"
        spawner, kind = self.roots.get(context, ("?", "thread"))
        return f"{context} ({kind} started by {spawner})"


# ---------------------------------------------------------------------------
# build + cache
# ---------------------------------------------------------------------------

_CONFIG = {"cache_path": None, "scope": None}
_MEMO: Dict[tuple, CallGraph] = {}


def configure(cache_path: Optional[str] = None,
              scope: Optional[Sequence[str]] = None) -> None:
    """Set the cache file and the project scope (paths the graph should
    cover even when only a subset is being linted). Called by the CLI;
    tests leave it unset and the graph covers exactly the linted files."""
    _CONFIG["cache_path"] = cache_path
    _CONFIG["scope"] = list(scope) if scope else None
    _MEMO.clear()


def get_callgraph(ctxs: Sequence[FileContext]) -> CallGraph:
    """Build (or reuse) the whole-program call graph for this run.

    Files in ``ctxs`` contribute their already-parsed trees; when a
    project scope is configured, files outside the linted set are loaded
    from the summary cache (sha1 match) or parsed from disk.
    """
    by_path = {ctx.path: ctx for ctx in ctxs}
    if _CONFIG["scope"]:
        paths = [p.replace(os.sep, "/")
                 for p in discover_files(_CONFIG["scope"])]
        for p in by_path:
            if p not in paths:
                paths.append(p)
    else:
        paths = sorted(by_path)

    sources: Dict[str, str] = {}
    shas: Dict[str, str] = {}
    for path in paths:
        ctx = by_path.get(path)
        if ctx is not None:
            source = ctx.source
        else:
            try:
                with open(path, encoding="utf-8") as f:
                    source = f.read()
            except OSError:
                continue
        sources[path] = source
        shas[path] = hashlib.sha1(source.encode()).hexdigest()

    memo_key = tuple(sorted(shas.items()))
    got = _MEMO.get(memo_key)
    if got is not None:
        return got

    cache = _load_cache(_CONFIG["cache_path"])
    cached_files = cache.get("files", {})

    # Pass 1: declarations (cache hit on per-file sha alone).
    decls_per_file: Dict[str, dict] = {}
    parsed: Dict[str, FileContext] = {}
    for path in sources:
        entry = cached_files.get(path)
        if entry is not None and entry.get("sha1") == shas[path]:
            decls_per_file[path] = entry["decls"]
            continue
        ctx = by_path.get(path) or _try_parse(path, sources[path])
        if ctx is None:
            continue
        parsed[path] = ctx
        decls_per_file[path] = extract_decls(ctx)
    decls = _Decls(decls_per_file)
    digest = decls.digest(decls_per_file)

    # Pass 2: function summaries (cache hit needs sha + decls digest).
    functions: Dict[str, FunctionSummary] = {}
    new_entries: Dict[str, dict] = {}
    for path in sources:
        if path not in decls_per_file:
            continue
        entry = cached_files.get(path)
        if (path not in parsed and entry is not None
                and entry.get("sha1") == shas[path]
                and cache.get("decls_digest") == digest):
            fns = [FunctionSummary.from_json(d) for d in entry["functions"]]
        else:
            ctx = parsed.get(path) or by_path.get(path) or _try_parse(
                path, sources[path])
            if ctx is None:
                continue
            fns = summarize_file(ctx, decls)
        new_entries[path] = {
            "sha1": shas[path],
            "decls": decls_per_file[path],
            "functions": [fn.to_json() for fn in fns],
        }
        for fn in fns:
            functions[fn.key] = fn

    graph = CallGraph(functions, decls)
    _MEMO.clear()
    _MEMO[memo_key] = graph
    _save_cache(_CONFIG["cache_path"], digest, new_entries)
    return graph


def _try_parse(path: str, source: str) -> Optional[FileContext]:
    try:
        return FileContext(path, source)
    except SyntaxError:
        return None


def _load_cache(path: Optional[str]) -> dict:
    if not path:
        return {}
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    if data.get("version") != CACHE_VERSION:
        return {}
    return data


def _save_cache(path: Optional[str], digest: str,
                files: Dict[str, dict]) -> None:
    if not path:
        return
    payload = {"version": CACHE_VERSION, "decls_digest": digest,
               "files": files}
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
    except OSError:  # pragma: no cover - cache is best-effort
        pass

"""TPU009: guarded-by race detection (Eraser-style static lockset).

For every ``self.*`` / module-global mutable attribute the call-graph
substrate (``_callgraph.py``) can see, this rule asks the two questions
TPU002's single-class heuristic cannot:

1. **Does the attribute escape to ≥ 2 threads?** Thread identities come
   from spawn sites (``threading.Thread(target=...)``, executor
   ``submit``/``map``, ``run_in_executor``, ``threading.Timer``) plus an
   implicit ``main`` identity for public entry points. An attribute
   escapes when the union of identities over all its access sites has at
   least two members and at least one access is a post-``__init__``
   write. Single-thread attributes — however lock-free — are not races.

2. **Which lock guards it?** The guard is inferred by majority vote over
   the *effective* locksets of the post-init writes (lexically held
   locks ∪ locks provably held at entry to the writing function, the
   interprocedural step that keeps "caller holds the lock" helpers
   clean). Writers define the discipline; reads then get checked against
   it, which is exactly the shape of the real bug class this rule exists
   for — counters mutated under a lock but scraped lock-free by a
   metrics thread.

Findings:

* a majority guard exists → every access (read or write) whose
  effective lockset misses the guard is reported, with the inferred
  guard, the vote, the thread identities, and a line-free witness call
  path (stable fingerprints for baselines);
* no lock is ever held → the attribute is reported once, at its first
  post-init write;
* locks appear but none wins the majority → reported once as
  inconsistently guarded.

Three precision policies keep a *static* Eraser honest about object
identity (the thing only the runtime tier can truly see):

* accesses through locally-constructed objects are thread-local
  (``req = CoreRequest(...); req.inputs = ...`` is not sharing);
* the lock-free cases ("no lock ever held" / "no consistent guard")
  only report classes that spawn a thread on one of their *own* methods
  — there the spawned thread and other callers provably share the same
  instance; per-request value objects whose methods merely *run* on
  several threads do not qualify (module globals always qualify: they
  are one instance by construction);
* findings in test files are dropped — tests poke quiesced internals
  by design, and the tpusan runtime witness covers them under
  ``TPUSAN=1``.

Deliberate single-mutator designs (e.g. the gpt engine's "engine loop is
the sole mutator of slot state") suppress with ``# tpulint:
disable=TPU009`` on the ``def`` line, same as TPU002. The tpusan runtime
tier mirrors this rule: ``sanitize.note_field_access`` tracks the same
per-attribute locksets under ``TPUSAN=1`` and ``scripts/tpusan_report.py``
diffs the two.
"""

from typing import Dict, List, Sequence, Set, Tuple

from tritonclient_tpu.analysis import _callgraph
from tritonclient_tpu.analysis._engine import FileContext, Finding, Rule


class GuardedByRule(Rule):
    id = "TPU009"
    name = "guarded-by"
    description = (
        "attribute shared across threads accessed outside its inferred "
        "guarding lock (Eraser-style interprocedural lockset analysis)"
    )

    def check_project(self, ctxs: Sequence[FileContext]) -> List[Finding]:
        if not ctxs:
            return []
        graph = _callgraph.get_callgraph(ctxs)
        linted = {
            ctx.path for ctx in ctxs if not _is_test_path(ctx.path)
        }
        findings: List[Finding] = []
        for (owner, attr), accesses in sorted(
                _group_accesses(graph).items()):
            findings.extend(
                _check_attr(graph, owner, attr, accesses, linted))
        return findings


def _is_test_path(path: str) -> bool:
    parts = path.split("/")
    return "tests" in parts or parts[-1].startswith("test_")


def _group_accesses(graph) -> Dict[Tuple[str, str],
                                   List[Tuple[str, "_callgraph.Access"]]]:
    groups: Dict[Tuple[str, str], List] = {}
    for key, fn in graph.functions.items():
        for access in fn.accesses:
            groups.setdefault((access.owner, access.attr), []).append(
                (key, access))
    return groups


def _check_attr(graph, owner, attr, accesses, linted) -> List[Finding]:
    post_init_writes = [
        (key, a) for key, a in accesses if a.write and not a.in_init
    ]
    if not post_init_writes:
        return []
    threads: Set[str] = set()
    for key, _a in accesses:
        threads |= graph.thread_set(key)
    if len(threads) < 2:
        return []  # never escapes: single-thread state
    contexts = ", ".join(sorted(
        graph.describe_context(t) for t in threads))

    # Majority vote over post-init write locksets.
    votes: Dict[str, int] = {}
    for key, a in post_init_writes:
        for lock in graph.effective_locks(key, a):
            votes[lock] = votes.get(lock, 0) + 1
    total = len(post_init_writes)
    guard = None
    if votes:
        # Highest vote count wins; ties break lexicographically for
        # deterministic output.
        best = sorted(votes.items(), key=lambda kv: (-kv[1], kv[0]))[0]
        if best[1] * 2 > total:
            guard = best[0]

    label = f"{owner}.{attr}"
    findings: List[Finding] = []
    if guard is not None:
        held = votes[guard]
        for key, a in sorted(
                accesses, key=lambda ka: (ka[1].line, ka[1].col)):
            if a.in_init or guard in graph.effective_locks(key, a):
                continue
            fn = graph.functions[key]
            if fn.path not in linted:
                continue
            kind = "write to" if a.write else "read of"
            context = _a_context(graph, key)
            witness = " -> ".join(graph.witness_path(key, context))
            findings.append(Finding(
                GuardedByRule.id, fn.path, a.line, a.col,
                f"{kind} `{label}` outside its guarding lock `{guard}` "
                f"(held at {held}/{total} writes; shared by: {contexts}; "
                f"witness: {witness})",
            ))
        return findings

    # No majority guard. Without a lock as evidence of intentional
    # sharing, require provable same-instance sharing: the owner class
    # spawns a thread on its own method (or the owner is a module
    # global — one instance by construction). Per-request value objects
    # whose methods merely run on several threads drop out here.
    is_module_global = owner not in graph.decls.known_classes
    if not is_module_global and owner not in \
            graph.self_spawning_classes():
        return []
    # One finding per attribute at the first post-init write, so an
    # unguarded attr is one actionable item rather than one per touch.
    key, a = min(post_init_writes,
                 key=lambda ka: (ka[1].line, ka[1].col))
    fn = graph.functions[key]
    if fn.path not in linted:
        return []
    if not votes:
        msg = (f"`{label}` is written with no lock ever held, but is "
               f"shared by: {contexts}")
    else:
        seen = ", ".join(f"`{k}`" for k in sorted(votes))
        msg = (f"`{label}` has no consistent guard (locks seen at some "
               f"writes: {seen}), but is shared by: {contexts}")
    context = _a_context(graph, key)
    witness = " -> ".join(graph.witness_path(key, context))
    return [Finding(GuardedByRule.id, fn.path, a.line, a.col,
                    f"{msg}; witness: {witness}")]


def _a_context(graph, key) -> str:
    """A deterministic thread identity for the witness path (prefer a
    spawned thread over main — it reads better in the message)."""
    ts = sorted(graph.thread_set(key))
    non_main = [t for t in ts if t != _callgraph.MAIN]
    return non_main[0] if non_main else _callgraph.MAIN

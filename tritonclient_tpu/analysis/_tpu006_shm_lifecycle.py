"""TPU006: shared-memory handle lifecycle (flow-sensitive).

The zero-copy plane's correctness rests on a register/set/unregister
protocol the AST-local rules cannot model: a use-after-unregister on a
PjRt/DLPack-backed region is silent corruption the CPU tests never catch.
This rule runs a small abstract interpreter over every function body,
tracking handles returned by ``create_shared_memory_region`` /
``create_sharded_memory_region`` through assignments, tuple unpacking,
``with`` blocks, and for-loops over handle tuples, plus the registration
state of region *names* passed to ``register_*_shared_memory`` /
``unregister_*_shared_memory``.

States are path-merged (may-analysis) at ``if``/``else``, loop, and
``try`` joins; every statement inside a ``try`` body contributes an
exception edge into its handlers and ``finally``, and ``return`` /
``raise`` are treated as function exits, so a cleanup that only runs on
the straight-line path still flags the exception path.

Findings:

* **use-after-destroy** — any handle operation (set/read/get_raw_handle/
  method call) on a path where ``destroy_shared_memory_region`` already
  ran;
* **use-after-unregister** — a handle operation after its linked region
  name was unregistered (and not re-registered) on some path;
* **double-register** — a region name registered again on a path where it
  is still registered;
* **destroy-while-registered** — ``destroy_shared_memory_region`` on a
  handle whose region name is still registered with the server on every
  incoming path (unregister first: the server keeps a dangling mapping);
* **leak** — a path (fall-through, ``return``, or uncaught ``raise``)
  exits the function with a created handle neither destroyed nor escaped
  (returned, yielded, stored beyond the frame, or passed to a non-shm
  call — ownership transfer).

Deliberate violations carry ``# tpulint: disable=TPU006`` (on the create
line for leaks, on the use line otherwise).
"""

import ast
from typing import Dict, List, Optional, Set, Tuple

from tritonclient_tpu.analysis._engine import FileContext, Finding, Rule

_CREATE_FNS = {
    "create_shared_memory_region",
    "create_sharded_memory_region",
}
_DESTROY_FNS = {"destroy_shared_memory_region"}
#: Module-level functions that operate on a handle without taking ownership.
_USE_FNS = {
    "set_shared_memory_region",
    "set_shared_memory_region_from_dlpack",
    "get_contents_as_numpy",
    "as_shared_memory_tensor",
    "get_raw_handle",
}
_REGISTER_METHODS = {
    "register_system_shared_memory",
    "register_cuda_shared_memory",
    "register_tpu_shared_memory",
}
_UNREGISTER_METHODS = {
    "unregister_system_shared_memory",
    "unregister_cuda_shared_memory",
    "unregister_tpu_shared_memory",
}

# Handle states.
_CREATED = "created"
_DESTROYED = "destroyed"
# Name states.
_REGISTERED = "registered"
_UNREGISTERED = "unregistered"


class _Env:
    """One abstract machine state: variable bindings + per-handle and
    per-region-name state sets (sets = may-information after joins)."""

    __slots__ = ("vars", "hstate", "nstate")

    def __init__(self):
        self.vars: Dict[str, int] = {}          # local name -> handle id
        self.hstate: Dict[int, Set[str]] = {}   # handle id -> state set
        self.nstate: Dict[str, Set[str]] = {}   # region-name key -> state set

    def copy(self) -> "_Env":
        env = _Env()
        env.vars = dict(self.vars)
        env.hstate = {k: set(v) for k, v in self.hstate.items()}
        env.nstate = {k: set(v) for k, v in self.nstate.items()}
        return env

    def join(self, other: Optional["_Env"]):
        if other is None:
            return
        for var, hid in other.vars.items():
            self.vars.setdefault(var, hid)
        for hid, states in other.hstate.items():
            self.hstate.setdefault(hid, set()).update(states)
        for name, states in other.nstate.items():
            self.nstate.setdefault(name, set()).update(states)


class _Handle:
    __slots__ = ("hid", "var", "site", "name_key", "escaped", "leak_reported")

    def __init__(self, hid, var, site, name_key):
        self.hid = hid
        self.var = var
        self.site = site          # the create-call AST node
        self.name_key = name_key  # region-name key ('' when unknown)
        self.escaped = False
        self.leak_reported = False


class ShmLifecycleRule(Rule):
    id = "TPU006"
    name = "shm-lifecycle"
    description = (
        "shared-memory handle state machine: use-after-unregister/destroy, "
        "double-register, and paths leaking a created region"
    )

    def check_file(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _FunctionAnalysis(self, ctx, node, findings).run()
        return findings


class _FunctionAnalysis:
    def __init__(self, rule, ctx, func, findings):
        self.rule = rule
        self.ctx = ctx
        self.func = func
        self.findings = findings
        self.handles: Dict[int, _Handle] = {}
        self._next_hid = 0
        # Findings deduped per (kind, handle-or-name, line).
        self._seen: Set[Tuple] = set()

    # -- entry ---------------------------------------------------------------

    def run(self):
        env = _Env()
        out = self._exec_block(self.func.body, env, raise_sink=None)
        if out is not None:
            self._check_exit(out)

    # -- reporting -----------------------------------------------------------

    def _report(self, kind, key, node, message):
        dedup = (kind, node.lineno)
        if dedup in self._seen:
            return
        self._seen.add(dedup)
        self.findings.append(
            Finding(
                self.rule.id, self.ctx.path, node.lineno, node.col_offset,
                message,
            )
        )

    def _check_exit(self, env: _Env, at: Optional[ast.AST] = None):
        """A path leaves the function: live created handles leak."""
        for hid, states in env.hstate.items():
            handle = self.handles.get(hid)
            if handle is None or handle.escaped or handle.leak_reported:
                continue
            if _CREATED in states:
                handle.leak_reported = True
                where = (
                    f"a path exiting at line {at.lineno}" if at is not None
                    else "a fall-through path"
                )
                self._report(
                    "leak", hid, handle.site,
                    f"shared-memory handle `{handle.var}` created here is "
                    f"never destroyed on {where}; call "
                    "destroy_shared_memory_region in a finally block",
                )

    # -- statement execution -------------------------------------------------

    def _exec_block(self, stmts, env: _Env, raise_sink) -> Optional[_Env]:
        """Execute statements; returns the fall-through env or None when
        every path returned/raised. ``raise_sink`` (a list of envs) absorbs
        exception edges when inside a try body."""
        cur: Optional[_Env] = env
        for stmt in stmts:
            if cur is None:
                break
            cur = self._exec_stmt(stmt, cur, raise_sink)
            if cur is not None and raise_sink is not None:
                # Any statement may raise: snapshot the post-state as a
                # handler-entry possibility (exception edge).
                raise_sink.append(cur.copy())
        return cur

    def _exec_stmt(self, stmt, env: _Env, raise_sink) -> Optional[_Env]:
        if isinstance(stmt, ast.Assign):
            self._do_assign(stmt, env)
            return env
        if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            if stmt.value is not None:
                self._scan_expr(stmt.value, env)
            return env
        if isinstance(stmt, ast.Expr):
            self._scan_expr(stmt.value, env)
            return env
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._mark_escapes(stmt.value, env)
                self._scan_expr(stmt.value, env)
            self._check_exit(env, at=stmt)
            return None
        if isinstance(stmt, ast.Raise):
            if raise_sink is not None:
                raise_sink.append(env.copy())
            else:
                self._check_exit(env, at=stmt)
            return None
        if isinstance(stmt, ast.If):
            return self._do_if(stmt, env, raise_sink)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return self._do_for(stmt, env, raise_sink)
        if isinstance(stmt, ast.While):
            self._scan_expr(stmt.test, env)
            body_out = self._exec_block(stmt.body, env.copy(), raise_sink)
            env.join(body_out)
            orelse_out = self._exec_block(stmt.orelse, env.copy(), raise_sink)
            out = env
            out.join(orelse_out)
            return out
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._do_with(stmt, env, raise_sink)
        if isinstance(stmt, ast.Try):
            return self._do_try(stmt, env, raise_sink)
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return env  # nested scopes analyzed independently
        if isinstance(stmt, (ast.Break, ast.Continue)):
            return env  # loop approximation: treated as fall-through
        # Default: scan contained expressions for events.
        for sub in ast.iter_child_nodes(stmt):
            if isinstance(sub, ast.expr):
                self._scan_expr(sub, env)
        return env

    def _do_if(self, stmt, env, raise_sink):
        self._scan_expr(stmt.test, env)
        guard = self._none_guard_var(stmt.test)
        if guard is not None and not stmt.orelse and guard in env.vars:
            # `if h is not None: <cleanup>` — the else path is the
            # handle-never-created world, so don't fork: forking would
            # report the guarded cleanup as a leak path.
            return self._exec_block(stmt.body, env, raise_sink)
        body_out = self._exec_block(stmt.body, env.copy(), raise_sink)
        else_out = self._exec_block(stmt.orelse, env.copy(), raise_sink)
        if body_out is None:
            return else_out
        body_out.join(else_out)
        return body_out

    @staticmethod
    def _none_guard_var(test) -> Optional[str]:
        if isinstance(test, ast.Name):
            return test.id
        if (
            isinstance(test, ast.Compare)
            and isinstance(test.left, ast.Name)
            and len(test.ops) == 1
            and isinstance(test.ops[0], (ast.IsNot, ast.NotEq))
            and len(test.comparators) == 1
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None
        ):
            return test.left.id
        return None

    def _do_for(self, stmt, env, raise_sink):
        self._scan_expr(stmt.iter, env)
        # `for h in (a, b, c):` over tracked handles: run the body once per
        # element with the target bound — the teardown-loop idiom.
        if (
            isinstance(stmt.target, ast.Name)
            and isinstance(stmt.iter, (ast.Tuple, ast.List))
            and any(
                isinstance(el, ast.Name) and el.id in env.vars
                for el in stmt.iter.elts
            )
        ):
            cur = env
            for el in stmt.iter.elts:
                if cur is None:
                    break
                if isinstance(el, ast.Name) and el.id in cur.vars:
                    cur.vars[stmt.target.id] = cur.vars[el.id]
                else:
                    cur.vars.pop(stmt.target.id, None)
                cur = self._exec_block(stmt.body, cur, raise_sink)
            if cur is not None:
                cur.vars.pop(stmt.target.id, None)
            return cur
        body_out = self._exec_block(stmt.body, env.copy(), raise_sink)
        env.join(body_out)
        orelse_out = self._exec_block(stmt.orelse, env.copy(), raise_sink)
        env.join(orelse_out)
        return env

    def _do_with(self, stmt, env, raise_sink):
        owned = []
        for item in stmt.items:
            expr = item.context_expr
            created = None
            if isinstance(expr, ast.Call):
                kind = self._classify_call(expr)
                if kind == "create" and isinstance(
                    item.optional_vars, ast.Name
                ):
                    created = self._track_create(
                        expr, item.optional_vars.id, env
                    )
                    for arg in expr.args:
                        self._scan_expr(arg, env)
                else:
                    self._scan_expr(expr, env)
            else:
                self._scan_expr(expr, env)
            if created is not None:
                owned.append(created)
        out = self._exec_block(stmt.body, env, raise_sink)
        if out is not None:
            for hid in owned:
                # `with create(...) as h:` — the context manager owns the
                # cleanup at block exit.
                out.hstate[hid] = {_DESTROYED}
        return out

    def _do_try(self, stmt, env, raise_sink):
        raised: List[_Env] = [env.copy()]
        body_out = self._exec_block(stmt.body, env, raised)
        handler_outs = []
        caught = bool(stmt.handlers)
        for handler in stmt.handlers:
            h_in = _Env()
            for snap in raised:
                h_in.join(snap)
            handler_outs.append(
                self._exec_block(handler.body, h_in, raise_sink)
            )
        merged: Optional[_Env] = None
        for candidate in [body_out] + handler_outs:
            if candidate is None:
                continue
            if merged is None:
                merged = candidate
            else:
                merged.join(candidate)
        if stmt.orelse and body_out is not None:
            merged_orelse = self._exec_block(
                stmt.orelse, body_out.copy(), raise_sink
            )
            if merged is None:
                merged = merged_orelse
            elif merged_orelse is not None:
                merged.join(merged_orelse)
        if stmt.finalbody:
            # The finally runs on the fall-through paths AND on the
            # exceptional path that propagates past this try (no handler,
            # or the handler re-raised): execute it over the join so a
            # finally-cleanup counts for every path.
            fin_in = merged if merged is not None else _Env()
            if not caught:
                for snap in raised:
                    fin_in.join(snap)
            merged = self._exec_block(stmt.finalbody, fin_in, raise_sink)
            if merged is not None and not caught and raise_sink is None:
                # Exception continues propagating after the finally: that
                # is a function exit for leak purposes.
                self._check_exit(merged, at=stmt)
        elif not caught and raise_sink is not None:
            for snap in raised:
                raise_sink.append(snap)
        return merged

    # -- assignments and expressions -----------------------------------------

    def _do_assign(self, stmt: ast.Assign, env: _Env):
        value = stmt.value
        targets = stmt.targets
        # Tuple unpacking of parallel creates: a, b = create(...), create(...)
        if (
            len(targets) == 1
            and isinstance(targets[0], ast.Tuple)
            and isinstance(value, ast.Tuple)
            and len(targets[0].elts) == len(value.elts)
        ):
            for tgt, val in zip(targets[0].elts, value.elts):
                self._assign_one(tgt, val, env)
            return
        for tgt in targets:
            self._assign_one(tgt, value, env)

    def _assign_one(self, target, value, env: _Env):
        if isinstance(value, ast.Call) and self._classify_call(value) == "create":
            if isinstance(target, ast.Name):
                self._track_create(value, target.id, env)
                return
            # Created straight into an attribute/subscript: ownership
            # lives beyond this frame — untracked by design.
            return
        if isinstance(value, ast.Name) and value.id in env.vars:
            hid = env.vars[value.id]
            if isinstance(target, ast.Name):
                env.vars[target.id] = hid  # alias
                return
            # Handle stored into an attribute/subscript/container: escapes.
            self._escape(hid, env)
            return
        self._scan_expr(value, env)
        if isinstance(target, ast.Name):
            # Rebinding a tracked variable to something else drops the
            # alias (the handle may live on via other aliases).
            env.vars.pop(target.id, None)

    def _track_create(self, call: ast.Call, var: str, env: _Env) -> int:
        hid = self._next_hid
        self._next_hid += 1
        name_key = ""
        if call.args:
            name_key = self._name_key(call.args[0])
        self.handles[hid] = _Handle(hid, var, call, name_key)
        env.vars[var] = hid
        env.hstate[hid] = {_CREATED}
        if name_key:
            env.nstate.setdefault(name_key, set())
        return hid

    @staticmethod
    def _name_key(node) -> str:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        try:
            return ast.dump(node)
        except Exception:  # pragma: no cover - dump never fails on exprs
            return ""

    # -- expression scanning (events) ----------------------------------------

    def _scan_expr(self, node, env: _Env):
        # ast.walk reaches every nested Call exactly once; _handle_call
        # therefore never recurses into arguments itself.
        for call in [n for n in ast.walk(node) if isinstance(n, ast.Call)]:
            self._handle_call(call, env)

    def _handle_call(self, call: ast.Call, env: _Env):
        kind = self._classify_call(call)
        if kind == "destroy":
            hid = self._arg_handle(call, env)
            if hid is not None:
                states = env.hstate.get(hid, set())
                handle = self.handles[hid]
                if _DESTROYED in states:
                    self._report(
                        "double-destroy", hid, call,
                        f"handle `{handle.var}` may already be destroyed on "
                        "a path reaching this destroy_shared_memory_region",
                    )
                if handle.name_key:
                    nstates = env.nstate.get(handle.name_key, set())
                    if nstates == {_REGISTERED}:
                        self._report(
                            "destroy-registered", hid, call,
                            f"handle `{handle.var}` is destroyed while its "
                            "region is still registered with the server; "
                            "unregister it first",
                        )
                env.hstate[hid] = {_DESTROYED}
            return
        if kind == "use":
            hid = self._arg_handle(call, env)
            if hid is not None:
                self._check_use(hid, call, env)
            return
        if kind == "register":
            name_key = self._name_key(call.args[0]) if call.args else ""
            if name_key:
                states = env.nstate.get(name_key)
                if states == {_REGISTERED}:
                    self._report(
                        "double-register", name_key, call,
                        f"region {self._name_desc(call.args[0])} is "
                        "registered twice without an intervening unregister",
                    )
                env.nstate[name_key] = {_REGISTERED}
            return
        if kind == "unregister":
            if call.args and not (
                isinstance(call.args[0], ast.Constant)
                and call.args[0].value == ""
            ):
                name_key = self._name_key(call.args[0])
                if name_key:
                    env.nstate[name_key] = {_UNREGISTERED}
            else:
                # unregister-all
                for name_key in env.nstate:
                    env.nstate[name_key] = {_UNREGISTERED}
            return
        # Method call on a tracked handle variable: a use.
        func = call.func
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id in env.vars
        ):
            self._check_use(env.vars[func.value.id], call, env)
            for arg in list(call.args) + [kw.value for kw in call.keywords]:
                self._mark_escapes(arg, env)
            return
        # Any other call: tracked handles passed as arguments escape
        # (ownership transfer: cleanup helpers, ExitStack, containers).
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            self._mark_escapes(arg, env)

    def _check_use(self, hid: int, call: ast.Call, env: _Env):
        handle = self.handles[hid]
        states = env.hstate.get(hid, set())
        if _DESTROYED in states:
            self._report(
                "use-after-destroy", hid, call,
                f"handle `{handle.var}` may be used after "
                "destroy_shared_memory_region on a path reaching this call",
            )
        if handle.name_key:
            nstates = env.nstate.get(handle.name_key, set())
            if _UNREGISTERED in nstates and _REGISTERED not in nstates:
                self._report(
                    "use-after-unregister", hid, call,
                    f"handle `{handle.var}` is used after its region was "
                    "unregistered from the server; re-register it or move "
                    "the use before the unregister",
                )

    def _arg_handle(self, call: ast.Call, env: _Env) -> Optional[int]:
        for arg in call.args[:1]:
            if isinstance(arg, ast.Name) and arg.id in env.vars:
                return env.vars[arg.id]
        return None

    @staticmethod
    def _name_desc(node) -> str:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return repr(node.value)
        return "named by this expression"

    def _mark_escapes(self, node, env: _Env):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id in env.vars:
                self._escape(env.vars[sub.id], env)

    def _escape(self, hid: int, env: _Env):
        self.handles[hid].escaped = True

    # -- call classification ---------------------------------------------------

    def _classify_call(self, call: ast.Call) -> Optional[str]:
        name = self.ctx.canonical_call_name(call.func)
        tail = None
        if name is not None:
            tail = name.rsplit(".", 1)[-1]
        elif isinstance(call.func, ast.Attribute):
            tail = call.func.attr
        if tail is None:
            return None
        if tail in _CREATE_FNS:
            return "create"
        if tail in _DESTROY_FNS:
            return "destroy"
        if tail in _USE_FNS:
            return "use"
        if tail in _REGISTER_METHODS:
            return "register"
        if tail in _UNREGISTER_METHODS:
            return "unregister"
        return None

"""TPU013: untrusted request data reaching a dangerous sink.

Every byte of the KServe v2 surface is attacker-controlled, and the
values parsed out of it — shapes, byte sizes, shm offsets, binary frame
lengths — feed allocation sizes, ``np.reshape``, buffer slice bounds,
``range()`` loop bounds, and reserve/alloc page math. The contract is
that every such value is laundered through ``protocol/_validate.py``
(``validate_*``) before it reaches any of those sinks; this rule finds
the flows that skip the laundering, interprocedurally, on the same
cached call-graph substrate TPU009/TPU011 use.

Two halves:

* ``_taint.py`` records, per function, where wire data enters (sources
  exist only in the protocol-boundary files: ``server/_http.py``,
  ``server/_grpc.py``, ``fleet/_http.py``), which sinks each
  *parameter* reaches unsanitized, and which callee parameters each
  value is forwarded into. Those facts ride inside the cached
  :class:`~tritonclient_tpu.analysis._callgraph.FunctionSummary`.
* This rule runs the interprocedural fixpoint: a parameter is
  *sinking* if it reaches a sink locally or is forwarded (unsanitized)
  into a sinking parameter of a callee. A finding is a wire source
  whose value reaches a sink — locally, or through a chain of calls —
  and the message carries the full source→sink call path so the fix
  site is obvious.

Sanitizers recognized: ``validate_*`` calls (the ``protocol/_validate``
helpers), ``min``/``max`` against an untainted bound, boolean-producing
builtins, and ``if <compare on the value>: raise/return`` range guards.

Deliberate trusts (e.g. a length-prefixed parse over a buffer already
capped by ``max_request_bytes``) suppress at the SINK line with
``# tpulint: disable=TPU013`` and a comment saying why — suppression is
honored during fact extraction, so the whole transitive flow drops.
"""

from typing import Dict, List, Sequence, Tuple, Union

from tritonclient_tpu.analysis import _callgraph
from tritonclient_tpu.analysis._engine import FileContext, Finding, Rule

Slot = Union[int, str]


class UntrustedSinkRule(Rule):
    id = "TPU013"
    name = "untrusted-sink"
    description = (
        "request-derived value reaches an allocation size, reshape, "
        "slice bound, loop bound, or shm/page-reservation sink without "
        "passing a protocol/_validate.py sanitizer"
    )

    def check_project(self, ctxs: Sequence[FileContext]) -> List[Finding]:
        if not ctxs:
            return []
        graph = _callgraph.get_callgraph(ctxs)
        taints = {
            key: fn.taint for key, fn in graph.functions.items()
            if fn.taint is not None
        }
        sinking = _sinking_params(taints)
        linted = {ctx.path for ctx in ctxs if not _is_test_path(ctx.path)}
        findings: List[Finding] = []
        seen = set()

        def emit(fn, line, col, message):
            dedup = (fn.path, line, message)
            if dedup in seen:
                return
            seen.add(dedup)
            findings.append(Finding(self.id, fn.path, line, col, message))

        for key in sorted(taints):
            fn = graph.functions[key]
            if fn.path not in linted:
                continue
            rec = taints[key]
            for kind, detail, line, col, src in rec.flows:
                emit(fn, line, col,
                     f"request-derived value reaches {kind} sink "
                     f"`{detail}` in `{key}` without passing a "
                     f"validate_* sanitizer")
            for callee, slot, line, col, src in rec.wire_calls:
                hit = _lookup(sinking, taints, callee, slot)
                if hit is None:
                    continue
                kind, detail, chain = hit
                path = " -> ".join([key] + chain)
                emit(fn, line, col,
                     f"request-derived value `{src}` flows into "
                     f"`{callee}` and reaches {kind} sink `{detail}` "
                     f"via {path} without passing a validate_* "
                     f"sanitizer")
        return findings


def _is_test_path(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    return "tests" in parts or parts[-1].startswith("test_")


def _lookup(sinking, taints, callee: str, slot: Slot):
    """(kind, detail, call chain) if this callee arg slot reaches a sink."""
    rec = taints.get(callee)
    if rec is None:
        return None
    param = rec.slot_param(slot)
    if param is None:
        return None
    return sinking.get((callee, param))


def _sinking_params(
    taints,
) -> Dict[Tuple[str, str], Tuple[str, str, List[str]]]:
    """Fixpoint: (function key, param) -> (sink kind, sink detail,
    call chain from that function down to the sink's function)."""
    sinking: Dict[Tuple[str, str], Tuple[str, str, List[str]]] = {}
    for key, rec in taints.items():
        for param, sinks in rec.param_sinks.items():
            kind, detail = sinks[0][0], sinks[0][1]
            sinking[(key, param)] = (kind, detail, [key])
    changed = True
    while changed:
        changed = False
        for key, rec in taints.items():
            for param, calls in rec.param_calls.items():
                if (key, param) in sinking:
                    continue
                for callee, slot, _line in calls:
                    hit = _lookup(sinking, taints, callee, slot)
                    if hit is None:
                        continue
                    kind, detail, chain = hit
                    sinking[(key, param)] = (kind, detail, [key] + chain)
                    changed = True
                    break
    return sinking

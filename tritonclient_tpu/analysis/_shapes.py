"""tpushape: per-function abstract shape/sharding/donation facts.

This module is the intraprocedural half of the JAX compute-plane rules
(TPU015/TPU016/TPU017): for every function it abstractly interprets the
jnp/lax/shard_map expressions it can see and records a serializable
:class:`FunctionShapes` fact sheet. The abstract value lattice tracks,
per local name / ``self`` attribute:

* **donation state** — which jitted callables donate which argument
  slots (``donate_argnums``/``donate_argnames``), which buffers were
  passed through a donated slot and not rebound from the call result
  (poisoned), and which are read afterwards (TPU015 arm A); plus the
  inverse fact for the advisory arm: ``self.X = <arithmetic on
  self.X>`` whole-array rebuilds inside a syntactic loop, and the set
  of names this function ever donates (TPU015 arm B exoneration).
* **mesh/sharding spec** — placements from ``jax.device_put(x, S)``
  where ``S`` is a ``named_sharding``/``NamedSharding`` value, and
  consumption specs from ``shard_map``/``_partial_shard_map``
  ``in_specs`` and ``jax.jit(..., in_shardings=...)``. A value placed
  under one spec flowing into a consumer whose in-spec differs is the
  TPU016 drift fact.
* **symbolic shape dynamism** — per-request magnitudes (``len(...)``,
  ``x.shape[i]``) flowing into a *traced dimension* (slice bound,
  allocation dim, ``reshape``/``pad`` argument) of a value passed to a
  jitted callable without a recognized bucketing sanitizer
  (``*bucket*``/``*pow2*``/``*round_up*``/``*pad_to*``/``*chunk*``,
  or ``min``/``max`` against an untainted bound) — the TPU017
  compile-cache-explosion fact.

The interprocedural stitching — propagating "this parameter is consumed
under spec S" / "this parameter becomes a traced dim" backwards along
the call graph and reconstructing producer→consumer paths — lives in
the rule modules (``_tpu015_donation.py``, ``_tpu016_sharding_drift.py``,
``_tpu017_bucket.py``), on top of the cached call-graph substrate
(``_callgraph.py`` attaches a :class:`FunctionShapes` to every
``FunctionSummary`` and bumps ``CACHE_VERSION`` to 7 for it).

Known imprecision (deliberate, documented): dynamic-shaped *arrays* are
not tracked across function boundaries (only dynamic magnitudes are);
sharding specs are compared structurally by canonical text with a
single implicit mesh; and donation poisoning is path-insensitive inside
``try``/``except``. The runtime complement is ``sanitize/_jax.py``.
"""

import ast
import re
from typing import Dict, List, Optional, Set, Tuple, Union

#: Origin token: a per-request dynamic magnitude (len / .shape read).
DYN = "<dyn>"
#: Origin token: an array whose traced shape is dynamic.
DSHAPE = "<dshape>"
#: Origin prefix: dynamic-shaped array whose dim came from parameter p.
_DSHAPE_PARAM = "<dshape:"

#: Recognized bucketing sanitizers (matched against the last dotted
#: segment of the callee name, lowercase).
_BUCKET_RE = re.compile(r"bucket|pow2|round_up|pad_to|chunk|align")

#: Shape-producing constructors whose first argument (or ``shape=``) is
#: a dimension tuple.
_ALLOC_CTORS = {"zeros", "ones", "empty", "full", "arange", "iota"}

#: Calls whose result is never a dynamic magnitude.
_CLEAN_CALLS = {
    "bool", "isinstance", "issubclass", "hasattr", "callable", "id",
    "hash", "type", "sorted", "enumerate",
}

#: shard_map spellings (last dotted segment).
_SHARD_MAP_NAMES = {"shard_map", "_partial_shard_map"}

#: Spec factories (last dotted segment).
_SPEC_FACTORIES = {"named_sharding", "NamedSharding"}

Slot = Union[int, str]


class FunctionShapes:
    """Serializable shape/sharding/donation facts for one function."""

    __slots__ = (
        "params", "donate_reads", "rebuilds", "donated_names",
        "device_attrs", "spec_flows", "spec_sinks", "spec_calls",
        "placed_calls", "dyn_flows", "dyn_sinks", "dyn_calls",
        "dyn_arg_calls",
    )

    def __init__(self):
        # Parameter names as seen by CALLERS (``self``/``cls`` dropped).
        self.params: List[str] = []
        # TPU015 arm A, locally complete: a buffer read after donation.
        # [name, callee, donate_line, line, col]
        self.donate_reads: List[list] = []
        # TPU015 arm B candidates: whole-array arithmetic rebuild of a
        # ``self`` attribute inside a syntactic loop. [attr, src, line, col]
        self.rebuilds: List[list] = []
        # Names this function passes through a donated slot (arm B
        # exoneration: a donated buffer is recycled, not rebuilt).
        self.donated_names: List[str] = []
        # Device-array attributes of the enclosing class (file-local
        # pre-scan; empty for module-level functions).
        self.device_attrs: List[str] = []
        # TPU016, locally complete: placed value consumed under a
        # different spec. [src, prod_spec, cons_spec, detail, line, col]
        self.spec_flows: List[list] = []
        # {param: [[cons_spec, detail, line, col]]} — parameter consumed
        # under spec S by a shard_map/jit boundary in this function.
        self.spec_sinks: Dict[str, List[list]] = {}
        # {param: [[callee_key, slot, line]]} — parameter forwarded.
        self.spec_calls: Dict[str, List[list]] = {}
        # Placed value forwarded into a resolvable call:
        # [callee_key, slot, prod_spec, line, col, src]
        self.placed_calls: List[list] = []
        # TPU017, locally complete: dynamic-shaped operand reaches a
        # jitted callable. [detail, line, col, src]
        self.dyn_flows: List[list] = []
        # {param: [[detail, line, col]]} — param used as a traced dim of
        # an operand passed to a jitted callable in this function.
        self.dyn_sinks: Dict[str, List[list]] = {}
        # {param: [[callee_key, slot, line]]} — param forwarded as a
        # plain magnitude into a resolvable call.
        self.dyn_calls: Dict[str, List[list]] = {}
        # Dynamic magnitude forwarded into a resolvable call:
        # [callee_key, slot, line, col, src]
        self.dyn_arg_calls: List[list] = []

    def empty(self) -> bool:
        return not (
            self.donate_reads or self.rebuilds or self.donated_names
            or self.spec_flows or self.spec_sinks or self.spec_calls
            or self.placed_calls or self.dyn_flows or self.dyn_sinks
            or self.dyn_calls or self.dyn_arg_calls
        )

    def to_json(self):
        return {
            "params": self.params,
            "donate_reads": self.donate_reads,
            "rebuilds": self.rebuilds,
            "donated_names": self.donated_names,
            "device_attrs": self.device_attrs,
            "spec_flows": self.spec_flows,
            "spec_sinks": self.spec_sinks,
            "spec_calls": self.spec_calls,
            "placed_calls": self.placed_calls,
            "dyn_flows": self.dyn_flows,
            "dyn_sinks": self.dyn_sinks,
            "dyn_calls": self.dyn_calls,
            "dyn_arg_calls": self.dyn_arg_calls,
        }

    @classmethod
    def from_json(cls, d):
        s = cls()
        s.params = list(d.get("params", []))
        s.donate_reads = [list(r) for r in d.get("donate_reads", [])]
        s.rebuilds = [list(r) for r in d.get("rebuilds", [])]
        s.donated_names = list(d.get("donated_names", []))
        s.device_attrs = list(d.get("device_attrs", []))
        s.spec_flows = [list(r) for r in d.get("spec_flows", [])]
        s.spec_sinks = {
            p: [list(r) for r in rows]
            for p, rows in d.get("spec_sinks", {}).items()
        }
        s.spec_calls = {
            p: [list(r) for r in rows]
            for p, rows in d.get("spec_calls", {}).items()
        }
        s.placed_calls = [list(r) for r in d.get("placed_calls", [])]
        s.dyn_flows = [list(r) for r in d.get("dyn_flows", [])]
        s.dyn_sinks = {
            p: [list(r) for r in rows]
            for p, rows in d.get("dyn_sinks", {}).items()
        }
        s.dyn_calls = {
            p: [list(r) for r in rows]
            for p, rows in d.get("dyn_calls", {}).items()
        }
        s.dyn_arg_calls = [list(r) for r in d.get("dyn_arg_calls", [])]
        return s

    def slot_param(self, slot: Slot) -> Optional[str]:
        """Callee parameter name for a caller argument slot."""
        if isinstance(slot, str):
            return slot if slot in self.params else None
        if 0 <= slot < len(self.params):
            return self.params[slot]
        return None


def _expr_text(node: ast.AST, limit: int = 48) -> str:
    try:
        text = ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on 3.9+
        text = type(node).__name__
    return text if len(text) <= limit else text[: limit - 3] + "..."


def _target_name(node) -> Optional[str]:
    """Textual key for a plain Name or ``self.X`` attribute target."""
    if isinstance(node, ast.Name):
        return node.id
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return f"self.{node.attr}"
    return None


def canonical_spec(ctx, call: ast.Call) -> Optional[str]:
    """Canonical text of a partition spec expression.

    ``P(None, 'tp')`` -> ``"None,tp"``; ``named_sharding(mesh)`` and
    ``P(None, None)`` -> ``""`` (replicated — trailing ``None`` axes are
    dropped so the two spellings compare equal). Non-constant axis args
    render as ``$name`` so only structurally identical dynamic specs
    compare equal.
    """
    name = ctx.canonical_call_name(call.func) or ""
    last = name.rsplit(".", 1)[-1]
    args = list(call.args)
    if last in _SPEC_FACTORIES:
        if last == "NamedSharding" and len(args) >= 2:
            inner = args[1]
            if isinstance(inner, ast.Call):
                return canonical_spec(ctx, inner)
            return None
        args = args[1:]  # drop the mesh argument
    elif last not in ("P", "PartitionSpec"):
        return None
    parts = []
    for a in args:
        if isinstance(a, ast.Constant):
            parts.append("None" if a.value is None else str(a.value))
        elif isinstance(a, ast.Name):
            parts.append(f"${a.id}")
        elif isinstance(a, ast.Tuple):
            parts.append("+".join(_expr_text(e) for e in a.elts))
        else:
            parts.append(f"${_expr_text(a)}")
    while parts and parts[-1] == "None":
        parts.pop()
    return ",".join(parts)


def _spec_of_expr(ctx, node, specs: Dict[str, str]) -> Optional[str]:
    """Spec of an expression: a spec variable, or an inline factory."""
    key = _target_name(node)
    if key is not None:
        return specs.get(key)
    if isinstance(node, ast.Call):
        return canonical_spec(ctx, node)
    return None


def _donated_slots(call: ast.Call) -> Optional[List[Slot]]:
    """Donated slots of a ``jax.jit(...)`` call, or None when absent."""
    slots: List[Slot] = []
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            vals = (kw.value.elts if isinstance(kw.value, ast.Tuple)
                    else [kw.value])
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    slots.append(v.value)
        elif kw.arg == "donate_argnames":
            vals = (kw.value.elts if isinstance(kw.value, ast.Tuple)
                    else [kw.value])
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    slots.append(v.value)
    return slots or None


def _jit_call(ctx, value) -> Optional[ast.Call]:
    """``value`` itself as a ``jax.jit``/``jax.pmap`` factory call.

    Only the direct form counts: ``jax.jit(...)()`` (immediately
    invoked) produces arrays, not a callable, and must not be
    recognized here.
    """
    if isinstance(value, ast.Call):
        name = ctx.canonical_call_name(value.func)
        if name in ("jax.jit", "jax.pmap"):
            return value
    return None


def _shard_map_call(ctx, value) -> Optional[ast.Call]:
    """The ``shard_map``/``_partial_shard_map`` call in ``value``."""
    for sub in ast.walk(value):
        if isinstance(sub, ast.Call):
            name = ctx.canonical_call_name(sub.func) or ""
            if name.rsplit(".", 1)[-1] in _SHARD_MAP_NAMES:
                return sub
    return None


def _consumer_specs(ctx, call: ast.Call) -> Optional[List[Optional[str]]]:
    """Canonical ``in_specs`` of a shard_map factory call (positional
    arg 2 for ``_partial_shard_map(f, mesh, in_specs, ...)`` or the
    ``in_specs=`` keyword), or ``in_shardings`` of a jit."""
    spec_node = None
    for kw in call.keywords:
        if kw.arg in ("in_specs", "in_shardings"):
            spec_node = kw.value
            break
    if spec_node is None and len(call.args) >= 3:
        name = ctx.canonical_call_name(call.func) or ""
        if name.rsplit(".", 1)[-1] in _SHARD_MAP_NAMES:
            spec_node = call.args[2]
    if spec_node is None:
        return None
    elts = (spec_node.elts if isinstance(spec_node, (ast.Tuple, ast.List))
            else [spec_node])
    out: List[Optional[str]] = []
    for e in elts:
        out.append(_spec_of_expr(ctx, e, {}) if isinstance(e, ast.Call)
                   else None)
    return out


def _is_device_value(ctx, value) -> bool:
    """True when ``value`` contains a jax call (device-array producer)."""
    for sub in ast.walk(value):
        if isinstance(sub, ast.Call):
            name = ctx.canonical_call_name(sub.func) or ""
            if name.startswith("jax."):
                return True
    return False


class _ClassFacts:
    """File-local class-level facts: donation/spec/jit attributes
    declared anywhere in a class body (``self.X = jax.jit(...)``)."""

    __slots__ = ("donating", "specs", "consumers", "jitted", "device_attrs")

    def __init__(self):
        self.donating: Dict[str, List[Slot]] = {}   # "self.X" -> slots
        self.specs: Dict[str, str] = {}             # "self.X" -> spec
        self.consumers: Dict[str, List] = {}        # "self.X" -> in_specs
        self.jitted: Set[str] = set()               # "self.X"
        self.device_attrs: Set[str] = set()         # bare attr names


def _scan_module(ctx) -> _ClassFacts:
    """Module-level factory assignments (``step = jax.jit(f, ...)``),
    visible to every function in the file."""
    facts = _ClassFacts()
    for node in ctx.tree.body:
        if not isinstance(node, ast.Assign):
            continue
        jit = _jit_call(ctx, node.value)
        smap = _shard_map_call(ctx, node.value)
        for tgt in node.targets:
            if not isinstance(tgt, ast.Name):
                continue
            if jit is not None:
                facts.jitted.add(tgt.id)
                slots = _donated_slots(jit)
                if slots:
                    facts.donating[tgt.id] = slots
                cons = _consumer_specs(ctx, jit)
                if cons:
                    facts.consumers[tgt.id] = cons
            elif smap is not None:
                cons = _consumer_specs(ctx, smap)
                if cons:
                    facts.consumers[tgt.id] = cons
            elif isinstance(node.value, ast.Call):
                spec = canonical_spec(ctx, node.value)
                if spec is not None:
                    facts.specs[tgt.id] = spec
    return facts


def _scan_class(ctx, cls: ast.ClassDef) -> _ClassFacts:
    facts = _ClassFacts()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        jit = _jit_call(ctx, value)
        smap = _shard_map_call(ctx, value)
        spec = (canonical_spec(ctx, value)
                if isinstance(value, ast.Call) else None)
        device = _is_device_value(ctx, value)
        for tgt in node.targets:
            targets = (tgt.elts if isinstance(tgt, (ast.Tuple, ast.List))
                       else [tgt])
            for t in targets:
                key = _target_name(t)
                if key is None or not key.startswith("self."):
                    continue
                if jit is not None:
                    facts.jitted.add(key)
                    slots = _donated_slots(jit)
                    if slots:
                        facts.donating[key] = slots
                    cons = _consumer_specs(ctx, jit)
                    if cons:
                        facts.consumers[key] = cons
                elif smap is not None:
                    cons = _consumer_specs(ctx, smap)
                    if cons:
                        facts.consumers[key] = cons
                elif spec is not None:
                    facts.specs[key] = spec
                if device and jit is None:
                    facts.device_attrs.add(key.split(".", 1)[1])
    return facts


class _ShapesWalker:
    """Single-pass, flow-sensitive walk of one function body."""

    def __init__(self, ctx, modkey: str, cls: Optional[str], node,
                 cls_facts: Optional[_ClassFacts],
                 mod_facts: Optional[_ClassFacts] = None):
        self.ctx = ctx
        self.modkey = modkey
        self.cls = cls
        self.node = node
        self.out = FunctionShapes()
        # Dynamic-magnitude origins per name (param names / DYN / DSHAPE).
        self.dyn: Dict[str, Set[str]] = {}
        # Sharding state.
        self.specs: Dict[str, str] = {}
        self.placed: Dict[str, str] = {}
        self.consumers: Dict[str, List] = {}
        # Donation state.
        self.donating: Dict[str, List[Slot]] = {}
        self.jitted: Set[str] = set()
        self.poisoned: Dict[str, Tuple[str, int]] = {}
        self._loop_depth = 0
        self._seen_calls: Set[int] = set()
        self._read_seen: Set[Tuple[str, int]] = set()
        for facts in (mod_facts, cls_facts):
            if facts is None:
                continue
            self.donating.update(facts.donating)
            self.specs.update(facts.specs)
            self.consumers.update(facts.consumers)
            self.jitted.update(facts.jitted)
        if cls_facts is not None:
            self.out.device_attrs = sorted(cls_facts.device_attrs)

    # -- entry ---------------------------------------------------------

    def run(self) -> FunctionShapes:
        args = self.node.args
        names = [a.arg for a in (args.posonlyargs + args.args)]
        is_method = self.cls is not None and not any(
            isinstance(d, ast.Name) and d.id == "staticmethod"
            for d in self.node.decorator_list
        )
        if is_method and names and names[0] in ("self", "cls"):
            names = names[1:]
        for a in args.kwonlyargs:
            if a.arg not in names:
                names.append(a.arg)
        self.out.params = names
        for a in names:
            self.dyn[a] = {a}
        for stmt in self.node.body:
            self._stmt(stmt)
        return self.out

    # -- dynamic-magnitude origins -------------------------------------

    def _dyn_origins(self, node) -> Set[str]:
        if node is None or isinstance(node, (ast.Constant, ast.Lambda)):
            return set()
        if isinstance(node, ast.Name):
            return set(self.dyn.get(node.id, ()))
        if isinstance(node, ast.Attribute):
            return set()  # attribute magnitudes are not per-request
        if isinstance(node, ast.Subscript):
            return self._subscript_origins(node)
        if isinstance(node, ast.Starred):
            return self._dyn_origins(node.value)
        if isinstance(node, ast.BinOp):
            return self._dyn_origins(node.left) | self._dyn_origins(
                node.right)
        if isinstance(node, ast.UnaryOp):
            return self._dyn_origins(node.operand)
        if isinstance(node, ast.BoolOp):
            out: Set[str] = set()
            for v in node.values:
                out |= self._dyn_origins(v)
            return out
        if isinstance(node, ast.IfExp):
            return self._dyn_origins(node.body) | self._dyn_origins(
                node.orelse)
        if isinstance(node, ast.Compare):
            return set()
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            out = set()
            for e in node.elts:
                out |= self._dyn_origins(e)
            return out
        if isinstance(node, ast.Call):
            return self._dyn_call_origins(node)
        return set()

    def _subscript_origins(self, node: ast.Subscript) -> Set[str]:
        base = node.value
        # ``x.shape[i]`` — a traced-operand magnitude: per-request.
        if isinstance(base, ast.Attribute) and base.attr == "shape":
            return {DYN}
        out = self._dyn_origins(base)
        sl = node.slice
        if isinstance(sl, ast.Slice):
            bound = (self._dyn_origins(sl.lower)
                     | self._dyn_origins(sl.upper)
                     | self._dyn_origins(sl.step))
            out |= self._dim_origins(bound)
        return out

    def _dim_origins(self, magnitudes: Set[str]) -> Set[str]:
        """Origins of a value whose traced SHAPE depends on the given
        magnitude origins: DYN becomes DSHAPE, params become markers."""
        out: Set[str] = set()
        if DYN in magnitudes or DSHAPE in magnitudes:
            out.add(DSHAPE)
        for m in magnitudes:
            if m in self.out.params:
                out.add(f"{_DSHAPE_PARAM}{m}>")
            elif m.startswith(_DSHAPE_PARAM):
                out.add(m)
        return out

    def _dyn_call_origins(self, call: ast.Call) -> Set[str]:
        name = self.ctx.canonical_call_name(call.func) or ""
        last = name.rsplit(".", 1)[-1]
        if last == "len":
            return {DYN}
        if _BUCKET_RE.search(last.lower()):
            return set()  # recognized bucketing sanitizer
        if last in _CLEAN_CALLS:
            return set()
        operands = list(call.args) + [k.value for k in call.keywords]
        arg_origins: Set[str] = set()
        for a in operands:
            arg_origins |= self._dyn_origins(a)
        if last in ("min", "max"):
            if len(operands) >= 2 and any(
                not self._dyn_origins(o) for o in operands
            ):
                return set()  # capped against an untainted bound
            return arg_origins
        if last in _ALLOC_CTORS:
            dims = self._dyn_origins(call.args[0]) if call.args else set()
            for kw in call.keywords:
                if kw.arg == "shape":
                    dims |= self._dyn_origins(kw.value)
            return self._dim_origins(dims)
        if last in ("reshape", "broadcast_to", "pad", "resize"):
            return self._dim_origins(arg_origins)
        return arg_origins

    # -- callable-name resolution (shared with _taint) ------------------

    def _func_key(self, call: ast.Call) -> Optional[str]:
        """Textual key of the called name (``f`` / ``self.f``) when the
        target is a locally-tracked callable."""
        return _target_name(call.func)

    def _callee_key(self, call: ast.Call) -> Optional[str]:
        func = call.func
        if isinstance(func, ast.Name):
            target = self.ctx.aliases.get(func.id)
            if target and "." in target:
                mod, _, name = target.rpartition(".")
                if name[:1].isupper():
                    return f"{name}.__init__"
                return f"{mod.rpartition('.')[2]}:{name}"
            if func.id[:1].isupper():
                return f"{func.id}.__init__"
            return f"{self.modkey}:{func.id}"
        if isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name):
                if base.id == "self" and self.cls:
                    return f"{self.cls}.{func.attr}"
                if base.id[:1].isupper():
                    return f"{base.id}.{func.attr}"
                target = self.ctx.aliases.get(base.id)
                if target:
                    return f"{target.rpartition('.')[2]}:{func.attr}"
        return None

    # -- per-call handling ----------------------------------------------

    def _handle_call(self, call: ast.Call):
        if id(call) in self._seen_calls:
            return
        self._seen_calls.add(id(call))
        fkey = self._func_key(call)
        # TPU016: direct call of a shard_map factory result —
        # ``_partial_shard_map(body, mesh, in_specs, ...)(x, w, b)``.
        cons = None
        if fkey is not None and fkey in self.consumers:
            cons = self.consumers[fkey]
        elif isinstance(call.func, ast.Call):
            inner = call.func
            name = self.ctx.canonical_call_name(inner.func) or ""
            if name.rsplit(".", 1)[-1] in _SHARD_MAP_NAMES:
                cons = _consumer_specs(self.ctx, inner)
            else:
                jit = _jit_call(self.ctx, inner)
                if jit is not None:
                    cons = _consumer_specs(self.ctx, jit)
        if cons:
            self._check_consumer(call, fkey or _expr_text(call.func), cons)
        # TPU017: dynamic-shaped operand reaching a jitted callable.
        if fkey is not None and fkey in self.jitted:
            self._check_jit_operands(call, fkey)
        # Forwarding facts into resolvable project callees.
        self._record_forwarding(call)

    def _check_consumer(self, call: ast.Call, label: str, cons) -> None:
        for i, arg in enumerate(call.args):
            if i >= len(cons) or cons[i] is None:
                continue
            want = cons[i]
            key = _target_name(arg)
            if key is None:
                continue
            if not self.ctx.is_suppressed("TPU016", call.lineno):
                have = self.placed.get(key)
                if have is not None and have != want:
                    self.out.spec_flows.append([
                        _expr_text(arg), have, want,
                        f"{label} in_specs[{i}]", call.lineno,
                        call.col_offset,
                    ])
                elif have is None and key in self.out.params:
                    self.out.spec_sinks.setdefault(key, []).append(
                        [want, f"{label} in_specs[{i}]", call.lineno,
                         call.col_offset])

    def _check_jit_operands(self, call: ast.Call, label: str) -> None:
        if self.ctx.is_suppressed("TPU017", call.lineno):
            return
        operands = [(i, a) for i, a in enumerate(call.args)]
        operands += [(kw.arg, kw.value) for kw in call.keywords
                     if kw.arg is not None]
        for slot, arg in operands:
            origins = self._dyn_origins(arg)
            if DSHAPE in origins:
                self.out.dyn_flows.append([
                    f"traced operand of `{label}`", call.lineno,
                    call.col_offset, _expr_text(arg)])
            for o in origins:
                if o.startswith(_DSHAPE_PARAM):
                    p = o[len(_DSHAPE_PARAM):-1]
                    self.out.dyn_sinks.setdefault(p, []).append(
                        [f"traced operand of `{label}`", call.lineno,
                         call.col_offset])

    def _record_forwarding(self, call: ast.Call):
        callee = self._callee_key(call)
        if callee is None:
            return
        name = self.ctx.canonical_call_name(call.func) or ""
        last = name.rsplit(".", 1)[-1]
        if _BUCKET_RE.search(last.lower()) or last in _CLEAN_CALLS:
            return
        slots = [(i, a) for i, a in enumerate(call.args)]
        slots += [(kw.arg, kw.value) for kw in call.keywords
                  if kw.arg is not None]
        for slot, arg in slots:
            key = _target_name(arg)
            # TPU016 forwarding: placed values and bare parameters.
            if key is not None and not self.ctx.is_suppressed(
                    "TPU016", call.lineno):
                spec = self.placed.get(key)
                if spec is not None:
                    self.out.placed_calls.append(
                        [callee, slot, spec, call.lineno, call.col_offset,
                         _expr_text(arg)])
                elif key in self.out.params:
                    self.out.spec_calls.setdefault(key, []).append(
                        [callee, slot, call.lineno])
            # TPU017 forwarding: dynamic magnitudes and bare parameters.
            if self.ctx.is_suppressed("TPU017", call.lineno):
                continue
            origins = self._dyn_origins(arg)
            if DYN in origins:
                self.out.dyn_arg_calls.append(
                    [callee, slot, call.lineno, call.col_offset,
                     _expr_text(arg)])
            for p in origins:
                if p in self.out.params:
                    self.out.dyn_calls.setdefault(p, []).append(
                        [callee, slot, call.lineno])

    # -- donation (TPU015 arm A) ----------------------------------------

    def _check_poisoned_reads(self, expr):
        if expr is None or not self.poisoned:
            return
        for node in ast.walk(expr):
            key = None
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                key = node.id
            elif (isinstance(node, ast.Attribute)
                    and isinstance(node.ctx, ast.Load)):
                key = _target_name(node)
            if key is None or key not in self.poisoned:
                continue
            callee, donate_line = self.poisoned.pop(key)
            dedup = (key, node.lineno)
            if dedup in self._read_seen:
                continue
            self._read_seen.add(dedup)
            if not self.ctx.is_suppressed("TPU015", node.lineno):
                self.out.donate_reads.append(
                    [key, callee, donate_line, node.lineno,
                     node.col_offset])

    def _donation_candidates(self, expr) -> List[Tuple[str, str, int]]:
        """(buffer name, callee label, line) for args passed through a
        donated slot of any call inside ``expr``."""
        out: List[Tuple[str, str, int]] = []
        if expr is None:
            return out
        for call in ast.walk(expr):
            if not isinstance(call, ast.Call):
                continue
            fkey = self._func_key(call)
            if fkey is None or fkey not in self.donating:
                continue
            if self.ctx.is_suppressed("TPU015", call.lineno):
                continue
            for slot in self.donating[fkey]:
                arg = None
                if isinstance(slot, int) and slot < len(call.args):
                    arg = call.args[slot]
                elif isinstance(slot, str):
                    for kw in call.keywords:
                        if kw.arg == slot:
                            arg = kw.value
                key = _target_name(arg) if arg is not None else None
                if key is not None:
                    out.append((key, fkey, call.lineno))
                    if key not in self.out.donated_names:
                        self.out.donated_names.append(key)
        return out

    # -- assignments / factory recognition ------------------------------

    def _bind(self, key: str, value):
        """Track factory assignments: jit/donation/spec/shard_map/
        device_put placements and dynamic-magnitude origins."""
        jit = _jit_call(self.ctx, value)
        if jit is not None:
            self.jitted.add(key)
            slots = _donated_slots(jit)
            if slots:
                self.donating[key] = slots
            cons = _consumer_specs(self.ctx, jit)
            if cons:
                self.consumers[key] = cons
            return
        smap = _shard_map_call(self.ctx, value)
        if smap is not None:
            cons = _consumer_specs(self.ctx, smap)
            if cons:
                self.consumers[key] = cons
            return
        if isinstance(value, ast.Call):
            spec = canonical_spec(self.ctx, value)
            if spec is not None:
                self.specs[key] = spec
                return
            name = self.ctx.canonical_call_name(value.func) or ""
            if name.rsplit(".", 1)[-1] == "device_put" and len(
                    value.args) >= 2:
                spec = _spec_of_expr(self.ctx, value.args[1], self.specs)
                if spec is not None:
                    self.placed[key] = spec
                    return
        self.dyn[key] = self._dyn_origins(value)

    def _assign_targets(self, targets, value):
        flat: List = []

        def _flatten(t):
            if isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    _flatten(e)
            elif isinstance(t, ast.Starred):
                _flatten(t.value)
            else:
                flat.append(t)

        for t in targets:
            _flatten(t)
        rebound: Set[str] = set()
        for t in flat:
            key = _target_name(t)
            if key is None:
                continue
            rebound.add(key)
            self.poisoned.pop(key, None)
            self.placed.pop(key, None)
            if len(flat) == 1 and value is not None:
                self._bind(key, value)
            else:
                self.dyn[key] = (self._dyn_origins(value)
                                 if value is not None else set())
        # device_put over a tuple re-places every rebound name.
        if value is not None and isinstance(value, ast.Call):
            name = self.ctx.canonical_call_name(value.func) or ""
            if (name.rsplit(".", 1)[-1] == "device_put"
                    and len(value.args) >= 2):
                spec = _spec_of_expr(self.ctx, value.args[1], self.specs)
                if spec is not None:
                    for key in rebound:
                        self.placed[key] = spec
        return rebound

    def _check_rebuild(self, stmt: ast.Assign):
        """TPU015 arm B candidate: ``self.X = <binop on self.X>`` inside
        a loop — a whole-array rebuild allocating a fresh buffer per
        iteration (scatter updates via ``.at[].set()`` are exempt)."""
        if self._loop_depth == 0 or len(stmt.targets) != 1:
            return
        key = _target_name(stmt.targets[0])
        if key is None or not key.startswith("self."):
            return
        if not isinstance(stmt.value, ast.BinOp):
            return
        attr = key.split(".", 1)[1]
        if attr not in set(self.out.device_attrs):
            return
        reads_self = any(
            _target_name(n) == key
            for n in ast.walk(stmt.value)
            if isinstance(n, ast.Attribute)
        )
        if not reads_self:
            return
        if self.ctx.is_suppressed("TPU015", stmt.lineno):
            return
        row = [attr, _expr_text(stmt), stmt.lineno, stmt.col_offset]
        if row not in self.out.rebuilds:
            self.out.rebuilds.append(row)

    # -- statements -----------------------------------------------------

    def _scan(self, expr):
        if expr is None:
            return
        self._check_poisoned_reads(expr)
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._handle_call(node)

    def _stmt(self, stmt):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs get their own walk
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            value = stmt.value
            self._scan(value)
            candidates = self._donation_candidates(value)
            if isinstance(stmt, ast.Assign):
                self._check_rebuild(stmt)
                rebound = self._assign_targets(stmt.targets, value)
            elif isinstance(stmt, ast.AugAssign):
                self._check_poisoned_reads(stmt.target)
                key = _target_name(stmt.target)
                rebound = set()
                if key is not None:
                    self.dyn[key] = (set(self.dyn.get(key, ()))
                                     | self._dyn_origins(value))
            else:
                rebound = (self._assign_targets([stmt.target], value)
                           if stmt.target is not None else set())
            for key, callee, line in candidates:
                if key not in rebound:
                    self.poisoned[key] = (callee, line)
            return
        if isinstance(stmt, ast.Expr):
            self._scan(stmt.value)
            for key, callee, line in self._donation_candidates(stmt.value):
                self.poisoned[key] = (callee, line)
            return
        if isinstance(stmt, ast.If):
            self._scan(stmt.test)
            before = dict(self.poisoned)
            for s in stmt.body:
                self._stmt(s)
            after_body = self.poisoned
            self.poisoned = dict(before)
            for s in stmt.orelse:
                self._stmt(s)
            self.poisoned.update(after_body)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._scan(stmt.iter)
                self._assign_targets([stmt.target], None)
            else:
                self._scan(stmt.test)
            self._loop_depth += 1
            # Two passes: a donation at the loop tail poisons reads at
            # the next iteration's head (dedup keeps findings single).
            for _ in range(2):
                for s in stmt.body:
                    self._stmt(s)
            self._loop_depth -= 1
            for s in stmt.orelse:
                self._stmt(s)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan(item.context_expr)
                if item.optional_vars is not None:
                    self._assign_targets([item.optional_vars], None)
            for s in stmt.body:
                self._stmt(s)
            return
        if isinstance(stmt, ast.Try):
            for s in stmt.body:
                self._stmt(s)
            for h in stmt.handlers:
                for s in h.body:
                    self._stmt(s)
            for s in stmt.orelse + stmt.finalbody:
                self._stmt(s)
            return
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                key = _target_name(t)
                if key is not None:
                    self.poisoned.pop(key, None)
            return
        if isinstance(stmt, (ast.Return, ast.Raise, ast.Assert)):
            for child in ast.iter_child_nodes(stmt):
                self._scan(child)
            return
        # pass / break / continue / global / import — nothing to do.


def extract_file_shapes(ctx, modkey: str) -> Dict[str, FunctionShapes]:
    """Shape facts for every function in a file, keyed like
    ``summarize_file`` keys its ``FunctionSummary`` rows."""
    out: Dict[str, FunctionShapes] = {}
    mod_facts = _scan_module(ctx)
    class_facts: Dict[str, _ClassFacts] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ClassDef):
            class_facts[node.name] = _scan_class(ctx, node)

    def walk(node, cls: Optional[str], key: str):
        facts = class_facts.get(cls) if cls else None
        out[key] = _ShapesWalker(ctx, modkey, cls, node, facts,
                                 mod_facts).run()
        for child in ast.walk(node):
            if child is node:
                continue
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if ctx.enclosing_function(child) is node:
                    walk(child, cls, f"{key}.<locals>.{child.name}")

    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if ctx.enclosing_function(node) is not None:
            continue
        cls = ctx.enclosing_class(node)
        if cls is not None:
            walk(node, cls.name, f"{cls.name}.{node.name}")
        else:
            walk(node, None, f"{modkey}:{node.name}")
    return out

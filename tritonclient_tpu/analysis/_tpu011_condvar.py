"""TPU011: condition-variable discipline.

Condition variables have a four-part contract that Python enforces no
part of: waits must re-check their predicate in a loop (wakeups can be
stolen or spurious), the predicate must only change under the cv's
lock (or the waiter can test-then-sleep right across the update — the
lost-wakeup window), notify must be issued with the lock held, and a
*timed* wait's return value must be consulted (a ``False`` return means
the predicate may still be false). The model checker (``tpumc``)
witnesses the lost-wakeup schedule dynamically; this rule finds the
shapes statically, interprocedurally, from the same call-graph
substrate TPU009 uses (``_callgraph.py`` records every
``wait``/``wait_for``/``notify``/``notify_all`` on a *declared
Condition* as a :class:`~tritonclient_tpu.analysis._callgraph.CvSite`;
method calls on Events/queues are not cv sites).

Five arms, all keyed to declared ``named_condition`` locks:

* **wait-no-loop** — an untimed ``wait()`` whose call site is not
  inside a loop. ``wait_for`` is exempt (it loops internally); timed
  waits are handled by the next arm instead.
* **timeout-ignored** — a timed ``wait``/``wait_for`` used as a bare
  expression statement: the ``False``-on-timeout result is dropped, so
  timeout and wakeup become indistinguishable. Exempt when the wait
  sits inside a loop whose test re-reads a ``self.*`` predicate — the
  loop re-check subsumes the result, which is then redundant by
  construction (``while not self._pending: cv.wait(timeout=...)``).
* **notify-without-lock** — ``notify``/``notify_all`` whose effective
  lockset (lexically held ∪ provably-held-at-entry, the TPU009
  fixpoint) does not include the cv. Python raises at runtime, but
  only on the paths that execute.
* **predicate-outside-lock** — the lost-wakeup shape. The predicate
  attributes of each wait (the enclosing ``while``/``if`` test, or the
  ``wait_for`` callable) are collected; any post-``__init__`` write to
  one of them anywhere in the program whose effective lockset misses
  the cv is the write a waiter can sleep across. Self-synchronizing
  attributes (queues, events) are exempt — their signal is the
  operation itself.
* **notify-no-write** — a notify whose enclosing function, its
  transitive callees, *and every direct caller's subtree* perform no
  attribute write and no wakeup-visible signal (``put``/``set``/…):
  the wakeup conveys no state change, so every correctly-looping
  waiter re-checks an unchanged predicate. Callers count so the
  ``self._mutate(); self._notify()`` helper split stays clean.
  Deliberately conservative; any write anywhere suppresses it.

Findings in test files are dropped (tests drive quiesced internals;
the tpumc harnesses are the dynamic witness there). Deliberate
violations — e.g. a timed wait used purely as a bounded park where the
loop re-derives all state — suppress with ``# tpulint:
disable=TPU011`` on the line or ``def``, with a comment saying why.
"""

from typing import Dict, List, Sequence, Set, Tuple

from tritonclient_tpu.analysis import _callgraph
from tritonclient_tpu.analysis._engine import FileContext, Finding, Rule


class CondvarDisciplineRule(Rule):
    id = "TPU011"
    name = "condvar-discipline"
    description = (
        "condition-variable discipline: wait without predicate loop, "
        "ignored timeout result, notify without lock or without a "
        "predicate write, predicate mutated outside the cv's lock"
    )

    def check_project(self, ctxs: Sequence[FileContext]) -> List[Finding]:
        if not ctxs:
            return []
        graph = _callgraph.get_callgraph(ctxs)
        linted = {
            ctx.path for ctx in ctxs if not _is_test_path(ctx.path)
        }
        findings: List[Finding] = []
        for key in sorted(graph.functions):
            fn = graph.functions[key]
            if fn.path not in linted:
                continue
            for site in fn.cvsites:
                findings.extend(_check_site(graph, key, fn, site, linted))
        return findings


def _is_test_path(path: str) -> bool:
    parts = path.split("/")
    return "tests" in parts or parts[-1].startswith("test_")


def _site_locks(graph, key: str, site) -> frozenset:
    return frozenset(site.locks) | graph.entry_lockset(key)


def _check_site(graph, key, fn, site, linted) -> List[Finding]:
    if site.kind in ("wait", "wait_for"):
        out = []
        if (site.kind == "wait" and not site.timed
                and not site.in_loop):
            out.append(Finding(
                CondvarDisciplineRule.id, fn.path, site.line, site.col,
                f"`{site.cv}.wait()` in `{key}` is not inside a "
                f"predicate re-check loop: a stolen or spurious wakeup "
                f"proceeds with the condition still false; use `while "
                f"not <pred>: wait()` or `wait_for(<pred>)`",
            ))
        if (site.timed and not site.result_used
                and not (site.in_loop and site.preds)):
            out.append(Finding(
                CondvarDisciplineRule.id, fn.path, site.line, site.col,
                f"result of timed `{site.cv}.{site.kind}(timeout=...)` "
                f"in `{key}` is ignored: a False return means the "
                f"timeout fired with the predicate still false — check "
                f"the result or re-test the predicate before acting",
            ))
        out.extend(_check_predicate_writes(graph, key, site, linted))
        return out
    # notify / notify_all
    out = []
    held = _site_locks(graph, key, site)
    if site.cv not in held:
        shown = ", ".join(f"`{l}`" for l in sorted(held)) or "none"
        out.append(Finding(
            CondvarDisciplineRule.id, fn.path, site.line, site.col,
            f"`{site.cv}.{site.kind}()` in `{key}` without holding "
            f"`{site.cv}` (effective locks: {shown}): notify requires "
            f"the cv's lock, and the unlocked window loses wakeups",
        ))
    if not _subtree_writes(graph, key) and not any(
            _subtree_writes(graph, caller)
            for caller, _held in graph.callers.get(key, ())):
        out.append(Finding(
            CondvarDisciplineRule.id, fn.path, site.line, site.col,
            f"`{site.cv}.{site.kind}()` in `{key}` with no predicate "
            f"write in the function or its callees: the wakeup conveys "
            f"no state change, so waiters re-check an unchanged "
            f"predicate",
        ))
    return out


def _check_predicate_writes(graph, key, site, linted) -> List[Finding]:
    """The lost-wakeup arm: a write to a wait's predicate attribute
    anywhere in the program without the cv held is the update a waiter
    can test-then-sleep across."""
    fn = graph.functions[key]
    cls = fn.cls
    if not cls or not site.preds:
        return []
    findings = []
    for attr in site.preds:
        bad: Set[str] = set()
        for wkey, wfn in graph.functions.items():
            for a in wfn.accesses:
                if (a.owner != cls or a.attr != attr or not a.write
                        or a.in_init):
                    continue
                if site.cv not in graph.effective_locks(wkey, a):
                    bad.add(wkey)
        if not bad:
            continue
        writers = ", ".join(f"`{w}`" for w in sorted(bad))
        findings.append(Finding(
            CondvarDisciplineRule.id, fn.path, site.line, site.col,
            f"wait predicate `{cls}.{attr}` (awaited on `{site.cv}` in "
            f"`{key}`) is written without `{site.cv}` held in {writers}"
            f": the waiter can test-then-sleep across that update and "
            f"miss its wakeup",
        ))
    return findings


_SUBTREE_CACHE_ATTR = "_tpu011_subtree_writes"


def _subtree_writes(graph, key: str) -> bool:
    """Does ``key`` or any transitive callee perform a post-init
    attribute write or a wakeup-visible signal (queue put, event set)?
    Memoized on the graph: the call subtree is the same for every
    notify site in a function."""
    cache: Dict[str, bool] = getattr(graph, _SUBTREE_CACHE_ATTR, None)
    if cache is None:
        cache = {}
        setattr(graph, _SUBTREE_CACHE_ATTR, cache)
    if key in cache:
        return cache[key]
    seen: Set[str] = set()
    stack = [key]
    result = False
    while stack:
        k = stack.pop()
        if k in seen:
            continue
        seen.add(k)
        fn = graph.functions.get(k)
        if fn is None:
            continue
        if fn.signals or any(
                a.write and not a.in_init for a in fn.accesses):
            result = True
            break
        for callee, _held, _line in fn.calls:
            if callee in graph.functions:
                stack.append(callee)
    cache[key] = result
    return result

"""tpulint core: file model, suppression handling, rule runner, reporters.

The engine is deliberately small: a rule gets a parsed ``FileContext`` (or
the whole list for project-level rules) and returns ``Finding`` objects;
the engine owns file discovery, ``# tpulint: disable=RULE`` suppression,
ordering, and output. Rules never print.
"""

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set

#: Files never worth analyzing: generated protobuf, caches, build output.
_SKIP_PARTS = {"__pycache__", ".git", "build", ".eggs"}
_SKIP_NAMES = {"kserve_pb2.py"}

_SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*disable(?P<scope>-file)?\s*=\s*"
    r"(?P<rules>[A-Z]+[0-9]+(?:\s*,\s*[A-Z]+[0-9]+)*)"
)


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str

    def text(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }

    def fingerprint(self) -> str:
        """Line-number-independent identity for baseline matching: a
        finding survives unrelated edits shifting it up or down."""
        return f"{self.rule}::{self.path}::{self.message}"


class FileContext:
    """One parsed source file plus the derived maps rules need."""

    def __init__(self, path: str, source: str):
        self.path = path.replace(os.sep, "/")
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.aliases = _collect_aliases(self.tree)
        self.file_suppressions: Set[str] = set()
        self.line_suppressions: Dict[int, Set[str]] = {}
        self._collect_suppressions()

    # -- suppressions --------------------------------------------------------

    def _collect_suppressions(self):
        comment_lines: Dict[int, Set[str]] = {}
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                rules = {r.strip() for r in m.group("rules").split(",")}
                if m.group("scope"):
                    self.file_suppressions |= rules
                else:
                    comment_lines.setdefault(tok.start[0], set()).update(rules)
        except tokenize.TokenError:
            pass
        for line, rules in comment_lines.items():
            self.line_suppressions.setdefault(line, set()).update(rules)
        # A suppression on (or immediately above) a def/class line covers the
        # whole body — the idiom for "caller holds the lock" methods.
        for node in ast.walk(self.tree):
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            first = min(
                [node.lineno] + [d.lineno for d in node.decorator_list]
            )
            rules = set()
            for line in (first - 1, first, node.lineno):
                rules |= comment_lines.get(line, set())
            if rules:
                for line in range(node.lineno, (node.end_lineno or node.lineno) + 1):
                    self.line_suppressions.setdefault(line, set()).update(rules)

    def is_suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_suppressions:
            return True
        return rule in self.line_suppressions.get(line, ())

    # -- shared AST helpers --------------------------------------------------

    def canonical_call_name(self, func: ast.AST) -> Optional[str]:
        """Dotted name of a call target with import aliases resolved.

        ``_time.sleep`` -> ``time.sleep`` when the file did ``import time as
        _time``; returns None for dynamic targets (``self.x()``, calls on
        call results, subscripts).
        """
        parts: List[str] = []
        node = func
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        parts.append(node.id)
        parts.reverse()
        head = self.aliases.get(parts[0])
        if head is not None:
            parts[0:1] = head.split(".")
        return ".".join(parts)

    def is_docstring(self, node: ast.Constant) -> bool:
        parent = self.parents.get(node)
        if not isinstance(parent, ast.Expr):
            return False
        grand = self.parents.get(parent)
        return isinstance(
            grand, (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)
        )

    def enclosing_function(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None

    def enclosing_class(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = self.parents.get(cur)
        return None


def _collect_aliases(tree: ast.Module) -> Dict[str, str]:
    """Flat import-alias map for the whole file (locals included: a
    project linter does not need per-scope namespaces)."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                bound = alias.asname or alias.name.split(".")[0]
                target = alias.name if alias.asname else alias.name.split(".")[0]
                aliases[bound] = target
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for alias in node.names:
                if alias.name == "*":
                    continue
                bound = alias.asname or alias.name
                aliases[bound] = f"{node.module}.{alias.name}"
    return aliases


class Rule:
    """Base rule. Subclasses set ``id``/``name``/``description`` and
    implement ``check_file`` and/or ``check_project``."""

    id = "TPU000"
    name = "base"
    description = ""

    def check_file(self, ctx: FileContext) -> List[Finding]:
        return []

    def check_project(self, ctxs: Sequence[FileContext]) -> List[Finding]:
        return []


def default_rules() -> List[Rule]:
    from tritonclient_tpu.analysis._tpu001_async_blocking import AsyncBlockingRule
    from tritonclient_tpu.analysis._tpu002_lock_discipline import LockDisciplineRule
    from tritonclient_tpu.analysis._tpu003_literals import ProtocolLiteralRule
    from tritonclient_tpu.analysis._tpu004_dtype_map import DtypeMapRule
    from tritonclient_tpu.analysis._tpu005_resource_leak import ResourceLeakRule
    from tritonclient_tpu.analysis._tpu006_shm_lifecycle import ShmLifecycleRule
    from tritonclient_tpu.analysis._tpu007_lock_order import LockOrderRule
    from tritonclient_tpu.analysis._tpu008_protocol_drift import ProtocolDriftRule
    from tritonclient_tpu.analysis._tpu009_guarded_by import GuardedByRule
    from tritonclient_tpu.analysis._tpu010_jax_hazard import JaxHazardRule
    from tritonclient_tpu.analysis._tpu011_condvar import CondvarDisciplineRule
    from tritonclient_tpu.analysis._tpu013_taint import UntrustedSinkRule
    from tritonclient_tpu.analysis._tpu014_validation_drift import (
        ValidationDriftRule,
    )
    from tritonclient_tpu.analysis._tpu015_donation import (
        DonationDisciplineRule,
    )
    from tritonclient_tpu.analysis._tpu016_sharding_drift import (
        ShardingDriftRule,
    )
    from tritonclient_tpu.analysis._tpu017_bucket import BucketDisciplineRule

    return [
        AsyncBlockingRule(),
        LockDisciplineRule(),
        ProtocolLiteralRule(),
        DtypeMapRule(),
        ResourceLeakRule(),
        ShmLifecycleRule(),
        LockOrderRule(),
        ProtocolDriftRule(),
        GuardedByRule(),
        JaxHazardRule(),
        CondvarDisciplineRule(),
        UntrustedSinkRule(),
        ValidationDriftRule(),
        DonationDisciplineRule(),
        ShardingDriftRule(),
        BucketDisciplineRule(),
    ]


def discover_files(paths: Iterable[str]) -> List[str]:
    files: List[str] = []
    for path in paths:
        if os.path.isfile(path):
            files.append(path)
            continue
        for root, dirs, names in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_PARTS)
            for name in sorted(names):
                if name.endswith(".py") and name not in _SKIP_NAMES:
                    files.append(os.path.join(root, name))
    return files


def run_analysis(
    paths: Sequence[str], select: Optional[Set[str]] = None
):
    """Lint ``paths`` (files or directories).

    Returns ``(findings, files_checked)``; findings are sorted and already
    filtered through suppressions.
    """
    rules = [r for r in default_rules() if select is None or r.id in select]
    ctxs: List[FileContext] = []
    findings: List[Finding] = []
    files = discover_files(paths)
    for path in files:
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            findings.append(Finding("PARSE", path, 1, 0, f"unreadable: {e}"))
            continue
        try:
            ctxs.append(FileContext(path, source))
        except SyntaxError as e:
            findings.append(
                Finding("PARSE", path, e.lineno or 1, 0, f"syntax error: {e.msg}")
            )
    for rule in rules:
        for ctx in ctxs:
            for finding in rule.check_file(ctx):
                if not ctx.is_suppressed(finding.rule, finding.line):
                    findings.append(finding)
        for finding in rule.check_project(ctxs):
            ctx = next((c for c in ctxs if c.path == finding.path), None)
            if ctx is None or not ctx.is_suppressed(finding.rule, finding.line):
                findings.append(finding)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, len(files)


def render_text(findings: Sequence[Finding], files_checked: int) -> str:
    lines = [f.text() for f in findings]
    noun = "finding" if len(findings) == 1 else "findings"
    lines.append(f"tpulint: {len(findings)} {noun} in {files_checked} files")
    return "\n".join(lines)


def render_json(findings: Sequence[Finding], files_checked: int) -> str:
    return json.dumps(
        {
            "tool": "tpulint",
            "files_checked": files_checked,
            "findings": [f.to_dict() for f in findings],
        },
        indent=2,
    )


def render_sarif(findings: Sequence[Finding], files_checked: int) -> str:
    """SARIF 2.1.0 for the static tier; the document shape lives in
    ``analysis/_sarif.py``, shared with the tpusan runtime tier so both
    outputs merge in code scanning and baselines."""
    from tritonclient_tpu.analysis._sarif import render_sarif as _render

    rules_meta = [
        {
            "id": rule.id,
            "name": rule.name,
            "shortDescription": {"text": rule.description},
        }
        for rule in default_rules()
    ]
    return _render(findings, rules_meta, tool_name="tpulint")

"""TPU005: resources acquired without guaranteed release.

Flags ``name = <acquiring call>`` where the acquired handle (file, mmap,
socket, HTTP connection, shm region, temp file) is a function local that

* is never used as a context manager (``with`` item, including
  ``contextlib.closing``),
* has no release call (``.close()`` etc., or ``os.close(fd)``) inside a
  ``finally`` block or ``except`` handler, and
* never escapes the function (returned/yielded, stored into an attribute,
  subscript, or container, or passed to another call — ownership transfer).

A release on the straight-line path only (``conn.close()`` not in a
``finally``) still flags: the exception path leaks. That is precisely the
bug class named by the rule — shm/file/trace handles must release on *all*
paths.
"""

import ast
from typing import List, Optional, Set

from tritonclient_tpu.analysis._engine import FileContext, Finding, Rule

_ACQUIRERS = {
    "open",
    "io.open",
    "os.open",
    "os.fdopen",
    "os.dup",
    "mmap.mmap",
    "gzip.open",
    "bz2.open",
    "lzma.open",
    "socket.socket",
    "socket.create_connection",
    "http.client.HTTPConnection",
    "http.client.HTTPSConnection",
    "tempfile.TemporaryFile",
    "tempfile.NamedTemporaryFile",
    "tempfile.mkstemp",
    "logging.FileHandler",
}

_RELEASE_METHODS = {"close", "shutdown", "release", "terminate", "unlink"}
_RELEASE_CALLS = {"os.close"}

#: Calls that USE a handle without taking ownership of it — passing a
#: handle here is not an escape, so the function still owes the release.
_NON_OWNING_CALLS = {
    "os.read",
    "os.write",
    "os.lseek",
    "os.fstat",
    "os.fsync",
    "os.ftruncate",
    "os.isatty",
    "print",
    "len",
    "repr",
    "str",
}


class ResourceLeakRule(Rule):
    id = "TPU005"
    name = "resource-leak"
    description = (
        "resource handle acquired without with/finally release on all paths"
    )

    def check_file(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(self._check_function(ctx, node))
        return findings

    def _check_function(self, ctx, func) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(func):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            if ctx.enclosing_function(node) is not func:
                continue  # nested functions get their own pass
            target = node.targets[0]
            if not isinstance(target, ast.Name):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            name = ctx.canonical_call_name(node.value.func)
            if name not in _ACQUIRERS:
                continue
            verdict = self._audit(ctx, func, node, target.id)
            if verdict is not None:
                findings.append(
                    Finding(
                        self.id,
                        ctx.path,
                        node.lineno,
                        node.col_offset,
                        f"`{target.id}` acquired via `{name}` {verdict}",
                    )
                )
        return findings

    def _audit(self, ctx, func, assign, var: str) -> Optional[str]:
        """None when the handle is safely managed, else the complaint."""
        released_in_cleanup = False
        released_anywhere = False
        cleanup_nodes = self._cleanup_nodes(func)
        for node in ast.walk(func):
            if getattr(node, "lineno", assign.lineno) < assign.lineno:
                continue
            if isinstance(node, (ast.With, ast.AsyncWith)):
                if any(
                    self._mentions(item.context_expr, var)
                    for item in node.items
                ):
                    return None  # context-managed
            elif isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
                if node.value is not None and self._escapes(node.value, var):
                    return None  # ownership leaves the function
            elif isinstance(node, ast.Assign) and node is not assign:
                if self._escapes(node.value, var) and any(
                    isinstance(t, (ast.Attribute, ast.Subscript, ast.Tuple))
                    for t in node.targets
                ):
                    return None  # stored beyond the local scope
            elif isinstance(node, ast.Call) and node is not assign.value:
                cname = ctx.canonical_call_name(node.func)
                is_release = (
                    cname in _RELEASE_CALLS
                    and any(
                        isinstance(a, ast.Name) and a.id == var
                        for a in node.args
                    )
                ) or (
                    isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == var
                    and node.func.attr in _RELEASE_METHODS
                )
                if is_release:
                    released_anywhere = True
                    if node in cleanup_nodes:
                        released_in_cleanup = True
                    continue
                if cname in _NON_OWNING_CALLS:
                    continue  # uses the handle, keeps ownership with us
                args = list(node.args) + [kw.value for kw in node.keywords]
                if any(self._escapes(a, var) for a in args):
                    return None  # handed to another owner
            elif isinstance(node, (ast.Dict, ast.List, ast.Set)):
                if self._mentions(node, var):
                    return None  # placed in a container that may outlive us
        if released_in_cleanup:
            return None
        if released_anywhere:
            return (
                "is released only on the straight-line path; move the "
                "release into a finally block or use `with`"
            )
        return "is never released; use `with`, or release it in a finally block"

    @staticmethod
    def _cleanup_nodes(func) -> Set[ast.AST]:
        """Every node lexically inside a finally block or except handler."""
        out: Set[ast.AST] = set()
        for node in ast.walk(func):
            stmts = []
            if isinstance(node, ast.Try) and node.finalbody:
                stmts.extend(node.finalbody)
            if isinstance(node, ast.ExceptHandler):
                stmts.extend(node.body)
            for stmt in stmts:
                out.update(ast.walk(stmt))
        return out

    @staticmethod
    def _mentions(node: ast.AST, var: str) -> bool:
        return any(
            isinstance(sub, ast.Name) and sub.id == var
            for sub in ast.walk(node)
        )

    @classmethod
    def _escapes(cls, node: ast.AST, var: str) -> bool:
        """True when ``var`` itself flows through ``node`` — as the bare
        name, inside a container, or as a call argument. ``var.method()``
        does NOT escape (the handle is only the receiver)."""
        parents = {}
        for parent in ast.walk(node):
            for child in ast.iter_child_nodes(parent):
                parents[child] = parent
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id == var:
                parent = parents.get(sub)
                if isinstance(parent, ast.Attribute) and parent.value is sub:
                    continue  # receiver of var.attr — not the handle itself
                return True
        return False

"""``python -m tritonclient_tpu.analysis`` — run tpulint."""

import sys

from tritonclient_tpu.analysis import main

if __name__ == "__main__":
    sys.exit(main())

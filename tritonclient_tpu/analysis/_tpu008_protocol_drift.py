"""TPU008: client/server protocol-drift conformance (project-wide).

The KServe v2 wire vocabulary lives in ``protocol/_literals.py``; the
*usage* of that vocabulary is split across four surfaces per transport
plane: the sync and aio clients build tensor/parameter dicts (HTTP JSON)
or proto maps (gRPC), and the matching server front-end parses them. A
key added on one side without the other is exactly the drift that used to
surface only as a runtime 400.

This rule diffs actual key usage per plane:

* **shed/quota-status conformance** — the deadline-aware scheduling
  statuses (HTTP 504 for shed, 499 for client-cancelled) and the fleet
  router's over-quota status (429) live in ``protocol/_literals.py`` as
  ``STATUS_SHED``/``STATUS_CANCELLED``/``STATUS_OVER_QUOTA``, and the
  tenant header as ``HEADER_TENANT_ID``; a protocol-plane file (client
  packages, server front-ends, the core, the fleet router) spelling any
  of them raw is the same drift vector as a respelled key.

* **plane symmetry** — for every *tensor-scope* canonical key (the keys
  that change how tensor bytes are routed or encoded: the shared-memory
  trio, the binary-data family, ``classification``), the set referenced
  by a plane's client modules must equal the set referenced by that
  plane's server front-end. Request-level parameter keys
  (``RESERVED_REQUEST_PARAMS``, repository controls, stream markers) are
  exempt: the front-ends forward them wholesale into
  ``CoreRequest.parameters``.
* **trio requiredness** — a side of a plane that references
  ``shared_memory_region`` must also reference
  ``shared_memory_byte_size`` and ``shared_memory_offset``: parsing the
  region name while ignoring its offset misreads every nonzero-offset
  tensor.

References are counted from ``KEY_*`` names and ``.KEY_*`` attributes
used *outside* import statements (an unused import is not conformance),
plus raw string literals equal to a canonical key value (drift through a
respelled literal still counts as usage — TPU003 flags the respelling
itself). The canonical set is parsed from a linted ``_literals.py`` when
present, else imported.

Findings are reported on the file that HAS the key, naming the side that
lacks it — the fix is either to parse the key on the missing side or to
remove it from the producing side.
"""

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tritonclient_tpu.analysis._engine import FileContext, Finding, Rule

#: Keys whose values ride CoreRequest.parameters wholesale: the server
#: never names them, so no server-side reference is owed. Kept in sync
#: with RESERVED_REQUEST_PARAMS plus repository/stream controls.
_PASSTHROUGH_KEYS = {
    "sequence_id",
    "sequence_start",
    "sequence_end",
    "priority",
    "timeout",
    "unload_dependents",
    # gRPC decoupled-stream markers: request-side read by the stream
    # servicer, response-side surfaced to user callbacks generically.
    "triton_enable_empty_final_response",
    "triton_final_response",
}

_SHM_TRIO = (
    "shared_memory_region",
    "shared_memory_byte_size",
    "shared_memory_offset",
)

#: Status values whose raw spelling in a protocol-plane file is drift
#: (use STATUS_SHED / STATUS_CANCELLED / STATUS_OVER_QUOTA from
#: protocol/_literals).
_SHED_STATUS_NAMES = {
    504: "STATUS_SHED",
    499: "STATUS_CANCELLED",
    429: "STATUS_OVER_QUOTA",
}

#: Validation statuses (the untrusted-request vocabulary): a raw 400 or
#: 413 in a protocol-plane file is the same drift vector — the two
#: planes must answer malformed input with the SAME status, so it gets
#: one spelling, in protocol/_literals.
_VALIDATION_STATUS_NAMES = {
    400: "STATUS_INVALID",
    413: "STATUS_TOO_LARGE",
}

#: Canonical invalid-request reasons (the label vocabulary of
#: nv_inference_invalid_request_total and the flight record's
#: ``invalid.reason``). A raw respelling mints a metric row no dashboard
#: aggregates and no alert matches.
_INVALID_REASON_NAMES = {
    "malformed": "INVALID_REASON_MALFORMED",
    "invalid_shape": "INVALID_REASON_SHAPE",
    "invalid_dtype": "INVALID_REASON_DTYPE",
    "data_mismatch": "INVALID_REASON_DATA_MISMATCH",
    "shm_bounds": "INVALID_REASON_SHM_BOUNDS",
    "too_large": "INVALID_REASON_TOO_LARGE",
}

#: Header/metadata keys whose raw spelling in a protocol-plane file is
#: drift: a router admitting one spelling while the replica stamps
#: another silently un-attributes every record — and a proxy honoring
#: one idempotency-key spelling while a client stamps another silently
#: disables every replay.
_HEADER_LITERAL_NAMES = {
    "tenant-id": "HEADER_TENANT_ID",
    "idempotency-key": "HEADER_IDEMPOTENCY_KEY",
    "retry-attempt": "HEADER_RETRY_ATTEMPT",
    "hedge-attempt": "HEADER_HEDGE_ATTEMPT",
    "retry-after": "HEADER_RETRY_AFTER",
}


class _Side:
    """Key usage of one (plane, side): key -> first (path, line) seen."""

    def __init__(self, label: str):
        self.label = label
        self.uses: Dict[str, Tuple[str, int]] = {}
        self.files: Set[str] = set()

    def add(self, key: str, path: str, line: int):
        self.uses.setdefault(key, (path, line))
        self.files.add(path)


class ProtocolDriftRule(Rule):
    id = "TPU008"
    name = "protocol-drift"
    description = (
        "wire key built by a plane's client but not parsed by its server "
        "front-end (or vice versa), or an incomplete shared-memory key trio"
    )

    def check_project(self, ctxs: Sequence[FileContext]) -> List[Finding]:
        canonical = self._canonical_keys(ctxs)
        if not canonical:
            return []
        sides: Dict[str, _Side] = {
            "http-client": _Side("HTTP client"),
            "http-server": _Side("HTTP server front-end"),
            "grpc-client": _Side("gRPC client"),
            "grpc-server": _Side("gRPC server front-end"),
        }
        for ctx in ctxs:
            side = self._side_of(ctx.path)
            if side is None:
                continue
            for key, line in self._key_references(ctx, canonical):
                sides[side].add(key, ctx.path, line)

        findings: List[Finding] = []
        tensor_keys = canonical - _PASSTHROUGH_KEYS
        for plane in ("http", "grpc"):
            client = sides[f"{plane}-client"]
            server = sides[f"{plane}-server"]
            if not client.files or not server.files:
                continue  # plane not present in the linted set
            cset = set(client.uses) & tensor_keys
            sset = set(server.uses) & tensor_keys
            for key in sorted(cset - sset):
                path, line = client.uses[key]
                findings.append(
                    Finding(
                        self.id, path, line, 0,
                        f"wire key '{key}' is built by the {client.label} "
                        f"but never parsed by the {server.label} "
                        f"({plane} plane) — protocol drift",
                    )
                )
            for key in sorted(sset - cset):
                path, line = server.uses[key]
                findings.append(
                    Finding(
                        self.id, path, line, 0,
                        f"wire key '{key}' is parsed by the {server.label} "
                        f"but never built by the {client.label} "
                        f"({plane} plane) — protocol drift",
                    )
                )
            for side in (client, server):
                present = [k for k in _SHM_TRIO if k in side.uses]
                missing = [
                    k for k in _SHM_TRIO
                    if k in canonical and k not in side.uses
                ]
                if present and missing:
                    path, line = side.uses[present[0]]
                    findings.append(
                        Finding(
                            self.id, path, line, 0,
                            f"the {side.label} ({plane} plane) references "
                            f"'{present[0]}' but not "
                            f"{', '.join(repr(k) for k in missing)} — "
                            "incomplete shared-memory key trio "
                            "(nonzero offsets/sizes would be ignored)",
                        )
                    )
        findings.extend(self._shed_status_findings(ctxs))
        return findings

    # -- shed-status conformance -----------------------------------------------

    @staticmethod
    def _in_protocol_plane(path: str) -> bool:
        # Same path-segment classification as _side_of, plus the server
        # core (which raises the shed CoreErrors the front-ends map) and
        # the fleet router (which answers 429s and reads the tenant
        # header on both planes).
        p = "/" + path.lstrip("/")
        if p.endswith("_literals.py"):
            return False  # the definition site
        return any(
            seg in p for seg in ("/http/", "/grpc/", "/server/", "/fleet/")
        )

    def _shed_status_findings(self, ctxs) -> List[Finding]:
        """Raw 504/499/429 integer literals — and raw tenant-header
        strings — in protocol-plane files: the shed/quota vocabulary
        spelled outside protocol/_literals is drift waiting to happen —
        a client matching 504 while the server starts answering a
        respelled code (or a router admitting header X while the replica
        stamps header Y) is exactly the bug class TPU008 exists for."""
        findings: List[Finding] = []
        for ctx in ctxs:
            if not self._in_protocol_plane(ctx.path):
                continue
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Constant):
                    continue
                if (
                    type(node.value) is int
                    and node.value in _SHED_STATUS_NAMES
                ):
                    name = _SHED_STATUS_NAMES[node.value]
                    findings.append(
                        Finding(
                            self.id, ctx.path, node.lineno, node.col_offset,
                            f"shed status {node.value} spelled as a raw "
                            f"literal; import {name} from "
                            "protocol/_literals so client and server "
                            "cannot drift on the shed status",
                        )
                    )
                elif (
                    type(node.value) is int
                    and node.value in _VALIDATION_STATUS_NAMES
                ):
                    name = _VALIDATION_STATUS_NAMES[node.value]
                    findings.append(
                        Finding(
                            self.id, ctx.path, node.lineno, node.col_offset,
                            f"validation status {node.value} spelled as a "
                            f"raw literal; import {name} from "
                            "protocol/_literals so the planes cannot "
                            "drift on how malformed input is answered",
                        )
                    )
                elif (
                    isinstance(node.value, str)
                    and node.value in _INVALID_REASON_NAMES
                    and not ctx.is_docstring(node)
                ):
                    name = _INVALID_REASON_NAMES[node.value]
                    findings.append(
                        Finding(
                            self.id, ctx.path, node.lineno, node.col_offset,
                            f"invalid-request reason {node.value!r} spelled "
                            f"as a raw literal; import {name} from "
                            "protocol/_literals so the metric's reason "
                            "vocabulary stays canonical",
                        )
                    )
                elif (
                    isinstance(node.value, str)
                    and node.value in _HEADER_LITERAL_NAMES
                    and not ctx.is_docstring(node)
                ):
                    name = _HEADER_LITERAL_NAMES[node.value]
                    findings.append(
                        Finding(
                            self.id, ctx.path, node.lineno, node.col_offset,
                            f"protocol header {node.value!r} spelled as a "
                            f"raw literal; import {name} from "
                            "protocol/_literals so router and replica "
                            "cannot drift on tenant attribution",
                        )
                    )
        return findings

    # -- canonical vocabulary --------------------------------------------------

    def _canonical_keys(self, ctxs) -> Set[str]:
        for ctx in ctxs:
            if not ctx.path.endswith("_literals.py"):
                continue
            keys = {
                node.value.value
                for node in ctx.tree.body
                if isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
                and any(
                    isinstance(t, ast.Name) and t.id.startswith("KEY_")
                    for t in node.targets
                )
            }
            if keys:
                return keys
        try:
            from tritonclient_tpu.protocol import _literals
        except ImportError:  # pragma: no cover - package always importable
            return set()
        return {
            value
            for name, value in vars(_literals).items()
            if name.startswith("KEY_") and isinstance(value, str)
        }

    # -- scope classification --------------------------------------------------

    @staticmethod
    def _side_of(path: str) -> Optional[str]:
        p = "/" + path.lstrip("/")
        if p.endswith("_literals.py"):
            return None  # the definition site
        if "/server/" in p:
            name = p.rsplit("/", 1)[-1]
            if name == "_http.py":
                return "http-server"
            if name == "_grpc.py":
                return "grpc-server"
            return None
        if "/http/" in p:
            return "http-client"
        if "/grpc/" in p:
            return "grpc-client"
        return None

    # -- reference collection --------------------------------------------------

    def _key_references(self, ctx, canonical: Set[str]):
        """Yield (canonical key, line) for every non-import usage."""
        import_lines: Set[int] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                for line in range(
                    node.lineno, (node.end_lineno or node.lineno) + 1
                ):
                    import_lines.add(line)
        # KEY_* constant -> value, resolved through this file's imports
        # (the canonical spelling) or the literal module's convention.
        try:
            from tritonclient_tpu.protocol import _literals
            key_values = {
                name: value
                for name, value in vars(_literals).items()
                if name.startswith("KEY_") and isinstance(value, str)
            }
        except ImportError:  # pragma: no cover
            key_values = {}
        for node in ast.walk(ctx.tree):
            if getattr(node, "lineno", None) in import_lines:
                continue
            if isinstance(node, ast.Name) and node.id.startswith("KEY_"):
                value = key_values.get(node.id)
                if value in canonical:
                    yield value, node.lineno
            elif isinstance(node, ast.Attribute) and node.attr.startswith("KEY_"):
                value = key_values.get(node.attr)
                if value in canonical:
                    yield value, node.lineno
            elif (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and node.value in canonical
                and not ctx.is_docstring(node)
            ):
                yield node.value, node.lineno

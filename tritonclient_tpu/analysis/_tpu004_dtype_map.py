"""TPU004: numpy<->Triton datatype tables must be mutually inverse and total.

Static leg (runs on whatever files are linted): extract the datatype tables
``_NP_TO_TRITON`` (dict values + later ``table[...] = "DT"`` augmentations)
and ``_TRITON_DTYPE_SIZES`` (dict keys) from their definition sites and
cross-check them against the canonical ``DATATYPES`` registry (taken from a
linted ``_literals.py`` when present, else from the installed
``tritonclient_tpu.protocol._literals``): every mapped name must be
canonical, and the size table must cover exactly the fixed-size set.

Runtime leg (only when the linted file IS the real
``tritonclient_tpu/utils/__init__.py``): import the tables and verify
``np_to_triton_dtype(triton_to_np_dtype(dt)) == dt`` for every fixed-size
datatype and that ``triton_dtype_size`` matches the numpy itemsize —
mutual inversion the AST cannot see through the dict comprehension.
"""

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tritonclient_tpu.analysis._engine import FileContext, Finding, Rule

_NP_TABLE = "_NP_TO_TRITON"
_SIZE_TABLE = "_TRITON_DTYPE_SIZES"
_CANONICAL = "DATATYPES"


class DtypeMapRule(Rule):
    id = "TPU004"
    name = "dtype-map"
    description = (
        "numpy<->Triton datatype tables inconsistent with the canonical "
        "DATATYPES registry or not mutually inverse"
    )

    def check_project(self, ctxs: Sequence[FileContext]) -> List[Finding]:
        findings: List[Finding] = []
        canonical = self._find_canonical(ctxs)
        for ctx in ctxs:
            np_values = self._np_to_triton_values(ctx)
            size_keys = self._size_table_keys(ctx)
            if np_values is None and size_keys is None:
                continue
            fixed = canonical - {"BYTES"}
            if np_values is not None:
                values, line = np_values
                for extra in sorted(values - canonical):
                    findings.append(
                        Finding(
                            self.id, ctx.path, line, 0,
                            f"{_NP_TABLE} maps to {extra!r}, which is not in "
                            "the canonical DATATYPES registry",
                        )
                    )
                for missing in sorted(fixed - values):
                    findings.append(
                        Finding(
                            self.id, ctx.path, line, 0,
                            f"{_NP_TABLE} has no numpy mapping for canonical "
                            f"datatype {missing!r} (table not total)",
                        )
                    )
            if size_keys is not None:
                keys, line = size_keys
                for extra in sorted(keys - fixed):
                    findings.append(
                        Finding(
                            self.id, ctx.path, line, 0,
                            f"{_SIZE_TABLE} sizes unknown datatype {extra!r}",
                        )
                    )
                for missing in sorted(fixed - keys):
                    findings.append(
                        Finding(
                            self.id, ctx.path, line, 0,
                            f"{_SIZE_TABLE} missing fixed-size datatype "
                            f"{missing!r} (table not total)",
                        )
                    )
            if ctx.path.endswith("tritonclient_tpu/utils/__init__.py"):
                findings.extend(self._runtime_check(ctx, canonical))
        return findings

    # -- static extraction ----------------------------------------------------

    def _find_canonical(self, ctxs) -> Set[str]:
        for ctx in ctxs:
            if not ctx.path.endswith("_literals.py"):
                continue
            found = self._module_assign(ctx, _CANONICAL)
            if found is not None:
                values = self._string_elements(found[0])
                if values:
                    return values
        from tritonclient_tpu.protocol import _literals

        return set(_literals.DATATYPES)

    @staticmethod
    def _module_assign(ctx, name) -> Optional[Tuple[ast.AST, int]]:
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    if isinstance(target, ast.Name) and target.id == name:
                        return node.value, node.lineno
        return None

    @staticmethod
    def _string_elements(node: ast.AST) -> Set[str]:
        """Constant strings in a set/list/tuple/frozenset(...) literal."""
        if isinstance(node, ast.Call) and node.args:
            node = node.args[0]
        out: Set[str] = set()
        if isinstance(node, (ast.Set, ast.List, ast.Tuple)):
            for el in node.elts:
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    out.add(el.value)
                elif isinstance(el, ast.Name):
                    # DT_* constant references: resolve textually (DT_FP32
                    # -> FP32) — the _literals idiom.
                    if el.id.startswith("DT_"):
                        out.add(el.id[3:])
        return out

    def _np_to_triton_values(self, ctx) -> Optional[Tuple[Set[str], int]]:
        found = self._module_assign(ctx, _NP_TABLE)
        if found is None or not isinstance(found[0], ast.Dict):
            return None
        node, line = found
        values = {
            v.value
            for v in node.values
            if isinstance(v, ast.Constant) and isinstance(v.value, str)
        }
        # Conditional augmentations: `_NP_TO_TRITON[dtype] = "BF16"`.
        for sub in ast.walk(ctx.tree):
            if (
                isinstance(sub, ast.Assign)
                and len(sub.targets) == 1
                and isinstance(sub.targets[0], ast.Subscript)
                and isinstance(sub.targets[0].value, ast.Name)
                and sub.targets[0].value.id == _NP_TABLE
                and isinstance(sub.value, ast.Constant)
                and isinstance(sub.value.value, str)
            ):
                values.add(sub.value.value)
        return values, line

    def _size_table_keys(self, ctx) -> Optional[Tuple[Set[str], int]]:
        found = self._module_assign(ctx, _SIZE_TABLE)
        if found is None or not isinstance(found[0], ast.Dict):
            return None
        node, line = found
        keys = {
            k.value
            for k in node.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)
        }
        return keys, line

    # -- runtime inversion check ----------------------------------------------

    def _runtime_check(self, ctx, canonical: Set[str]) -> List[Finding]:
        findings: List[Finding] = []
        try:
            import numpy as np

            from tritonclient_tpu import utils as u
        except Exception as e:  # pragma: no cover - import environment issue
            return [
                Finding(
                    self.id, ctx.path, 1, 0,
                    f"unable to import utils for runtime dtype check: {e}",
                )
            ]
        for dt in sorted(canonical - {"BYTES"}):
            np_dtype = u.triton_to_np_dtype(dt)
            if np_dtype is None:
                findings.append(
                    Finding(
                        self.id, ctx.path, 1, 0,
                        f"triton_to_np_dtype({dt!r}) is None (not total)",
                    )
                )
                continue
            back = u.np_to_triton_dtype(np_dtype)
            if back != dt:
                findings.append(
                    Finding(
                        self.id, ctx.path, 1, 0,
                        f"dtype maps not mutually inverse: {dt!r} -> "
                        f"{np_dtype!r} -> {back!r}",
                    )
                )
            size = u.triton_dtype_size(dt)
            itemsize = np.dtype(np_dtype).itemsize
            if size != itemsize:
                findings.append(
                    Finding(
                        self.id, ctx.path, 1, 0,
                        f"triton_dtype_size({dt!r}) == {size} but numpy "
                        f"itemsize is {itemsize}",
                    )
                )
        if u.triton_to_np_dtype("BYTES") is None:
            findings.append(
                Finding(
                    self.id, ctx.path, 1, 0,
                    "triton_to_np_dtype('BYTES') is None (BYTES must map to "
                    "np.object_)",
                )
            )
        return findings

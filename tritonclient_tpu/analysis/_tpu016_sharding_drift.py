"""TPU016: sharding drift between a producer and a consuming boundary.

An array committed to the mesh under one ``NamedSharding`` that flows
into a ``shard_map``/``jax.jit`` boundary whose in-spec differs forces
XLA to insert an implicit reshard — a device-to-device all-to-all (or,
degenerately, a host round-trip) on every call, silently, with no
Python site to profile. The drift is statically decidable whenever both
the producer spec (``jax.device_put(x, named_sharding(mesh, ...))``)
and the consumer spec (``in_specs=``/``in_shardings=``) are visible,
and the rule reports the exact producer→consumer call path the same way
TPU013 reports taint flows.

Specs compare by canonical axis text with trailing replicated axes
dropped, so ``P(None, 'tp')`` vs ``named_sharding(mesh, None, 'tp')``
match and ``P(None)`` vs ``P()`` (both fully replicated) match; only a
provable axis disagreement fires.

Example::

    pool_spec = named_sharding(mesh, None, "tp")     # heads on tp
    pool = jax.device_put(pool, pool_spec)
    f = shard_map(body, mesh=mesh,
                  in_specs=(P("tp", None),),          # rows on tp!
                  out_specs=P(None, None))
    f(pool)        # implicit all-to-all reshard on every call

Fix: make the producer and consumer agree — either place the array
under the consumer's spec at allocation time, or change the boundary's
``in_specs`` to match the resident layout (and reshard once, outside
the hot path, if a layout change is genuinely needed). Suppress a
deliberate reshard at the call line with
``# tpulint: disable=TPU016`` and a comment saying why.

The interprocedural half: a parameter consumed under spec S inside a
callee propagates backwards (like TPU013's sinking params), so a placed
array forwarded through helpers into a mismatched boundary is still
caught, with the full call chain in the message.
"""

from typing import Dict, List, Sequence, Tuple, Union

from tritonclient_tpu.analysis import _callgraph
from tritonclient_tpu.analysis._engine import FileContext, Finding, Rule

Slot = Union[int, str]


def _fmt(spec: str) -> str:
    return f"P({spec})" if spec else "replicated"


class ShardingDriftRule(Rule):
    id = "TPU016"
    name = "sharding-drift"
    description = (
        "array placed under one NamedSharding flows into a "
        "shard_map/jit boundary whose in-spec differs, forcing an "
        "implicit reshard on every call"
    )

    def check_project(self, ctxs: Sequence[FileContext]) -> List[Finding]:
        if not ctxs:
            return []
        graph = _callgraph.get_callgraph(ctxs)
        shapes = {
            key: fn.shapes for key, fn in graph.functions.items()
            if fn.shapes is not None
        }
        consuming = _consuming_params(shapes)
        linted = {ctx.path for ctx in ctxs if not _is_test_path(ctx.path)}
        findings: List[Finding] = []
        seen = set()

        def emit(fn, line, col, message):
            dedup = (fn.path, line, message)
            if dedup in seen:
                return
            seen.add(dedup)
            findings.append(Finding(self.id, fn.path, line, col, message))

        for key in sorted(shapes):
            fn = graph.functions[key]
            if fn.path not in linted:
                continue
            rec = shapes[key]
            for src, have, want, detail, line, col in rec.spec_flows:
                emit(fn, line, col,
                     f"`{src}` is placed under {_fmt(have)} but consumed "
                     f"by {detail} expecting {_fmt(want)} in `{key}`: "
                     f"every call pays an implicit reshard — align the "
                     f"placement with the boundary spec")
            for callee, slot, have, line, col, src in rec.placed_calls:
                hit = _lookup(consuming, shapes, callee, slot)
                if hit is None:
                    continue
                want, detail, chain = hit
                if want == have:
                    continue
                path = " -> ".join([key] + chain)
                emit(fn, line, col,
                     f"`{src}` is placed under {_fmt(have)} but flows "
                     f"into `{callee}` and is consumed by {detail} "
                     f"expecting {_fmt(want)} via {path}: every call "
                     f"pays an implicit reshard — align the placement "
                     f"with the boundary spec")
        return findings


def _is_test_path(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    return "tests" in parts or parts[-1].startswith("test_")


def _lookup(consuming, shapes, callee: str, slot: Slot):
    rec = shapes.get(callee)
    if rec is None:
        return None
    param = rec.slot_param(slot)
    if param is None:
        return None
    return consuming.get((callee, param))


def _consuming_params(
    shapes,
) -> Dict[Tuple[str, str], Tuple[str, str, List[str]]]:
    """Fixpoint: (function key, param) -> (consumer spec, boundary
    detail, call chain down to the consuming function)."""
    consuming: Dict[Tuple[str, str], Tuple[str, str, List[str]]] = {}
    for key, rec in shapes.items():
        for param, sinks in rec.spec_sinks.items():
            spec, detail = sinks[0][0], sinks[0][1]
            consuming[(key, param)] = (spec, detail, [key])
    changed = True
    while changed:
        changed = False
        for key, rec in shapes.items():
            for param, calls in rec.spec_calls.items():
                if (key, param) in consuming:
                    continue
                for callee, slot, _line in calls:
                    hit = _lookup(consuming, shapes, callee, slot)
                    if hit is None:
                        continue
                    spec, detail, chain = hit
                    consuming[(key, param)] = (spec, detail, [key] + chain)
                    changed = True
                    break
    return consuming

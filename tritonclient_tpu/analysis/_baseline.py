"""Baseline support: fail only on findings absent from a recorded set.

The baseline is how the lint scope grows without a flag day: widening
``discover_files`` to ``scripts/`` and ``tests/`` surfaced pre-existing
findings that are real but not this change's to fix — they get recorded
once (``--write-baseline``) and CI then fails only on *new* findings
(``--baseline``).

Matching is by fingerprint (``rule::path::message``), deliberately
line-number free: editing an unrelated part of a file must not resurrect
its baselined findings. The baseline stores a count per fingerprint, so
introducing a *second* instance of an already-baselined violation in the
same file with the same message still fails. Fixing a baselined finding
leaves a stale entry; ``--write-baseline`` regenerates the file (the
round-trip tests assert add/remove behavior both ways).
"""

import json
from typing import Dict, List, Sequence, Tuple

from tritonclient_tpu.analysis._engine import Finding

_FORMAT = "tpulint-baseline"
_VERSION = 1


def write_baseline(path: str, findings: Sequence[Finding]):
    counts: Dict[str, int] = {}
    for f in findings:
        fp = f.fingerprint()
        counts[fp] = counts.get(fp, 0) + 1
    doc = {
        "format": _FORMAT,
        "version": _VERSION,
        "findings": {fp: counts[fp] for fp in sorted(counts)},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")


def load_baseline(path: str) -> Dict[str, int]:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("format") != _FORMAT:
        raise ValueError(f"{path} is not a tpulint baseline file")
    counts = doc.get("findings", {})
    if not all(
        isinstance(k, str) and isinstance(v, int) for k, v in counts.items()
    ):
        raise ValueError(f"{path}: malformed baseline findings map")
    return dict(counts)


def apply_baseline(
    findings: Sequence[Finding], baseline: Dict[str, int]
) -> Tuple[List[Finding], int]:
    """Split findings into (new, suppressed_count).

    The first N findings matching a fingerprint with baseline count N are
    suppressed; any beyond that are new.
    """
    budget = dict(baseline)
    fresh: List[Finding] = []
    suppressed = 0
    for f in findings:
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            suppressed += 1
        else:
            fresh.append(f)
    return fresh, suppressed

"""Autofixes for the mechanical rules (``--fix``).

Only rewrites with exactly one correct spelling are automated:

* **TPU001** — ``time.sleep(x)`` as a statement inside an ``async def``
  becomes ``await asyncio.sleep(x)`` (adding ``import asyncio`` when
  missing). The sync-code ``time.sleep`` leg is NOT auto-fixed: whether a
  sync sleep should become async, move to an executor, or carry a
  suppression is a design decision.
* **TPU003** — a string literal exactly equal to a canonical ``KEY_*`` /
  ``EP_*`` value is replaced by the constant name, with a
  ``from tritonclient_tpu.protocol._literals import ...`` line added for
  names the file does not already import. Near-misses and f-string
  templates are diagnosed only — their correct replacement is not
  mechanical.

Fixes are applied bottom-up so source positions stay valid, and the
caller re-lints afterwards; running ``--fix`` twice must change nothing
(idempotency is asserted in tests/test_tpulint.py).
"""

import ast
from typing import Dict, List, Optional, Sequence, Tuple

from tritonclient_tpu.analysis._engine import FileContext, Finding

#: (start_line, start_col, end_line, end_col, replacement) — 1-based lines.
_Edit = Tuple[int, int, int, int, str]

_LITERALS_MODULE = "tritonclient_tpu.protocol._literals"


def _literal_constants() -> Dict[str, str]:
    """value -> constant name for every KEY_* / EP_* string constant."""
    from tritonclient_tpu.protocol import _literals

    out: Dict[str, str] = {}
    for name, value in vars(_literals).items():
        if isinstance(value, str) and (
            name.startswith("KEY_") or name.startswith("EP_")
        ):
            out.setdefault(value, name)
    return out


def apply_fixes(findings: Sequence[Finding]) -> Dict[str, int]:
    """Rewrite files in place; returns {path: edits applied}."""
    by_path: Dict[str, List[Finding]] = {}
    for f in findings:
        if f.rule in ("TPU001", "TPU003"):
            by_path.setdefault(f.path, []).append(f)
    applied: Dict[str, int] = {}
    for path, file_findings in sorted(by_path.items()):
        count = _fix_file(path, file_findings)
        if count:
            applied[path] = count
    return applied


def _fix_file(path: str, findings: Sequence[Finding]) -> int:
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    except OSError:
        return 0
    try:
        ctx = FileContext(path, source)
    except SyntaxError:
        return 0
    edits: List[_Edit] = []
    needed_imports: List[str] = []
    need_asyncio = False
    for finding in findings:
        if finding.rule == "TPU001":
            edit = _fix_sleep(ctx, finding)
            if edit is not None:
                edits.append(edit)
                need_asyncio = True
        elif finding.rule == "TPU003":
            fixed = _fix_literal(ctx, finding)
            if fixed is not None:
                edit, const_name = fixed
                edits.append(edit)
                needed_imports.append(const_name)
    if not edits:
        return 0
    lines = source.splitlines()
    for line1, col1, line2, col2, text in sorted(edits, reverse=True):
        i, j = line1 - 1, line2 - 1
        lines[i : j + 1] = [lines[i][:col1] + text + lines[j][col2:]]
    _insert_imports(ctx, lines, needed_imports, need_asyncio)
    new_source = "\n".join(lines)
    if source.endswith("\n") and not new_source.endswith("\n"):
        new_source += "\n"
    with open(path, "w", encoding="utf-8") as f:
        f.write(new_source)
    return len(edits)


# -- TPU001: time.sleep -> await asyncio.sleep ------------------------------


def _fix_sleep(ctx: FileContext, finding: Finding) -> Optional[_Edit]:
    call = _call_at(ctx, finding.line, finding.col)
    if call is None or ctx.canonical_call_name(call.func) != "time.sleep":
        return None
    # Statement position only, and only on an async path: `await` is
    # invalid elsewhere, and the sync-leg fix is a design decision.
    parent = ctx.parents.get(call)
    if not isinstance(parent, ast.Expr) or parent.value is not call:
        return None
    enclosing = ctx.enclosing_function(call)
    in_async = False
    while enclosing is not None:
        if isinstance(enclosing, ast.AsyncFunctionDef):
            in_async = True
            break
        if isinstance(enclosing, ast.FunctionDef):
            break  # sync frame between the call and any async def
        enclosing = ctx.enclosing_function(enclosing)
    if not in_async:
        return None
    func = call.func
    return (
        call.lineno,
        call.col_offset,
        func.end_lineno,
        func.end_col_offset,
        "await asyncio.sleep",
    )


def _call_at(ctx: FileContext, line: int, col: int) -> Optional[ast.Call]:
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Call)
            and node.lineno == line
            and node.col_offset == col
        ):
            return node
    return None


# -- TPU003: canonical-literal rewrite --------------------------------------


def _fix_literal(
    ctx: FileContext, finding: Finding
) -> Optional[Tuple[_Edit, str]]:
    constants = _literal_constants()
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and node.lineno == finding.line
            and node.col_offset == finding.col
        ):
            name = constants.get(node.value)
            if name is None:
                return None  # template/near-miss: not mechanical
            edit = (
                node.lineno,
                node.col_offset,
                node.end_lineno,
                node.end_col_offset,
                name,
            )
            return edit, name
    return None


# -- import maintenance -----------------------------------------------------


def _insert_imports(
    ctx: FileContext, lines: List[str], const_names: List[str], need_asyncio: bool
):
    already = set(ctx.aliases)
    missing = sorted(
        {n for n in const_names if n not in already}
    )
    add_asyncio = need_asyncio and "asyncio" not in already
    if not missing and not add_asyncio:
        return
    insert_at = _import_insert_index(ctx)
    new_lines = []
    if add_asyncio:
        new_lines.append("import asyncio")
    if missing:
        new_lines.append(
            f"from {_LITERALS_MODULE} import {', '.join(missing)}"
        )
    lines[insert_at:insert_at] = new_lines


def _import_insert_index(ctx: FileContext) -> int:
    """0-based line index after the last top-level import (or the module
    docstring, or 0)."""
    last = 0
    for node in ctx.tree.body:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            last = node.end_lineno or node.lineno
        elif (
            isinstance(node, ast.Expr)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
            and last == 0
        ):
            last = node.end_lineno or node.lineno
        else:
            break
    return last

"""Shared SARIF 2.1.0 rendering for the static and runtime analysis tiers.

tpulint (static, ``analysis/_engine.py``) and tpusan (runtime,
``tritonclient_tpu/sanitize``) report through the same ``Finding`` shape
and the same ``rule::path::message`` fingerprint, so their SARIF outputs
merge in GitHub code scanning and their findings round-trip through one
``--baseline`` file. This module owns the SARIF document shape exactly
once; each tool supplies its driver name and rule metadata.
"""

import json
from typing import Dict, List, Optional, Sequence

SARIF_SCHEMA = (
    "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
    "Schemata/sarif-schema-2.1.0.json"
)

#: partialFingerprints key shared by both tools: code scanning treats a
#: static finding and its runtime witness of the same violation as one
#: result stream instead of duplicating annotations.
FINGERPRINT_KEY = "tpulint/v1"

_INFO_URI = "https://github.com/triton-inference-server/client"


def render_sarif(
    findings: Sequence,
    rules_meta: List[Dict],
    tool_name: str = "tpulint",
    level_for: Optional[Dict[str, str]] = None,
) -> str:
    """SARIF 2.1.0 — the format GitHub code scanning ingests to annotate
    PRs. One run, one driver (``tool_name``), one result per finding.

    ``findings`` are ``Finding``-shaped objects (rule/path/line/col/
    message/fingerprint()); ``rules_meta`` the driver's declared rules;
    ``level_for`` optional per-rule severity overrides (default
    ``warning``, ``PARSE`` always ``error``).
    """
    rules_meta = list(rules_meta)
    known = {r["id"] for r in rules_meta}
    # PARSE (and any future synthetic rule ids) still need a rule entry:
    # SARIF results must reference a declared rule.
    for extra in sorted({f.rule for f in findings} - known):
        rules_meta.append(
            {
                "id": extra,
                "name": extra.lower(),
                "shortDescription": {"text": "file could not be analyzed"},
            }
        )
    levels = dict(level_for or {})
    results = [
        {
            "ruleId": f.rule,
            "level": (
                "error" if f.rule == "PARSE" else levels.get(f.rule, "warning")
            ),
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path,
                            "uriBaseId": "SRCROOT",
                        },
                        "region": {
                            "startLine": max(f.line, 1),
                            "startColumn": f.col + 1,
                        },
                    }
                }
            ],
            "partialFingerprints": {FINGERPRINT_KEY: f.fingerprint()},
        }
        for f in findings
    ]
    doc = {
        "$schema": SARIF_SCHEMA,
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": tool_name,
                        "informationUri": _INFO_URI,
                        "rules": rules_meta,
                    }
                },
                "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
                "results": results,
            }
        ],
    }
    return json.dumps(doc, indent=2)


def load_sarif_findings(path: str) -> List[dict]:
    """Flatten a SARIF file back to finding dicts (rule/path/line/message/
    fingerprint) — the inverse used by ``scripts/tpusan_report.py`` to diff
    a runtime run against the static picture."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    out: List[dict] = []
    for run in doc.get("runs", []):
        for res in run.get("results", []):
            loc = (res.get("locations") or [{}])[0].get(
                "physicalLocation", {}
            )
            out.append(
                {
                    "rule": res.get("ruleId", ""),
                    "path": loc.get("artifactLocation", {}).get("uri", ""),
                    "line": loc.get("region", {}).get("startLine", 1),
                    "message": res.get("message", {}).get("text", ""),
                    "fingerprint": res.get("partialFingerprints", {}).get(
                        FINGERPRINT_KEY, ""
                    ),
                }
            )
    return out

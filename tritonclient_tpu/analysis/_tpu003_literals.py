"""TPU003: protocol-literal conformance.

``tritonclient_tpu/protocol/_literals.py`` is the single source of truth
for KServe v2 endpoint paths and drift-prone JSON/parameter keys. Under the
protocol front-ends (any path containing ``/http/``, ``/grpc/``, or
``/server/``), this rule flags:

* any ``v2``-prefixed path string (including f-string templates and
  ``^v2``-anchored regex literals) spelled out instead of imported — the
  historical HTTP/gRPC drift vector;
* any literal equal to an enforced canonical key (``shared_memory_region``
  and friends) instead of the ``KEY_*`` constant;
* near-misses: strings that *look like* a datatype (``FP8``, ``INT33``) or
  sit one edit away from a canonical key — wire drift that would otherwise
  fail only at integration time.

Docstrings are exempt (prose, not wire traffic); ``_literals.py`` itself is
exempt (it is the definition site).
"""

import ast
import re
from typing import List, Optional

from tritonclient_tpu.analysis._engine import FileContext, Finding, Rule
from tritonclient_tpu.protocol import _literals as lit

_SCOPE_PARTS = ("/http/", "/grpc/", "/server/")
_EXEMPT_SUFFIXES = ("/_literals.py",)

_ENFORCED_KEYS = {
    lit.KEY_SHM_REGION,
    lit.KEY_SHM_OFFSET,
    lit.KEY_SHM_BYTE_SIZE,
    lit.KEY_BINARY_DATA,
    lit.KEY_BINARY_DATA_SIZE,
    lit.KEY_BINARY_DATA_OUTPUT,
    lit.KEY_CLASSIFICATION,
    lit.KEY_SEQUENCE_ID,
    lit.KEY_SEQUENCE_START,
    lit.KEY_SEQUENCE_END,
    lit.KEY_EMPTY_FINAL_RESPONSE,
    lit.KEY_FINAL_RESPONSE,
    lit.KEY_UNLOAD_DEPENDENTS,
}

_DATATYPE_SHAPE_RE = re.compile(r"^(U?INT|FP|BF)[0-9]+$")


def _edit_distance_at_most_one(a: str, b: str) -> bool:
    if a == b:
        return False
    la, lb = len(a), len(b)
    if abs(la - lb) > 1:
        return False
    if la == lb:
        return sum(x != y for x, y in zip(a, b)) == 1
    if la > lb:
        a, b, la, lb = b, a, lb, la
    # one insertion turns a into b
    i = j = edits = 0
    while i < la and j < lb:
        if a[i] == b[j]:
            i += 1
            j += 1
        else:
            edits += 1
            if edits > 1:
                return False
            j += 1
    return True


class ProtocolLiteralRule(Rule):
    id = "TPU003"
    name = "protocol-literal"
    description = (
        "wire literal under http/, grpc/, or server/ duplicating or "
        "near-missing the canonical set in protocol/_literals.py"
    )

    def check_file(self, ctx: FileContext) -> List[Finding]:
        path = "/" + ctx.path.lstrip("/")
        if not any(part in path for part in _SCOPE_PARTS):
            return []
        if path.endswith(_EXEMPT_SUFFIXES):
            return []
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            value: Optional[str] = None
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                if ctx.is_docstring(node):
                    continue
                if self._inside_fstring(ctx, node):
                    continue  # judged as part of the whole JoinedStr
                value = node.value
            elif isinstance(node, ast.JoinedStr):
                value = self._template(node)
            if value is None:
                continue
            msg = self._judge(value)
            if msg is not None:
                findings.append(
                    Finding(self.id, ctx.path, node.lineno, node.col_offset, msg)
                )
        return findings

    @staticmethod
    def _inside_fstring(ctx, node) -> bool:
        cur = ctx.parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.JoinedStr):
                return True
            if isinstance(cur, ast.stmt):
                return False
            cur = ctx.parents.get(cur)
        return False

    @staticmethod
    def _template(node: ast.JoinedStr) -> str:
        parts = []
        for piece in node.values:
            if isinstance(piece, ast.Constant) and isinstance(piece.value, str):
                parts.append(piece.value)
            else:
                parts.append("{}")
        return "".join(parts)

    def _judge(self, value: str) -> Optional[str]:
        if value.startswith("v2/") or value in ("v2", "^v2") or value.startswith(
            ("v2?", "^v2/")
        ):
            return (
                f"endpoint literal {value!r} spelled outside "
                "protocol/_literals.py; import or build it from "
                "tritonclient_tpu.protocol._literals"
            )
        if value in _ENFORCED_KEYS:
            return (
                f"wire key {value!r} duplicates a canonical literal; import "
                "the KEY_* constant from tritonclient_tpu.protocol._literals"
            )
        if _DATATYPE_SHAPE_RE.match(value) and value not in lit.DATATYPES:
            return (
                f"{value!r} looks like a datatype string but is not in "
                "protocol/_literals.DATATYPES — wire drift?"
            )
        if len(value) >= 10:
            for key in _ENFORCED_KEYS:
                if len(key) >= 10 and _edit_distance_at_most_one(value, key):
                    return (
                        f"{value!r} is one edit away from canonical wire key "
                        f"{key!r} — wire drift?"
                    )
        return None

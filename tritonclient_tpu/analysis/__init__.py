"""tpulint — project-specific static analysis for the TPU serving stack.

Five AST-based check families tuned to the bug classes this codebase's
surfaces actually grow (two protocol front-ends, sync+aio clients, a
threaded server core, a DLPack/shm registry):

=======  =================  ====================================================
rule     name               catches
=======  =================  ====================================================
TPU001   async-blocking     ``time.sleep`` / sync socket / file I/O / sync
                            gRPC inside ``async def`` bodies (and
                            ``time.sleep`` anywhere — one refactor from
                            stalling an in-process event loop)
TPU002   lock-discipline    instance attributes guarded by a class's lock in
                            one method and touched lock-free in another
TPU003   protocol-literal   KServe v2 endpoint paths / wire keys spelled out
                            under http/, grpc/, server/ instead of imported
                            from protocol/_literals.py; datatype near-misses
TPU004   dtype-map          numpy<->Triton datatype tables not mutually
                            inverse or not total vs protocol/_literals
TPU005   resource-leak      shm/file/socket/trace handles acquired without
                            ``with``/``finally`` release on all paths
=======  =================  ====================================================

Suppress a deliberate violation with ``# tpulint: disable=TPU001`` (comma
list allowed) on the offending line, or on a ``def``/``class`` line to
cover the whole body; ``# tpulint: disable-file=TPU003`` anywhere in a file
covers the file. Run ``python -m tritonclient_tpu.analysis <paths>``
(exit 1 on findings; ``--format json`` for machine-readable output).
"""

from tritonclient_tpu.analysis._engine import (  # noqa: F401
    FileContext,
    Finding,
    Rule,
    default_rules,
    render_json,
    render_text,
    run_analysis,
)

__all__ = [
    "FileContext",
    "Finding",
    "Rule",
    "default_rules",
    "main",
    "render_json",
    "render_text",
    "run_analysis",
]


def main(argv=None) -> int:
    """CLI entry point (``python -m tritonclient_tpu.analysis``)."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="tpulint",
        description="Project-specific static analysis for tritonclient_tpu.",
    )
    parser.add_argument(
        "paths", nargs="*", default=["tritonclient_tpu"],
        help="files or directories to lint (default: tritonclient_tpu)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="report format (default: text)",
    )
    parser.add_argument(
        "--select", default="",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule table and exit",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in default_rules():
            print(f"{rule.id}  {rule.name}: {rule.description}")
        return 0

    select = (
        {r.strip().upper() for r in args.select.split(",") if r.strip()}
        or None
    )
    findings, files_checked = run_analysis(args.paths, select=select)
    render = render_json if args.format == "json" else render_text
    print(render(findings, files_checked))
    return 1 if findings else 0

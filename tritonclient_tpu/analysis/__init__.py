"""tpulint — project-specific static analysis for the TPU serving stack.

Sixteen check families tuned to the bug classes this codebase's
surfaces actually grow (two protocol front-ends, sync+aio clients, a
threaded server core, a DLPack/shm registry, a JAX compute plane).
TPU001–TPU005 are AST-local; TPU006–TPU008 and TPU014 are flow- and
project-sensitive; TPU009–TPU011, TPU013, and TPU015–TPU017 are
interprocedural over the whole-program call graph (``_callgraph.py``
— the latter three over its tpushape abstract-value layer,
``_shapes.py``):

=======  =================  ====================================================
rule     name               catches
=======  =================  ====================================================
TPU001   async-blocking     ``time.sleep`` / sync socket / file I/O / sync
                            gRPC inside ``async def`` bodies (including
                            ``async with``/``async for`` and blocking calls
                            bound through ``functools.partial``), and
                            ``time.sleep`` anywhere
TPU002   lock-discipline    instance attributes guarded by a class's lock in
                            one method and touched lock-free in another
TPU003   protocol-literal   KServe v2 endpoint paths / wire keys spelled out
                            under http/, grpc/, server/ instead of imported
                            from protocol/_literals.py; datatype near-misses
TPU004   dtype-map          numpy<->Triton datatype tables not mutually
                            inverse or not total vs protocol/_literals
TPU005   resource-leak      shm/file/socket/trace handles acquired without
                            ``with``/``finally`` release on all paths
TPU006   shm-lifecycle      flow-sensitive state machine over shm handles
                            (create → register → set/read → unregister →
                            destroy): use-after-unregister/destroy,
                            double-register, leak paths incl. exception edges
TPU007   lock-order         cycles in the project-wide lock-acquisition
                            graph (with-nesting + calls under a lock) —
                            potential deadlocks, both sites cited
TPU008   protocol-drift     wire keys built by a plane's client but not
                            parsed by its server front-end (or vice versa);
                            incomplete shared-memory key trios
TPU009   guarded-by         Eraser-style static lockset race detection:
                            thread entry points are discovered
                            (``threading.Thread``, executor submit/map,
                            ``run_in_executor``), each attribute escaping
                            to ≥2 threads gets its guard inferred by
                            majority vote over lock-held writes, and
                            accesses outside that guard are reported with
                            the inferred guard + witness path
TPU010   jax-hot-path       device→host syncs (``np.asarray``/``float``/
                            ``.item()``/bool-branching on device arrays,
                            ``block_until_ready``) and retrace triggers
                            (jit built per call, static-arg drift) on any
                            function reachable from a ``# tpulint:
                            hot-path`` annotated root
TPU011   condvar-           condition-variable discipline over declared
         discipline         ``named_condition`` locks: untimed wait outside
                            a predicate re-check loop, timed-wait result
                            ignored, notify without the cv's lock or with
                            no predicate write in its call subtree, and
                            wait predicates mutated outside the cv (the
                            lost-wakeup shape ``tpumc`` witnesses
                            dynamically)
TPU013   untrusted-sink     interprocedural taint: request-derived values
                            (HTTP body/header parses, gRPC request fields,
                            fleet proxy pass-throughs) reaching allocation
                            sizes, ``reshape``, buffer slice bounds,
                            ``range()`` loop bounds, or shm/page-reservation
                            math without passing a ``protocol/_validate``
                            sanitizer — reported with the full source→sink
                            call path (``tpufuzz`` is the dynamic witness)
TPU014   validation-drift   a request field validated on one protocol plane
                            (HTTP/gRPC server front-end) but referenced
                            unvalidated on the other, or validated only in
                            a client library while the server trusts it
TPU015   donation-          a buffer passed through ``donate_argnums``/
         discipline         ``donate_argnames`` read again on any path
                            (garbage on real TPUs — the CPU backend
                            ignores donation, so tests stay green), plus
                            the inverse advisory: a hot-loop operand
                            rebuilt every step but never donated
TPU016   sharding-drift     an array placed under one ``NamedSharding``
                            flowing into a shard_map/jit boundary whose
                            in-spec differs — an implicit reshard
                            (all-to-all or host round-trip) per call,
                            reported with the producer→consumer path
TPU017   bucket-discipline  a per-request magnitude (``len``/``.shape``)
                            shaping a traced operand of a jitted callable
                            without passing a pow2/chunk bucketing
                            function — one XLA compile per distinct size
                            (the tpusan compile-cache watcher is the
                            runtime witness)
=======  =================  ====================================================

Suppress a deliberate violation with ``# tpulint: disable=TPU001`` (comma
list allowed) on the offending line, or on a ``def``/``class`` line to
cover the whole body; ``# tpulint: disable-file=TPU003`` anywhere in a file
covers the file. Project-wide rules (TPU004/007–011/013/014) honor the same
syntax at the line their finding points to. Mark a hot root with
``# tpulint: hot-path`` on (or immediately above) its ``def`` line —
TPU010 treats everything call-graph-reachable from it as hot.
``--explain RULE`` prints a rule's worked example and fix guidance.

Run ``python -m tritonclient_tpu.analysis <paths>`` (exit 1 on findings).
``--format json|sarif`` selects machine-readable output (SARIF 2.1.0 for
GitHub code scanning), ``--baseline FILE`` fails only on findings absent
from a recorded baseline, ``--write-baseline FILE`` records one, and
``--fix`` applies the mechanical rewrites (TPU003 literal → constant,
TPU001 ``time.sleep`` → ``await asyncio.sleep`` on async paths) and
re-lints. ``--changed`` lints only git-touched files against the cached
whole-program call graph (``--callgraph-cache``) — the pre-commit path.
"""

from tritonclient_tpu.analysis._engine import (  # noqa: F401
    FileContext,
    Finding,
    Rule,
    default_rules,
    render_json,
    render_sarif,
    render_text,
    run_analysis,
)

__all__ = [
    "FileContext",
    "Finding",
    "Rule",
    "default_rules",
    "explain_rule",
    "main",
    "render_json",
    "render_sarif",
    "render_text",
    "run_analysis",
]


def explain_rule(rule_id):
    """The worked example + fix guidance for a rule: the docstring of
    the module defining it, headed by the one-line description. Returns
    None for an unknown rule id/name (``--explain`` exits 2 on that)."""
    import importlib

    want = rule_id.strip()
    for rule in default_rules():
        if rule.id != want.upper() and rule.name != want.lower():
            continue
        module = importlib.import_module(type(rule).__module__)
        doc = (module.__doc__ or "").strip()
        header = f"{rule.id}  {rule.name}: {rule.description}"
        return f"{header}\n\n{doc}" if doc else header
    return None


def _git_changed_files(paths):
    """Python files under ``paths`` that git reports as modified vs HEAD
    (staged or not) or untracked. Empty list when nothing changed or git
    is unavailable (the caller then lints nothing, succeeding fast)."""
    import os
    import subprocess

    try:
        out = subprocess.run(
            ["git", "diff", "--name-only", "HEAD", "--"],
            capture_output=True, text=True, check=True,
        ).stdout
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            capture_output=True, text=True, check=True,
        ).stdout
    except (OSError, subprocess.CalledProcessError):
        return []
    roots = [os.path.normpath(p) for p in paths]
    changed = []
    for line in (out + untracked).splitlines():
        f = line.strip()
        if not f.endswith(".py") or not os.path.exists(f):
            continue
        norm = os.path.normpath(f)
        if any(norm == r or norm.startswith(r + os.sep) for r in roots):
            changed.append(f)
    return sorted(set(changed))


def main(argv=None) -> int:
    """CLI entry point (``python -m tritonclient_tpu.analysis``)."""
    import argparse
    import sys

    parser = argparse.ArgumentParser(
        prog="tpulint",
        description="Project-specific static analysis for tritonclient_tpu.",
    )
    parser.add_argument(
        "paths", nargs="*", default=["tritonclient_tpu"],
        help="files or directories to lint (default: tritonclient_tpu)",
    )
    parser.add_argument(
        "--format", choices=("text", "json", "sarif"), default="text",
        help="report format (default: text)",
    )
    parser.add_argument(
        "--select", default="",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule table and exit",
    )
    parser.add_argument(
        "--explain", metavar="RULE", default=None,
        help="print RULE's worked example and fix guidance (from its "
        "rule-module documentation) and exit",
    )
    parser.add_argument(
        "--baseline", metavar="FILE", default=None,
        help="fail only on findings absent from this baseline file",
    )
    parser.add_argument(
        "--write-baseline", metavar="FILE", default=None,
        help="record current findings as the baseline and exit 0",
    )
    parser.add_argument(
        "--fix", action="store_true",
        help="apply mechanical fixes (TPU001 async sleep, TPU003 literal "
        "rewrites), then re-lint and report what remains",
    )
    parser.add_argument(
        "--changed", action="store_true",
        help="lint only files git reports as touched (working tree vs "
        "HEAD, plus untracked), restricted to the given paths; the "
        "interprocedural rules still see the whole project through the "
        "call-graph scope + cache, so this is the <2 s pre-commit path",
    )
    parser.add_argument(
        "--callgraph-cache", metavar="FILE", default=None,
        help="persist per-file call-graph summaries here (implied by "
        "--changed: .tpulint_cache/callgraph.json); unchanged files are "
        "loaded instead of re-summarized",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in default_rules():
            print(f"{rule.id}  {rule.name}: {rule.description}")
        return 0

    if args.explain:
        doc = explain_rule(args.explain)
        if doc is None:
            print(
                f"tpulint: unknown rule {args.explain!r} (see --list-rules)",
                file=sys.stderr,
            )
            return 2
        try:
            print(doc)
        except BrokenPipeError:
            pass
        return 0

    select = (
        {r.strip().upper() for r in args.select.split(",") if r.strip()}
        or None
    )

    from tritonclient_tpu.analysis import _callgraph

    cache = args.callgraph_cache
    lint_paths = list(args.paths)
    scope = None
    if args.changed:
        cache = cache or ".tpulint_cache/callgraph.json"
        # The whole-program substrate still covers the full lint scope —
        # a changed callee must be judged against its unchanged callers.
        scope = lint_paths
        lint_paths = _git_changed_files(lint_paths)
        if not lint_paths:
            print("tpulint: 0 findings in 0 files (no changed files)")
            return 0
    prev = dict(_callgraph._CONFIG)
    _callgraph.configure(cache_path=cache, scope=scope)
    try:
        findings, files_checked = run_analysis(lint_paths, select=select)

        if args.fix:
            from tritonclient_tpu.analysis._fix import apply_fixes

            applied = apply_fixes(findings)
            for path, count in sorted(applied.items()):
                noun = "fix" if count == 1 else "fixes"
                print(f"tpulint: applied {count} {noun} in {path}",
                      file=sys.stderr)
            findings, files_checked = run_analysis(lint_paths, select=select)
    finally:
        _callgraph.configure(**prev)

    if args.write_baseline:
        from tritonclient_tpu.analysis._baseline import write_baseline

        write_baseline(args.write_baseline, findings)
        print(
            f"tpulint: wrote baseline with {len(findings)} findings to "
            f"{args.write_baseline}",
            file=sys.stderr,
        )
        return 0

    suppressed = 0
    if args.baseline:
        from tritonclient_tpu.analysis._baseline import (
            apply_baseline,
            load_baseline,
        )

        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError, KeyError) as e:
            print(f"tpulint: cannot load baseline: {e}", file=sys.stderr)
            return 2
        findings, suppressed = apply_baseline(findings, baseline)

    render = {
        "json": render_json,
        "sarif": render_sarif,
    }.get(args.format, render_text)
    print(render(findings, files_checked))
    if suppressed and args.format == "text":
        print(
            f"tpulint: {suppressed} baselined finding(s) suppressed",
            file=sys.stderr,
        )
    return 1 if findings else 0
